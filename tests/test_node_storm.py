"""Mass node-failure resilience (ISSUE 10 tentpole, docs/NODE_FAILURE.md).

The contracts under test, across all four layers:

  * **Batched invalidation** — killing K nodes one-at-a-time vs in one
    `BATCH_NODE_UPDATE_STATUS` sweep yields BIT-identical final
    placements, and a rate-capped sweep drains a mass expiry in
    ceil(K / cap) raft entries with carry-over, never a per-node flood.
  * **Taint-masked device state** — node status/eligibility flips ride
    the delta journal as eligibility-mask SETs (no epoch bump): the
    tensor cache and its per-shard device twins stay RESIDENT through a
    storm (`nomad.solver.state_cache.reseeds` unchanged, twins still
    node-sharded on the virtual 8-device mesh), and the journaled mask
    keeps bit-parity with the `node.ready()` host oracle through
    arbitrary churn.
  * **Storm containment** — replacement evals dedupe to one per
    (namespace, job) per batch, redundant node-update evals coalesce in
    the broker (and the leader cancels the superseded records), lost-
    alloc replacement work is shed/cap/deadline-exempt, and a
    down/up-cycling node is flap-damped with exponential re-admit.
  * **Determinism** — every storm here is driven through ManualClock +
    seeded RNG (DET001 now scopes `server/heartbeat.py`); the chaos
    shapes (`heartbeat.sweep` faults, a 3-server virtual-transport
    cluster) replay bit-identically.
"""
import math
import random
import time
import types

import numpy as np
import pytest

from nomad_tpu import faults, mock
from nomad_tpu.chrono import ManualClock
from nomad_tpu.metrics import metrics
from nomad_tpu.scheduler import new_scheduler
from nomad_tpu.server import Server
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.server.fsm import (
    BATCH_NODE_UPDATE_STATUS, EVAL_UPDATE, NODE_UPDATE_ELIGIBILITY,
    NODE_UPDATE_STATUS, NomadFSM, RaftLog,
)
from nomad_tpu.server.heartbeat import (
    INVALIDATE_RETRY_BACKOFF_S, FlapDamper, create_node_evals,
    create_node_evals_batch,
)
from nomad_tpu.server.plan_apply import Planner
from nomad_tpu.solver import backend, buckets, sharding, state_cache
from nomad_tpu.solver.state_cache import cache
from nomad_tpu.structs import (
    Evaluation, SchedulerConfiguration, SCHED_ALG_TPU,
    ALLOC_CLIENT_RUNNING, JOB_TYPE_SYSTEM,
    NODE_SCHED_ELIGIBLE, NODE_SCHED_INELIGIBLE,
    NODE_STATUS_DOWN, NODE_STATUS_READY, TRIGGER_NODE_UPDATE,
)


@pytest.fixture(autouse=True)
def _fresh():
    state_cache.reset()
    faults.clear()
    yield
    state_cache.reset()
    faults.clear()


def wait_until(cond, timeout=10.0, step=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(step)
    return cond()


# ------------------------------------------------------------------ helpers

def _mk_job(j: int, count: int, cpu: int = 250, mem: int = 128,
            priority: int = 50):
    job = mock.batch_job()
    job.id = job.name = f"storm-job-{j}"
    job.priority = priority
    tg = job.task_groups[0]
    tg.count = count
    tg.networks = []
    tg.tasks[0].resources.networks = []
    tg.tasks[0].resources.cpu = cpu
    tg.tasks[0].resources.memory_mb = mem
    return job


class _Shim:
    """Worker-planner glue over the real serial applier (inline apply:
    single-threaded, deterministic)."""

    def __init__(self, planner, state):
        self.planner = planner
        self.state = state

    def submit_plan(self, plan):
        return self.planner.apply_plan(plan)

    def update_eval(self, ev):
        self.state.upsert_evals(self.state.latest_index() + 1, [ev])

    def create_eval(self, ev):
        self.state.upsert_evals(self.state.latest_index() + 1, [ev])

    def refresh_snapshot(self, old):
        return self.state.snapshot()


def _seed_cluster(n_nodes: int = 24, n_jobs: int = 4, count: int = 6):
    """A deterministic loaded cluster: pinned node ids, `n_jobs` batch
    jobs placed through the REAL scheduler/planner path with pinned
    eval ids (fixed shuffles/jitter)."""
    random.seed(31)
    fsm = NomadFSM()
    s = fsm.state
    s.set_scheduler_config(
        1, SchedulerConfiguration(scheduler_algorithm=SCHED_ALG_TPU))
    idx = 2
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.id = f"node-{i:04d}"
        n.name = n.id
        s.upsert_node(idx, n)
        nodes.append(n)
        idx += 1
    planner = Planner(RaftLog(fsm), s)
    shim = _Shim(planner, s)
    for j in range(n_jobs):
        job = _mk_job(j, count)
        s.upsert_job(s.latest_index() + 1, job)
        ev = Evaluation(id=f"seed-ev-{j}", namespace="default",
                        job_id=job.id, type="batch", priority=50)
        s.upsert_evals(s.latest_index() + 1, [ev])
        new_scheduler("batch", s.snapshot(), shim).process(ev)
    return fsm, nodes


def _fingerprint(s):
    """(live placements, full alloc dispositions, usage bytes, mask
    bytes) — the storm differential witness, id-independent because
    replacement alloc ids are fresh uuids in each leg."""
    live = tuple(sorted(
        (a.job_id, a.name, a.node_id) for a in s.iter_allocs()
        if a.desired_status == "run" and not a.terminal_status()))
    disp = tuple(sorted(
        (a.job_id, a.name, a.node_id, a.desired_status, a.client_status)
        for a in s.iter_allocs()))
    view = s.usage.view()
    elig = view.elig.tobytes() if view.elig is not None else b""
    return live, disp, view.cap.tobytes(), view.used.tobytes(), elig


def _storm_leg(fsm, doomed_ids: list[str], batched: bool) -> int:
    """Down `doomed_ids` (one entry vs one-per-node), enqueue the
    replacement evals with ids pinned by (job, occurrence) — so the two
    legs' schedulers draw identical per-eval rng streams for the FIRST
    (effective) eval of each job — and process every eval through the
    real planner. Returns the number of invalidation raft entries."""
    random.seed(99)
    s = fsm.state
    raft = RaftLog(fsm)
    planner = Planner(raft, s)
    shim = _Shim(planner, s)
    if batched:
        raft.apply(BATCH_NODE_UPDATE_STATUS, {
            "node_ids": list(doomed_ids), "status": NODE_STATUS_DOWN,
            "updated_at": 1000.0})
        entries = 1
        evals = create_node_evals_batch(s, list(doomed_ids))
    else:
        entries = 0
        for nid in doomed_ids:
            raft.apply(NODE_UPDATE_STATUS, {
                "node_id": nid, "status": NODE_STATUS_DOWN,
                "updated_at": 1000.0})
            entries += 1
        evals = []
        for nid in doomed_ids:
            evals.extend(create_node_evals(s, nid))
    occ: dict = {}
    for ev in evals:
        k = (ev.namespace, ev.job_id)
        ev.id = f"storm-ev-{ev.job_id}-{occ.get(k, 0)}"
        occ[k] = occ.get(k, 0) + 1
    raft.apply(EVAL_UPDATE, {"evals": evals})
    for ev in evals:
        new_scheduler(ev.type, s.snapshot(), shim).process(ev)
    return entries


# ------------------------------------------------- the storm differential

def test_storm_differential_serial_vs_batched_bit_identical():
    """Acceptance: killing K nodes one-at-a-time (K raft entries, one
    eval set per node) vs in ONE batched sweep (1 entry, deduped evals)
    must land bit-identical final placements, dispositions, usage
    matrices, and eligibility masks."""
    fsm, nodes = _seed_cluster()
    s = fsm.state
    loaded = [n.id for n in nodes if s.allocs_by_node(n.id)]
    assert len(loaded) >= 2, "seed must spread allocs over several nodes"
    doomed = sorted(set(loaded[:4]) | {nodes[0].id, nodes[1].id})
    blob = fsm.snapshot_bytes()
    twin = NomadFSM()
    twin.restore_bytes(blob)

    serial_entries = _storm_leg(fsm, doomed, batched=False)
    batch_entries = _storm_leg(twin, doomed, batched=True)
    assert serial_entries == len(doomed)
    assert batch_entries == 1

    fp_serial = _fingerprint(fsm.state)
    fp_batch = _fingerprint(twin.state)
    assert fp_serial == fp_batch, "storm legs diverged"

    # the storm actually moved work: every doomed node's live allocs
    # were replaced onto survivors
    live, _, _, _, _ = fp_batch
    assert live, "replacements never landed"
    assert not any(node_id in doomed for _, _, node_id in live), \
        "a replacement landed on a downed node"


def test_batched_eval_set_is_strictly_smaller():
    """The flood arithmetic: the per-node path emits one eval per
    (job, node) pair; the batch dedupes to one per job."""
    fsm, nodes = _seed_cluster(n_nodes=12, n_jobs=3, count=8)
    s = fsm.state
    doomed = [n.id for n in nodes if s.allocs_by_node(n.id)]
    per_node = []
    for nid in doomed:
        per_node.extend(create_node_evals(s, nid))
    batched = create_node_evals_batch(s, doomed)
    batched_jobs = {(e.namespace, e.job_id) for e in batched}
    assert len(batched) == len(batched_jobs), "batch output has dupes"
    assert {(e.namespace, e.job_id) for e in per_node} == batched_jobs
    assert len(per_node) > len(batched), \
        "the batch path saved no eval flood — dedupe is dead code"


# ------------------------------------------- create_node_evals batch scale

def test_create_node_evals_batch_dedupe_priority_and_system_once():
    s = NomadFSM().state
    s.set_scheduler_config(1, SchedulerConfiguration())
    nodes = []
    idx = 2
    for i in range(4):
        n = mock.node()
        n.id = f"b-node-{i}"
        s.upsert_node(idx, n)
        nodes.append(n)
        idx += 1
    job_a = _mk_job("a", 2, priority=70)
    job_b = _mk_job("b", 2, priority=40)
    job_c = _mk_job("c", 1)                 # allocs only on the survivor
    sysjob = mock.system_job()
    sysjob.priority = 60
    for job in (job_a, job_b, job_c, sysjob):
        s.upsert_job(idx, job)
        idx += 1
    # job A spans doomed nodes 0+1, job B spans 1+2, job C on node 3
    placement = [(job_a, 0), (job_a, 1), (job_b, 1), (job_b, 2),
                 (job_c, 3)]
    for k, (job, ni) in enumerate(placement):
        a = mock.alloc_for(job, nodes[ni])
        a.id = f"b-alloc-{k}"
        s.upsert_allocs(idx, [a])
        idx += 1

    doomed = [nodes[0].id, nodes[1].id, nodes[2].id]
    evals = create_node_evals_batch(s, doomed)
    by_job = {e.job_id: e for e in evals}
    # one eval per affected job + ONE per system job, none for job C
    assert set(by_job) == {job_a.id, job_b.id, sysjob.id}
    assert len(evals) == 3
    # priority/type inherit from the job
    assert by_job[job_a.id].priority == 70
    assert by_job[job_b.id].priority == 40
    assert by_job[sysjob.id].priority == 60
    assert by_job[sysjob.id].type == JOB_TYPE_SYSTEM
    assert all(e.triggered_by == TRIGGER_NODE_UPDATE for e in evals)
    assert all(e.status == "pending" for e in evals)
    # the eval anchors to the first doomed node carrying the job's alloc
    assert by_job[job_a.id].node_id == nodes[0].id
    assert by_job[job_b.id].node_id == nodes[1].id
    # serial comparison: per-node calls emit the (job, node) cross
    # product — 2 for A, 2 for B, 3 for the system job
    per_node = []
    for nid in doomed:
        per_node.extend(create_node_evals(s, nid))
    assert len(per_node) == 7


def test_disconnect_window_allocs_ride_instead_of_immediate_loss():
    """max_client_disconnect (satellite): a RUNNING alloc on a downed
    node inside its disconnect window is NOT immediately stopped/lost —
    the node-update eval (which must still fire: it drives the unknown
    transition) marks it `unknown` and parks a timeout-later eval; only
    window expiry makes it lost."""
    fsm = NomadFSM()
    s = fsm.state
    s.set_scheduler_config(
        1, SchedulerConfiguration(scheduler_algorithm=SCHED_ALG_TPU))
    idx = 2
    nodes = []
    for i in range(4):
        n = mock.node()
        n.id = f"dc-node-{i}"
        s.upsert_node(idx, n)
        nodes.append(n)
        idx += 1
    job = _mk_job("dc", 1)
    job.type = "service"
    job.task_groups[0].max_client_disconnect_sec = 300.0
    s.upsert_job(idx, job)
    idx += 1
    a = mock.alloc_for(job, nodes[0])
    a.id = "dc-alloc-0"
    a.name = f"{job.id}.{job.task_groups[0].name}[0]"
    a.task_group = job.task_groups[0].name
    a.client_status = ALLOC_CLIENT_RUNNING
    s.upsert_allocs(idx, [a])
    idx += 1

    raft = RaftLog(fsm)
    raft.apply(BATCH_NODE_UPDATE_STATUS, {
        "node_ids": [nodes[0].id], "status": NODE_STATUS_DOWN,
        "updated_at": time.time()})
    evals = create_node_evals_batch(s, [nodes[0].id])
    assert [e.job_id for e in evals] == [job.id], \
        "the disconnect-window job still needs its node-update eval"
    evals[0].id = "dc-ev-0"
    raft.apply(EVAL_UPDATE, {"evals": evals})
    planner = Planner(raft, s)
    new_scheduler("service", s.snapshot(), _Shim(planner, s)) \
        .process(evals[0])

    cur = s.alloc_by_id(a.id)
    assert cur.client_status == "unknown", \
        "a disconnect-window alloc must ride as unknown, not be lost"
    assert cur.desired_status == "run", \
        "a disconnect-window alloc was stopped inside its window"
    assert cur.disconnected_at > 0
    # the window-expiry eval is parked for later
    later = [e for e in s.iter_evals()
             if e.job_id == job.id and e.wait_until_unix]
    assert later, "no timeout-later eval was parked for window expiry"

    # window expiry: backdate the disconnect and reconcile again
    cur = cur.copy()
    cur.disconnected_at = time.time() - 400.0
    s.upsert_allocs(s.latest_index() + 1, [cur])
    ev2 = Evaluation(id="dc-ev-1", namespace="default", job_id=job.id,
                     type="service", priority=50,
                     triggered_by=TRIGGER_NODE_UPDATE)
    raft.apply(EVAL_UPDATE, {"evals": [ev2]})
    new_scheduler("service", s.snapshot(), _Shim(planner, s)).process(ev2)
    cur = s.alloc_by_id(a.id)
    assert cur.client_status == "lost" or cur.desired_status == "stop", \
        "an expired disconnect window must finally lose the alloc"
    live = [al for al in s.allocs_by_job("default", job.id)
            if al.desired_status == "run" and not al.terminal_status()]
    assert any(al.node_id != nodes[0].id for al in live), \
        "no replacement placed after window expiry"


# --------------------------------------------- rate-capped, paced sweeps

def _manual_server(**cfg_kw):
    clock = ManualClock()
    s = Server(num_workers=0, gc_interval=9999)
    s.heartbeats.clock = clock
    s.heartbeats.ttl_spread = 0.0        # deterministic deadlines
    s.flap_damper.clock = clock
    s.state.set_scheduler_config(
        s.state.latest_index() + 1,
        SchedulerConfiguration(scheduler_algorithm=SCHED_ALG_TPU,
                               **cfg_kw))
    return s, clock


def _count_applies(s, counts: dict):
    orig = s.raft.apply

    def counting(msg_type, payload, **kw):
        counts[msg_type] = counts.get(msg_type, 0) + 1
        counts.setdefault("_sizes", []).append(
            len(payload.get("node_ids", ())) if msg_type ==
            BATCH_NODE_UPDATE_STATUS else 0)
        return orig(msg_type, payload, **kw)

    s.raft.apply = counting


def test_rate_capped_sweep_paces_a_mass_expiry():
    """Acceptance: K expired nodes drain in ceil(K / cap) batch entries
    with carry-over — never one raft entry per node, never one
    unbounded megaflood."""
    cap = 4
    s, clock = _manual_server(heartbeat_invalidate_rate_cap=cap,
                              flap_damping_threshold=0)
    try:
        doomed, survivors = [], []
        for i in range(13):
            n = mock.node()
            s.node_register(n)
            (doomed if i < 10 else survivors).append(n.id)
        clock.advance(s.heartbeats.min_ttl + 1.0)    # everyone expired
        for nid in survivors:
            s.node_heartbeat(nid)                    # back to now + ttl
        counts: dict = {}
        _count_applies(s, counts)
        carry0 = metrics.counter("nomad.heartbeat.sweep_carryover")
        sweeps = 0
        while any(s.state.node_by_id(nid).status != NODE_STATUS_DOWN
                  for nid in doomed):
            s.heartbeats._sweep(clock.time())
            sweeps += 1
            assert sweeps <= 10, "sweeps are not making progress"
        expect = math.ceil(len(doomed) / cap)
        assert sweeps == expect
        assert counts.get(BATCH_NODE_UPDATE_STATUS, 0) == expect, \
            "invalidation cost more raft entries than ceil(K/cap)"
        assert counts.get(NODE_UPDATE_STATUS, 0) == 0, \
            "a per-node status entry leaked through the batch path"
        sizes = [z for z in counts["_sizes"] if z]
        assert max(sizes) <= cap
        assert sum(sizes) == len(doomed)
        assert metrics.counter("nomad.heartbeat.sweep_carryover") > carry0
        for nid in survivors:
            assert s.state.node_by_id(nid).status == NODE_STATUS_READY
    finally:
        s.shutdown()


@pytest.mark.chaos
def test_sweep_fault_rearms_whole_batch_and_retries():
    """`heartbeat.sweep` fault site: a failed batch invalidate re-arms
    EVERY member with the short backoff (nodes stay tracked), and a
    heartbeat landing before the retry wins the per-node CAS."""
    s, clock = _manual_server(flap_damping_threshold=0)
    try:
        nodes = [mock.node() for _ in range(5)]
        for n in nodes:
            s.node_register(n)
        clock.advance(s.heartbeats.min_ttl + 1.0)
        faults.install({"heartbeat.sweep": {"mode": "raise", "times": 1}})
        s.heartbeats._sweep(clock.time())
        assert all(s.state.node_by_id(n.id).status == NODE_STATUS_READY
                   for n in nodes), "a faulted sweep must commit nothing"
        with s.heartbeats._lock:
            deadlines = dict(s.heartbeats._deadlines)
        retry_at = clock.time() + INVALIDATE_RETRY_BACKOFF_S
        assert all(deadlines[n.id] == retry_at for n in nodes), \
            "a failed batch must re-arm every member"
        # one node heartbeats before the retry: the CAS saves it
        s.node_heartbeat(nodes[4].id)
        clock.advance(INVALIDATE_RETRY_BACKOFF_S + 0.1)
        s.heartbeats._sweep(clock.time())
        for n in nodes[:4]:
            assert s.state.node_by_id(n.id).status == NODE_STATUS_DOWN
        assert s.state.node_by_id(nodes[4].id).status == NODE_STATUS_READY
    finally:
        s.shutdown()


def test_invalidate_batch_carries_evals_in_the_same_raft_entry():
    """Atomicity by construction: the down-batch's replacement evals
    ride the SAME raft entry as the status flips (the JOB_REGISTER
    shape) — a crash or leadership loss between two separate entries
    could otherwise commit the flips and strand the down nodes
    eval-less forever (the next sweep filters them as terminal)."""
    s, clock = _manual_server(flap_damping_threshold=0)
    try:
        nodes = [mock.node() for _ in range(3)]
        for n in nodes:
            s.node_register(n)
        sysjob = mock.system_job()
        s.state.upsert_job(s.state.latest_index() + 1, sysjob)
        clock.advance(s.heartbeats.min_ttl + 1.0)
        counts: dict = {}
        _count_applies(s, counts)
        s.heartbeats._sweep(clock.time())
        assert all(s.state.node_by_id(n.id).status == NODE_STATUS_DOWN
                   for n in nodes)
        got = [e for e in s.state.iter_evals()
               if e.triggered_by == TRIGGER_NODE_UPDATE]
        assert [e.job_id for e in got] == [sysjob.id]
        # ONE entry carried both; no separate EVAL_UPDATE was applied
        assert counts.get(BATCH_NODE_UPDATE_STATUS, 0) == 1
        assert counts.get(EVAL_UPDATE, 0) == 0
        # and a FAILED apply commits neither flips nor evals
        n4 = mock.node()
        s.node_register(n4)
        clock.advance(s.heartbeats.min_ttl + 1.0)
        faults.install({"heartbeat.sweep": {"mode": "raise", "times": 1}})
        s.heartbeats._sweep(clock.time())
        assert s.state.node_by_id(n4.id).status == NODE_STATUS_READY
        assert len([e for e in s.state.iter_evals()
                    if e.triggered_by == TRIGGER_NODE_UPDATE]) == len(got)
    finally:
        s.shutdown()


# ------------------------------------------- taint mask vs epoch contract

def _store_with_nodes(n_nodes: int):
    fsm = NomadFSM()
    s = fsm.state
    s.set_scheduler_config(
        1, SchedulerConfiguration(scheduler_algorithm=SCHED_ALG_TPU))
    nodes = []
    idx = 2
    for i in range(n_nodes):
        n = mock.node()
        n.id = f"t-node-{i:04d}"
        s.upsert_node(idx, n)
        nodes.append(n)
        idx += 1
    return fsm, s, nodes


def test_status_flip_journals_taint_without_epoch_bump():
    fsm, s, nodes = _store_with_nodes(8)
    u = s.usage
    e0, v0 = u.epoch, u.version
    raft = RaftLog(fsm)
    raft.apply(BATCH_NODE_UPDATE_STATUS, {
        "node_ids": [nodes[0].id, nodes[3].id],
        "status": NODE_STATUS_DOWN, "updated_at": 1.0})
    assert u.epoch == e0, "a status flip must NOT bump the epoch"
    assert u.version == v0 + 2
    _, entries = u.delta_log.tail
    taints = [e for e in entries if e[2] is None]
    assert len(taints) == 2 and all(e[4] == 0.0 for e in taints)
    assert u.elig[u.row[nodes[0].id]] == 0.0
    assert u.elig[u.row[nodes[1].id]] == 1.0
    # drain + eligibility flips ride the journal too
    s.update_node_eligibility(s.latest_index() + 1, nodes[1].id,
                              NODE_SCHED_INELIGIBLE)
    assert u.epoch == e0
    assert u.elig[u.row[nodes[1].id]] == 0.0
    # a no-op flip (down node marked down again) adds no journal entry
    v_now = u.version
    s.update_node_status(s.latest_index() + 1, nodes[0].id,
                         NODE_STATUS_DOWN, 2.0)
    assert u.version == v_now
    # epoch stays reserved for true node-set mutation
    extra = mock.node()
    s.upsert_node(s.latest_index() + 1, extra)
    assert u.epoch == e0 + 1


def test_storm_advances_tensor_cache_without_reseed():
    """Acceptance: `nomad.solver.state_cache.reseeds` is UNCHANGED by a
    mass status flip — the taint rides the delta journal into the
    resident cache instead of evicting it."""
    fsm, s, nodes = _store_with_nodes(16)
    rows = np.arange(16, dtype=np.int64)
    assert state_cache.gather(s.snapshot().usage, rows) is not None
    reseeds0 = metrics.counter("nomad.solver.state_cache.reseeds")
    misses0 = metrics.counter("nomad.solver.state_cache.misses")
    doomed = [n.id for n in nodes[:6]]
    RaftLog(fsm).apply(BATCH_NODE_UPDATE_STATUS, {
        "node_ids": doomed, "status": NODE_STATUS_DOWN, "updated_at": 1.0})
    view = s.snapshot().usage
    got = state_cache.gather(view, rows)
    assert got is not None
    assert metrics.counter("nomad.solver.state_cache.reseeds") == reseeds0, \
        "the storm reseeded the cache — taint must ride the journal"
    assert metrics.counter("nomad.solver.state_cache.misses") == misses0
    assert got.cap.tobytes() == view.cap[rows].tobytes()
    assert got.used.tobytes() == view.used[rows].tobytes()
    tc = cache()
    assert tc.elig is not None
    assert int((tc.elig[:16] < 0.5).sum()) == len(doomed)
    assert tc.stats()["tainted_rows"] == len(doomed)


def test_taint_mask_bit_parity_with_ready_oracle():
    """The journaled mask vs the host oracle: through a churn of status
    flips, drains, eligibility writes, and re-admissions, the cache's
    advanced elig column equals `node.ready()` per node at every step."""
    fsm, s, nodes = _store_with_nodes(12)
    raft = RaftLog(fsm)
    rows = np.arange(12, dtype=np.int64)
    state_cache.gather(s.snapshot().usage, rows)
    rng = random.Random(5)
    for step in range(40):
        n = nodes[rng.randrange(len(nodes))]
        op = rng.randrange(4)
        idx = s.latest_index() + 1
        if op == 0:
            raft.apply(BATCH_NODE_UPDATE_STATUS, {
                "node_ids": [n.id], "status": NODE_STATUS_DOWN,
                "updated_at": float(step)})
        elif op == 1:
            s.update_node_status(idx, n.id, NODE_STATUS_READY, float(step))
        elif op == 2:
            s.update_node_eligibility(idx, n.id, NODE_SCHED_INELIGIBLE)
        else:
            s.update_node_eligibility(idx, n.id, NODE_SCHED_ELIGIBLE)
        view = s.snapshot().usage
        assert state_cache.gather(view, rows) is not None
        oracle = np.array([s.node_by_id(m.id).ready() for m in nodes],
                          bool)
        got = cache().elig[:12] > 0.5
        assert np.array_equal(got, oracle), \
            f"mask diverged from ready() oracle at step {step}"
        assert np.array_equal(view.elig > 0.5, oracle)


def test_sharded_twins_stay_partitioned_through_a_storm(monkeypatch):
    """Acceptance: on the virtual 8-device mesh, a mass status flip
    leaves the per-shard device twins RESIDENT and node-sharded — the
    taint advance never pays a reseed or collapses the partitioning."""
    monkeypatch.setattr(backend, "SHARD_MIN_NODES", 8)
    fsm, s, nodes = _store_with_nodes(24)
    n = len(nodes)
    bucket = buckets.node_bucket(n)
    rows = np.arange(n, dtype=np.int64)
    got = state_cache.gather(s.snapshot().usage, rows, bucket=bucket)
    assert got is not None and got.cap_dev is not None
    assert sharding.is_node_sharded(cache()._used_dev)
    reseeds0 = metrics.counter("nomad.solver.state_cache.reseeds")
    misses0 = metrics.counter("nomad.solver.state_cache.misses")
    RaftLog(fsm).apply(BATCH_NODE_UPDATE_STATUS, {
        "node_ids": [m.id for m in nodes[:8]],
        "status": NODE_STATUS_DOWN, "updated_at": 1.0})
    view = s.snapshot().usage
    got2 = state_cache.gather(view, rows, bucket=bucket)
    assert got2 is not None and got2.used_dev is not None
    assert sharding.is_node_sharded(got2.used_dev)
    assert sharding.is_node_sharded(cache()._used_dev), \
        "the storm collapsed the twin's partitioning"
    assert metrics.counter("nomad.solver.state_cache.reseeds") == reseeds0
    assert metrics.counter("nomad.solver.state_cache.misses") == misses0
    assert int((cache().elig[:n] < 0.5).sum()) == 8


# --------------------------------------------------- broker storm traffic

def _broker(cap=0, ttl=0.0):
    b = EvalBroker()
    b.depth_cap = cap
    b.eval_deadline_s = ttl
    b.set_enabled(True)
    return b


def _node_ev(job="j1", eid=None, priority=50):
    return Evaluation(id=eid or f"ne-{job}-{random.random()}",
                      namespace="default", job_id=job, type="batch",
                      priority=priority, triggered_by=TRIGGER_NODE_UPDATE)


def test_node_update_evals_coalesce_while_queued():
    b = _broker()
    first = _node_ev("j1", "ne-first")
    b.enqueue(first)
    base = metrics.counter("nomad.broker.node_update_coalesced")
    dup = _node_ev("j1", "ne-dup")
    b.enqueue(dup)
    assert b.depth() == 1, "the redundant node-update eval was queued"
    assert metrics.counter("nomad.broker.node_update_coalesced") == base + 1
    assert b.take_coalesced() == ["ne-dup"]
    assert b.take_coalesced() == []
    # a different job does not coalesce
    b.enqueue(_node_ev("j2", "ne-other"))
    assert b.depth() == 2


def test_outstanding_node_update_eval_does_not_coalesce():
    """A dequeued (mid-solve) eval's snapshot may predate the new
    failure: the newcomer must park via the ordinary one-per-job dedupe
    (pending), NOT be superseded."""
    b = _broker()
    first = _node_ev("j1", "ne-out-1")
    b.enqueue(first)
    got, token = b.dequeue(["batch"], timeout=1)
    assert got.id == first.id
    second = _node_ev("j1", "ne-out-2")
    b.enqueue(second)
    assert b.take_coalesced() == []
    assert b.stats["total_pending"] == 1
    # but a THIRD arrival now coalesces against the pending second
    third = _node_ev("j1", "ne-out-3")
    b.enqueue(third)
    assert b.take_coalesced() == ["ne-out-3"]
    b.ack(first.id, token)


def test_node_update_evals_are_shed_and_deadline_exempt():
    """Replacement-of-lost-work traffic bypasses the depth cap, is never
    a shed victim, and takes no enqueue TTL — it must outlive any user
    churn burst instead of dead-lettering behind it."""
    b = _broker(cap=3, ttl=2.0)
    user = [Evaluation(namespace="default", job_id=f"u{i}", type="batch",
                       priority=90) for i in range(3)]
    for ev in user:
        b.enqueue(ev)
    assert b.depth() == 3
    nu = _node_ev("lost-job", "ne-exempt", priority=10)
    b.enqueue(nu)
    assert b.depth() == 4, "node-update eval must bypass the cap"
    assert b.stats["total_shed"] == 0
    assert nu.id in b._evals
    # over-cap user arrivals shed users, never the node-update eval
    b.enqueue(Evaluation(namespace="default", job_id="u-late",
                         type="batch", priority=95))
    assert b.stats["total_shed"] == 1
    assert nu.id in b._evals and nu.id not in \
        {e.id for e in b.failed_evals()}
    # no deadline was stamped on the node-update eval
    queued = b._evals[nu.id]
    assert not queued.deadline_unix, \
        "lost-alloc replacement work must not expire behind a burst"


def test_dead_lettered_node_update_eval_does_not_coalesce():
    """A dead-lettered node-update eval never runs a scheduler pass
    (the reaper terminates it into a backed-off follow-up), so it must
    NOT act as the 'queued' covering eval — the newcomer parks via the
    ordinary one-per-job dedupe instead of being canceled."""
    b = _broker()
    b.delivery_limit = 1
    first = _node_ev("j-dead", "ne-dead-1")
    b.enqueue(first)
    got, token = b.dequeue(["batch"], timeout=1)
    assert got.id == first.id
    b.nack(first.id, token)          # count >= limit -> dead-letter
    assert any(e.id == first.id for e in b.failed_evals())
    second = _node_ev("j-dead", "ne-dead-2")
    b.enqueue(second)
    assert b.take_coalesced() == [], \
        "a dead-lettered eval coalesced away its replacement coverage"
    assert b.stats["total_pending"] == 1


def test_cancel_coalesced_restashes_ids_on_apply_failure():
    """A transient raft failure while canceling superseded evals must
    re-stash the drained ids — losing them leaks the coalesced evals
    as permanently-pending state records (eval GC only reaps
    terminal)."""
    s = Server(num_workers=0, gc_interval=9999)
    try:
        s.eval_broker.set_enabled(True)
        first = _node_ev("rs-job", "rs-ev-1")
        dup = _node_ev("rs-job", "rs-ev-2")
        s.raft.apply(EVAL_UPDATE, {"evals": [first, dup]})
        s.eval_broker.enqueue(first)
        s.eval_broker.enqueue(dup)          # superseded, parked
        orig = s.raft.apply
        fail = {"armed": True}

        def flaky(msg_type, payload, **kw):
            if fail["armed"] and msg_type == EVAL_UPDATE and \
                    any(e.id == "rs-ev-2" for e in payload["evals"]):
                fail["armed"] = False
                raise RuntimeError("transient raft apply failure")
            return orig(msg_type, payload, **kw)

        s.raft.apply = flaky
        with pytest.raises(RuntimeError):
            s._cancel_coalesced_evals()
        assert s.state.eval_by_id("rs-ev-2").status == "pending"
        s._cancel_coalesced_evals()          # next tick retries
        assert s.state.eval_by_id("rs-ev-2").status == "canceled"
    finally:
        s.shutdown()


def test_flap_damper_follows_heartbeat_clock_dynamically():
    """Swapping heartbeats.clock after construction must move the
    damper too — the two clocks diverging makes hold/no-hold window
    math nondeterministic (wall time mixed with manual time)."""
    s = Server(num_workers=0, gc_interval=9999)
    try:
        clock = ManualClock()
        s.heartbeats.clock = clock
        assert s.flap_damper.clock is clock
        own = ManualClock()
        s.flap_damper.clock = own            # explicit injection wins
        assert s.flap_damper.clock is own
    finally:
        s.shutdown()


def test_leader_cancels_coalesced_eval_records():
    s = Server(num_workers=0, gc_interval=9999)
    try:
        s.eval_broker.set_enabled(True)
        first = _node_ev("cj", "co-ev-1")
        dup = _node_ev("cj", "co-ev-2")
        s.raft.apply(EVAL_UPDATE, {"evals": [first, dup]})
        s.eval_broker.enqueue(first)
        s.eval_broker.enqueue(dup)          # superseded, parked
        s._cancel_coalesced_evals()
        cur = s.state.eval_by_id("co-ev-2")
        assert cur.status == "canceled"
        assert "superseded" in cur.status_description
        assert s.state.eval_by_id("co-ev-1").status == "pending"
        # idempotent on an empty park list
        s._cancel_coalesced_evals()
    finally:
        s.shutdown()


# -------------------------------------------------------- flap damping

class _FakeCfgServer:
    def __init__(self, **kw):
        cfg = SchedulerConfiguration(
            flap_damping_threshold=kw.get("threshold", 3),
            flap_damping_window_s=kw.get("window", 100.0),
            flap_damping_backoff_s=kw.get("backoff", 30.0),
            flap_damping_backoff_max_s=kw.get("backoff_max", 120.0))
        self.state = types.SimpleNamespace(
            get_scheduler_config=lambda: cfg)


def test_flap_damper_threshold_and_exponential_backoff():
    clock = ManualClock()
    d = FlapDamper(_FakeCfgServer(), clock=clock)
    nid = "flappy"
    for cycle in range(2):
        d.record_down(nid)
        assert d.record_up(nid) is None
        clock.advance(1.0)
    d.record_down(nid)
    hold = d.record_up(nid)                  # third cycle trips
    assert hold == pytest.approx(clock.time() + 30.0)
    assert d.held(nid)
    assert d.due() == []
    clock.advance(30.1)
    assert d.due() == [nid]
    d.release(nid)
    assert not d.held(nid)
    # the next episode doubles, then caps
    for expect in (60.0, 120.0, 120.0):
        for _ in range(3):
            d.record_down(nid)
            hold = d.record_up(nid)
            clock.advance(0.5)
        assert hold == pytest.approx(clock.time() - 0.5 + expect)
        d.release(nid)


def test_flap_damper_quiet_spell_resets_episode_and_zero_disables():
    clock = ManualClock()
    d = FlapDamper(_FakeCfgServer(window=50.0), clock=clock)
    nid = "n"
    for _ in range(3):
        d.record_up(nid)
    d.release(nid)
    # a full quiet window ends the episode: back to the base backoff
    clock.advance(60.0)
    for _ in range(3):
        d.record_up(nid)
    with d._lock:
        deadline = d._held[nid]
    assert deadline == pytest.approx(clock.time() + 30.0)
    # threshold 0 disables entirely
    d0 = FlapDamper(_FakeCfgServer(threshold=0), clock=clock)
    for _ in range(10):
        assert d0.record_up(nid) is None
    assert not d0.held(nid)


def test_flap_damper_adopts_replicated_holds():
    clock = ManualClock()
    d = FlapDamper(_FakeCfgServer(), clock=clock)
    held = mock.node()
    held.flap_held_until = clock.time() + 40.0
    free = mock.node()
    state = types.SimpleNamespace(iter_nodes=lambda: [held, free])
    assert d.adopt(state) == 1
    assert d.held(held.id) and not d.held(free.id)
    clock.advance(41.0)
    assert d.due() == [held.id]
    d.reset()
    assert not d.held(held.id)


def test_flapping_node_held_ineligible_then_readmitted():
    """Server-level: a node cycling down/up past the threshold is held
    ineligible (flap_held_until rides raft), blocked evals are NOT
    unblocked onto it, and the leader tick re-admits it after the
    hold — restoring eligibility and clearing the hold from state."""
    s, clock = _manual_server(flap_damping_threshold=3,
                              flap_damping_window_s=300.0,
                              flap_damping_backoff_s=30.0,
                              flap_damping_backoff_max_s=900.0)
    try:
        n = mock.node()
        s.node_register(n)
        sysjob = mock.system_job()
        s.state.upsert_job(s.state.latest_index() + 1, sysjob)
        held0 = metrics.counter("nomad.heartbeat.flap_held")
        for _ in range(3):
            s.node_update_status(n.id, NODE_STATUS_DOWN)
            clock.advance(1.0)
            s.node_update_status(n.id, NODE_STATUS_READY)
            clock.advance(1.0)
        cur = s.state.node_by_id(n.id)
        assert cur.scheduling_eligibility == NODE_SCHED_INELIGIBLE
        assert cur.flap_held_until > clock.time()
        assert s.flap_damper.held(n.id)
        assert metrics.counter("nomad.heartbeat.flap_held") == held0 + 1
        assert not cur.ready()
        # a held node re-registering must not wash its hold away
        fresh = mock.node()
        fresh.id = n.id
        fresh.name = n.name
        s.node_register(fresh)
        cur = s.state.node_by_id(n.id)
        assert cur.flap_held_until > 0
        assert cur.scheduling_eligibility == NODE_SCHED_INELIGIBLE
        # too early: the tick does nothing
        s._flap_readmit_tick()
        assert s.flap_damper.held(n.id)
        # hold expiry: re-admitted, eligibility restored, hold cleared,
        # and the system-job evals the suppressed READY path skipped
        # are finally emitted (the node must get its node-local system
        # allocs back)
        sys_evals0 = len([e for e in s.state.iter_evals()
                          if e.job_id == sysjob.id])
        clock.advance(31.0)
        s._flap_readmit_tick()
        cur = s.state.node_by_id(n.id)
        assert cur.scheduling_eligibility == NODE_SCHED_ELIGIBLE
        assert cur.flap_held_until == 0.0
        assert not s.flap_damper.held(n.id)
        assert metrics.counter("nomad.heartbeat.flap_readmitted") >= 1
        assert len([e for e in s.state.iter_evals()
                    if e.job_id == sysjob.id]) == sys_evals0 + 1
    finally:
        s.shutdown()


def test_poison_job_does_not_starve_batch_eval_construction(monkeypatch):
    """Per-job failure isolation in create_node_evals_batch: one job
    whose eval construction raises loses its eval (counted) instead of
    failing the whole batch — an exception would otherwise re-arm and
    retry the ENTIRE sweep batch forever, starving invalidation of
    every other expired node."""
    fsm, s, nodes = _store_with_nodes(2)
    idx = s.latest_index() + 1
    good = _mk_job("good", 1)
    bad = _mk_job("bad", 1)
    for job in (good, bad):
        s.upsert_job(idx, job)
        idx += 1
    for k, job in enumerate((good, bad)):
        a = mock.alloc_for(job, nodes[0])
        a.id = f"poison-alloc-{k}"
        s.upsert_allocs(idx, [a])
        idx += 1
    orig = s.job_by_id

    def poisoned(ns, jid):
        if jid == bad.id:
            raise RuntimeError("poison job")
        return orig(ns, jid)

    monkeypatch.setattr(s, "job_by_id", poisoned)
    errs0 = metrics.counter("nomad.heartbeat.node_eval_errors")
    evals = create_node_evals_batch(s, [nodes[0].id])
    assert [e.job_id for e in evals] == [good.id], \
        "the healthy job's eval must survive the poison member"
    assert metrics.counter("nomad.heartbeat.node_eval_errors") == errs0 + 1


def test_held_node_cycling_below_threshold_stays_suppressed():
    """A node inside an active flap hold that cycles down/up again
    (below the reset threshold, so record_up returns no new hold) must
    NOT take the ordinary READY path: no system-job evals, no unblock —
    it is ineligible until the readmit tick lifts the hold."""
    s, clock = _manual_server(flap_damping_threshold=3,
                              flap_damping_window_s=300.0,
                              flap_damping_backoff_s=30.0,
                              flap_damping_backoff_max_s=900.0)
    try:
        n = mock.node()
        s.node_register(n)
        sysjob = mock.system_job()
        s.state.upsert_job(s.state.latest_index() + 1, sysjob)
        for _ in range(3):
            s.node_update_status(n.id, NODE_STATUS_DOWN)
            clock.advance(1.0)
            s.node_update_status(n.id, NODE_STATUS_READY)
            clock.advance(1.0)
        assert s.flap_damper.held(n.id)
        # another down/up cycle DURING the hold: one up < threshold
        # (the DOWN edge legitimately emits its replacement evals —
        # only the READY edge must stay suppressed)
        s.node_update_status(n.id, NODE_STATUS_DOWN)
        clock.advance(1.0)
        sys_evals0 = len([e for e in s.state.iter_evals()
                          if e.job_id == sysjob.id])
        res = s.node_update_status(n.id, NODE_STATUS_READY)
        cur = s.state.node_by_id(n.id)
        assert cur.scheduling_eligibility == NODE_SCHED_INELIGIBLE
        assert cur.flap_held_until > 0
        sys_evals = [e.id for e in s.state.iter_evals()
                     if e.job_id == sysjob.id]
        assert len(sys_evals) == sys_evals0, \
            "a held node's up-edge emitted system evals through the hold"
        assert not any(eid in res["eval_ids"] for eid in sys_evals)
    finally:
        s.shutdown()


def test_operator_eligibility_write_supersedes_flap_hold():
    s, clock = _manual_server(flap_damping_threshold=2,
                              flap_damping_window_s=300.0,
                              flap_damping_backoff_s=60.0,
                              flap_damping_backoff_max_s=900.0)
    try:
        n = mock.node()
        s.node_register(n)
        for _ in range(2):
            s.node_update_status(n.id, NODE_STATUS_DOWN)
            clock.advance(1.0)
            s.node_update_status(n.id, NODE_STATUS_READY)
            clock.advance(1.0)
        assert s.flap_damper.held(n.id)
        s.node_update_eligibility(n.id, NODE_SCHED_ELIGIBLE)
        cur = s.state.node_by_id(n.id)
        assert cur.flap_held_until == 0.0
        assert cur.scheduling_eligibility == NODE_SCHED_ELIGIBLE
        assert not s.flap_damper.held(n.id)
    finally:
        s.shutdown()


# ------------------------------------------------ end-to-end storm drill

def test_mass_failure_recovers_all_replacements_no_reseed():
    """E2E through a live server: batch-down 1/3 of a loaded cluster,
    let the workers replace everything, and audit the bounded-cost
    contract — one invalidation entry, deduped evals, zero reseeds,
    zero node-update dead letters."""
    s = Server(num_workers=2, gc_interval=9999)
    s.start()
    try:
        nodes = []
        for _ in range(9):
            n = mock.node()
            s.node_register(n)
            nodes.append(n)
        jobs = []
        for j in range(3):
            job = _mk_job(f"e2e-{j}", 6)
            s.job_register(job)
            jobs.append(job)
        assert wait_until(lambda: all(
            len([a for a in s.state.allocs_by_job("default", job.id)
                 if not a.terminal_status()]) == 6 for job in jobs))
        doomed = sorted({a.node_id for job in jobs
                         for a in s.state.allocs_by_job("default", job.id)
                         })[:3]
        reseeds0 = metrics.counter("nomad.solver.state_cache.reseeds")
        batches0 = metrics.counter("nomad.heartbeat.invalidate_batches")
        dead0 = metrics.counter("nomad.broker.dead_letter")
        t0 = time.time()
        flipped = s.heartbeats._invalidate_batch(list(doomed))
        assert flipped == len(doomed)

        def recovered():
            for job in jobs:
                live = [a for a in
                        s.state.allocs_by_job("default", job.id)
                        if a.desired_status == "run"
                        and not a.terminal_status()
                        and a.node_id not in doomed]
                if len(live) < 6:
                    return False
            return True

        assert wait_until(recovered, timeout=30), \
            "replacements never fully landed on the survivors"
        recovery_s = time.time() - t0
        assert recovery_s < 30
        assert metrics.counter("nomad.heartbeat.invalidate_batches") \
            == batches0 + 1
        assert metrics.counter("nomad.solver.state_cache.reseeds") \
            == reseeds0, "the storm evicted the device state cache"
        assert metrics.counter("nomad.broker.dead_letter") == dead0, \
            "lost-alloc replacement work dead-lettered"
    finally:
        s.shutdown()


@pytest.mark.chaos
def test_storm_batch_replicates_and_holds_survive_failover():
    """Virtual 3-server cluster: the batched down-entry replicates to
    followers, and a flap hold committed by the old leader is ADOPTED
    by the new leader's damper after a failover."""
    from tests.test_raft import make_cluster, shutdown_all, \
        wait_stable_leader
    servers = make_cluster(3)
    try:
        leader = wait_stable_leader(servers)
        clock = ManualClock()
        leader.heartbeats.clock = clock
        nodes = [mock.node() for _ in range(6)]
        for n in nodes:
            leader.node_register(n)
        leader.heartbeats.initialize_heartbeat_timers(grace=0.0)
        clock.advance(leader.heartbeats.min_ttl +
                      leader.heartbeats.ttl_spread + 1.0)
        leader.heartbeats._sweep(clock.time())
        followers = [s for s in servers if s is not leader]
        assert wait_until(lambda: all(
            all(f.state.node_by_id(n.id) is not None and
                f.state.node_by_id(n.id).status == NODE_STATUS_DOWN
                for n in nodes) for f in followers), timeout=10), \
            "the batched down-entry never replicated"
        # a flap hold rides raft: the new leader adopts it at establish
        hold_until = time.time() + 3600.0
        leader.raft.apply(NODE_UPDATE_ELIGIBILITY, {
            "node_id": nodes[0].id,
            "eligibility": NODE_SCHED_INELIGIBLE,
            "flap_until": hold_until})
        leader.shutdown()
        new_leader = wait_stable_leader(followers)
        assert wait_until(lambda: new_leader.flap_damper.held(nodes[0].id),
                          timeout=10), \
            "the new leader never adopted the replicated flap hold"
    finally:
        shutdown_all(servers)
