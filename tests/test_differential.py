"""Differential host-vs-TPU scheduler tests (VERDICT r2 next #2/#3).

The host GenericStack samples candidate nodes stochastically (shuffle +
log2 limit + power-of-two-choices, ref scheduler/stack.go:71,84), so two
runs of the HOST scheduler on the same state produce different node sets.
Exact distribution equality is therefore not the parity criterion — score
dominance is: the TPU assignment, scored under the host's own scoring
model (mean of ScoreFitBinPack + JobAntiAffinity at placement time, ref
scheduler/rank.go:737 ScoreNormalizationIterator), must be at least as
good as what the host stack achieved, while placing the same number of
instances without overcommit.

A property-based fuzzer drives random clusters/jobs through both paths
and checks: all placed, feasible, non-overcommitting, score-dominant.
"""
import random

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler import new_scheduler
from nomad_tpu.structs import (
    Evaluation, SchedulerConfiguration, SCHED_ALG_TPU, allocs_fit,
)
from nomad_tpu.structs.funcs import score_fit_binpack

from test_solver import Harness


# --------------------------------------------------------------- score model

def host_model_score(state, job, tg_name: str) -> float:
    """Total host-model score of a committed assignment.

    Per placement, the host scores mean(binpack_norm, anti) with anti
    present only when the node already held allocs of this job+TG
    (rank.go:536,737). Components depend only on the target node's own
    state, so the total is order-independent across nodes and can be
    replayed per node.
    """
    tg = job.lookup_task_group(tg_name)
    desired = max(tg.count, 1)
    per_instance_cpu = sum(t.resources.cpu for t in tg.tasks)
    per_instance_mem = sum(t.resources.memory_mb for t in tg.tasks)

    by_node: dict[str, int] = {}
    for a in state.allocs_by_job(job.namespace, job.id):
        if a.task_group == tg_name and not a.terminal_status():
            by_node[a.node_id] = by_node.get(a.node_id, 0) + 1

    from nomad_tpu.structs import ComparableResources
    total = 0.0
    for node_id, k in by_node.items():
        node = state.node_by_id(node_id)
        for j in range(k):
            # fitness is scored with the candidate included (rank.go:479)
            util = ComparableResources(
                cpu_shares=(j + 1) * per_instance_cpu,
                memory_mb=(j + 1) * per_instance_mem)
            base = score_fit_binpack(node, util) / 18.0
            if j > 0:
                anti = -(j + 1.0) / desired
                total += (base + anti) / 2.0
            else:
                total += base
    return total


def run_scenario(algorithm: str, seed: int, n_nodes: int, count: int,
                 cpu: int = 500, mem: int = 256, node_seed_fn=None,
                 config_kwargs=None):
    """One seeded cluster + batch job through the full scheduler path.
    `config_kwargs` extends the SchedulerConfiguration (e.g. the
    plan-pipeline knobs)."""
    random.seed(seed)
    rng = np.random.default_rng(seed)
    h = Harness()
    h.state.set_scheduler_config(
        h.get_next_index(),
        SchedulerConfiguration(scheduler_algorithm=algorithm,
                               **(config_kwargs or {})))
    for i in range(n_nodes):
        n = mock.node()
        if node_seed_fn is not None:
            node_seed_fn(n, i, rng)
        h.state.upsert_node(h.get_next_index(), n)
    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.count = count
    tg.networks = []
    task = tg.tasks[0]
    task.resources.cpu = cpu
    task.resources.memory_mb = mem
    task.resources.networks = []
    h.state.upsert_job(h.get_next_index(), job)
    ev = Evaluation(job_id=job.id, type=job.type)
    h.process(lambda s, p: new_scheduler(job.type, s, p), ev)
    return h, job


def check_committed(h, job, expect: int) -> None:
    allocs = h.state.allocs_by_job("default", job.id)
    assert len(allocs) == expect, f"placed {len(allocs)}/{expect}"
    by_node: dict[str, list] = {}
    for a in allocs:
        by_node.setdefault(a.node_id, []).append(a)
    for node_id, node_allocs in by_node.items():
        node = h.state.node_by_id(node_id)
        fit, dim, _ = allocs_fit(node, node_allocs)
        assert fit, f"overcommit on {node.name}: {dim}"


# -------------------------------------------------------------------- tests

def _hetero(n, i, rng):
    n.node_resources.cpu.cpu_shares = int(rng.choice([4000, 8000, 16000]))
    n.node_resources.memory.memory_mb = int(rng.choice([8192, 16384, 32768]))


@pytest.mark.parametrize("seed", [1, 7, 42])
def test_score_dominance_uniform_cluster(seed):
    h_host, job_h = run_scenario("binpack", seed, n_nodes=12, count=20)
    h_tpu, job_t = run_scenario(SCHED_ALG_TPU, seed, n_nodes=12, count=20)
    check_committed(h_host, job_h, 20)
    check_committed(h_tpu, job_t, 20)
    s_host = host_model_score(h_host.state, job_h, "worker")
    s_tpu = host_model_score(h_tpu.state, job_t, "worker")
    assert s_tpu >= s_host - 1e-6, f"tpu {s_tpu:.4f} < host {s_host:.4f}"


def test_score_dominance_heterogeneous_cluster():
    """Both paths are stochastic on heterogeneous clusters (the host via
    its 2-way sampling, the TPU via the matching decorrelation jitter),
    so dominance is asserted in aggregate across seeds with a per-seed
    band — the same claim shape as the fuzzer."""
    agg_host = agg_tpu = 0.0
    for seed in (3, 11, 17, 23):
        h_host, job_h = run_scenario("binpack", seed, n_nodes=20, count=40,
                                     node_seed_fn=_hetero)
        h_tpu, job_t = run_scenario(SCHED_ALG_TPU, seed, n_nodes=20,
                                    count=40, node_seed_fn=_hetero)
        check_committed(h_host, job_h, 40)
        check_committed(h_tpu, job_t, 40)
        s_host = host_model_score(h_host.state, job_h, "worker")
        s_tpu = host_model_score(h_tpu.state, job_t, "worker")
        agg_host += s_host
        agg_tpu += s_tpu
        assert s_tpu >= s_host * 0.85 - 1e-6, \
            f"seed {seed}: tpu {s_tpu:.4f} far below host {s_host:.4f}"
    assert agg_tpu >= agg_host - 1e-6, \
        f"aggregate: tpu {agg_tpu:.4f} < host {agg_host:.4f}"


def test_fuzz_spread_jobs_host_vs_tpu():
    """Chunked-path (scan kernel) differential coverage: spread-stanza
    jobs through both schedulers — all placed, no overcommit, and the
    TPU spread imbalance across racks is no worse than the host's +1
    (the reference's even-spread boost itself only converges to within
    one instance per value)."""
    from nomad_tpu.structs import Spread

    def add_spread(job):
        job.task_groups[0].spreads = [Spread(
            attribute="${meta.rack}", weight=100)]

    rng = np.random.default_rng(7)
    for trial in range(4):
        seed = int(rng.integers(0, 2 ** 31))
        n_nodes = int(rng.integers(8, 20))
        count = int(rng.integers(4, 24))
        racks = int(rng.integers(2, 5))

        def shape(n, i, _rng, racks=racks):
            n.meta["rack"] = f"r{i % racks}"
            n.compute_class()

        def run(algorithm):
            random.seed(seed)
            h = Harness()
            h.state.set_scheduler_config(
                h.get_next_index(),
                SchedulerConfiguration(scheduler_algorithm=algorithm))
            rng2 = np.random.default_rng(seed)
            for i in range(n_nodes):
                n = mock.node()
                shape(n, i, rng2)
                h.state.upsert_node(h.get_next_index(), n)
            job = mock.batch_job()
            tg = job.task_groups[0]
            tg.count = count
            tg.networks = []
            tg.tasks[0].resources.networks = []
            tg.tasks[0].resources.cpu = 200
            tg.tasks[0].resources.memory_mb = 128
            add_spread(job)
            h.state.upsert_job(h.get_next_index(), job)
            ev = Evaluation(job_id=job.id, type=job.type)
            h.process(lambda s, p: new_scheduler(job.type, s, p), ev)
            return h, job

        def imbalance(h, job):
            per = {}
            for a in h.state.allocs_by_job("default", job.id):
                rack = h.state.node_by_id(a.node_id).meta["rack"]
                per[rack] = per.get(rack, 0) + 1
            counts = [per.get(f"r{r}", 0) for r in range(racks)]
            return max(counts) - min(counts)

        h_host, job_h = run("binpack")
        h_tpu, job_t = run(SCHED_ALG_TPU)
        check_committed(h_host, job_h, count)
        check_committed(h_tpu, job_t, count)
        assert imbalance(h_tpu, job_t) <= imbalance(h_host, job_h) + 1, \
            f"trial {trial}: tpu spread imbalance " \
            f"{imbalance(h_tpu, job_t)} vs host {imbalance(h_host, job_h)}"


def test_fuzz_host_vs_tpu_random_scenarios():
    """Property fuzz: random cluster sizes/asks; both paths must place
    everything that fits and never overcommit.

    Scoring: both schedulers are greedy heuristics — the host's sampling
    randomness can occasionally luck into a better trajectory than exact
    full-matrix greedy, so per-trial strict dominance is not a theorem.
    The parity claim is: within a 10% band on every trial, and at least
    host-equal in aggregate across the corpus (the same shape of claim as
    BASELINE's rejection-rate parity)."""
    rng = np.random.default_rng(20260729)
    agg_host = 0.0
    agg_tpu = 0.0
    for trial in range(8):
        seed = int(rng.integers(0, 2 ** 31))
        n_nodes = int(rng.integers(4, 24))
        count = int(rng.integers(2, 48))
        cpu = int(rng.choice([100, 250, 500, 1000]))
        mem = int(rng.choice([64, 128, 256, 512]))
        # keep the ask satisfiable: mock nodes are 4000 cpu / 8192 mem
        # minus 100 cpu / 256 mem node reservation (mock.py)
        total_cap = n_nodes * min(3900 // cpu, 7936 // mem)
        count = min(count, total_cap)
        h_host, job_h = run_scenario("binpack", seed, n_nodes, count,
                                     cpu=cpu, mem=mem)
        h_tpu, job_t = run_scenario(SCHED_ALG_TPU, seed, n_nodes, count,
                                    cpu=cpu, mem=mem)
        check_committed(h_host, job_h, count)
        check_committed(h_tpu, job_t, count)
        s_host = host_model_score(h_host.state, job_h, "worker")
        s_tpu = host_model_score(h_tpu.state, job_t, "worker")
        agg_host += s_host
        agg_tpu += s_tpu
        assert s_tpu >= s_host * 0.9 - 1e-6, \
            f"trial {trial} (seed {seed}, {n_nodes}n/{count}c): " \
            f"tpu {s_tpu:.4f} < 0.9 * host {s_host:.4f}"
    assert agg_tpu >= agg_host - 1e-6, \
        f"aggregate: tpu {agg_tpu:.4f} < host {agg_host:.4f}"


def test_fuzz_constraints_and_distinct_parity():
    """Feature fuzz (VERDICT r2 #3: include the chunked-path features):
    random constraint mixes — attribute equality, regexp on meta,
    distinct_hosts, distinct_property quotas — must never be violated by
    either path, and both must place the same number of instances."""
    from nomad_tpu.structs import (Constraint, OP_DISTINCT_HOSTS,
                                   OP_DISTINCT_PROPERTY, OP_REGEX)
    rng = np.random.default_rng(42424242)
    for trial in range(6):
        seed = int(rng.integers(0, 2 ** 31))
        n_nodes = int(rng.integers(6, 20))
        racks = int(rng.integers(2, 5))
        kind = ["eq", "regexp", "distinct_hosts", "distinct_prop"][trial % 4]

        def build(algorithm):
            random.seed(seed)
            h = Harness()
            h.state.set_scheduler_config(
                h.get_next_index(),
                SchedulerConfiguration(scheduler_algorithm=algorithm))
            for i in range(n_nodes):
                n = mock.node()
                n.meta["rack"] = f"r{i % racks}"
                n.attributes["flavor"] = "big" if i % 2 else "small"
                # scheduling-relevant fields changed after mock.node():
                # recompute the class hash (the real registration path,
                # server.node_register, does this server-side)
                n.compute_class()
                h.state.upsert_node(h.get_next_index(), n)
            job = mock.batch_job()
            tg = job.task_groups[0]
            tg.networks = []
            t = tg.tasks[0]
            t.resources.networks = []
            t.resources.cpu = 200
            t.resources.memory_mb = 128
            if kind == "eq":
                tg.count = min(10, n_nodes * 3)
                job.constraints = [Constraint(
                    ltarget="${attr.flavor}", rtarget="big", operand="=")]
            elif kind == "regexp":
                tg.count = min(10, n_nodes * 3)
                job.constraints = [Constraint(
                    ltarget="${meta.rack}", rtarget="^r[01]$",
                    operand=OP_REGEX)]
            elif kind == "distinct_hosts":
                tg.count = n_nodes - 1
                job.constraints = [Constraint(operand=OP_DISTINCT_HOSTS)]
            else:
                tg.count = racks * 2
                job.constraints = [Constraint(
                    ltarget="${meta.rack}", rtarget="2",
                    operand=OP_DISTINCT_PROPERTY)]
            h.state.upsert_job(h.get_next_index(), job)
            ev = Evaluation(job_id=job.id, type=job.type)
            h.process(lambda s, p: new_scheduler(job.type, s, p), ev)
            return h, job

        h_host, job_h = build("binpack")
        h_tpu, job_t = build(SCHED_ALG_TPU)
        for h, job, label in ((h_host, job_h, "host"),
                              (h_tpu, job_t, "tpu")):
            allocs = h.state.allocs_by_job("default", job.id)
            nodes = {a.node_id: h.state.node_by_id(a.node_id)
                     for a in allocs}
            if kind == "eq":
                assert all(nodes[a.node_id].attributes["flavor"] == "big"
                           for a in allocs), f"{label}: eq violated"
            elif kind == "regexp":
                assert all(nodes[a.node_id].meta["rack"] in ("r0", "r1")
                           for a in allocs), f"{label}: regexp violated"
            elif kind == "distinct_hosts":
                ids = [a.node_id for a in allocs]
                assert len(ids) == len(set(ids)), \
                    f"{label}: distinct_hosts violated"
            else:
                per = {}
                for a in allocs:
                    r = nodes[a.node_id].meta["rack"]
                    per[r] = per.get(r, 0) + 1
                assert all(v <= 2 for v in per.values()), \
                    f"{label}: distinct_property quota violated ({per})"
            # overcommit check
            by_node: dict[str, list] = {}
            for a in allocs:
                by_node.setdefault(a.node_id, []).append(a)
            for nid, na in by_node.items():
                fit, dim, _ = allocs_fit(nodes[nid], na)
                assert fit, f"{label}: overcommit {dim}"
        n_host = len(h_host.state.allocs_by_job("default", job_h.id))
        n_tpu = len(h_tpu.state.allocs_by_job("default", job_t.id))
        assert n_tpu == n_host, \
            f"trial {trial} ({kind}): tpu placed {n_tpu} vs host {n_host}"


def test_differential_disconnect_canary_churn_host_vs_tpu():
    """VERDICT r3 #3 tail: the new corpus dimensions (disconnect window,
    canary gate, drain churn) through BOTH scheduler paths — the tpu-batch
    path must produce the same COVERAGE (live counts, name slots, gate
    discipline) as the host stack at every step of an identical scripted
    sequence. Scores may differ; the reconciliation semantics must not."""
    import random as _r

    from nomad_tpu.structs import (
        AllocDeploymentStatus, DesiredTransition, DrainStrategy,
        NODE_STATUS_DOWN, NODE_STATUS_READY, TRIGGER_NODE_UPDATE,
    )

    def run(algorithm, seed):
        _r.seed(seed)
        h = Harness()
        h.state.set_scheduler_config(
            h.get_next_index(),
            SchedulerConfiguration(scheduler_algorithm=algorithm))
        nodes = []
        for i in range(8):
            n = mock.node()
            h.state.upsert_node(h.get_next_index(), n)
            nodes.append(n)
        job = mock.canary_job(canaries=1)
        job.task_groups[0].max_client_disconnect_sec = 120.0
        h.state.upsert_job(h.get_next_index(), job)
        ev = Evaluation(job_id=job.id, type=job.type)
        h.process(lambda s, p: new_scheduler(job.type, s, p), ev)

        def allocs():
            return h.state.allocs_by_job("default", job.id)

        def live():
            return [a for a in allocs() if a.desired_status == "run"]

        def mark_all_running():
            for a in allocs():
                if a.desired_status != "run" or \
                        a.client_status not in ("pending", "running"):
                    continue
                a2 = a.copy()
                a2.client_status = "running"
                a2.deployment_status = AllocDeploymentStatus(
                    healthy=True,
                    canary=bool(a.deployment_status
                                and a.deployment_status.canary))
                h.state.upsert_allocs(h.get_next_index(), [a2])

        def reeval(j):
            ev2 = Evaluation(job_id=j.id, type=j.type,
                             triggered_by=TRIGGER_NODE_UPDATE)
            h.state.upsert_evals(h.get_next_index(), [ev2])
            h.process(lambda s, p: new_scheduler(j.type, s, p), ev2)

        obs = []
        mark_all_running()
        obs.append(("placed", len(live())))

        # canary update
        v1 = job.copy()
        v1.version = 1
        v1.task_groups[0].tasks[0].config = {"command": "/bin/v1"}
        h.state.upsert_job(h.get_next_index(), v1)
        reeval(v1)
        canaries = [a for a in live()
                    if a.deployment_status and a.deployment_status.canary]
        old_live = [a for a in live() if a.job.version == 0]
        obs.append(("canaries", len(canaries)))
        obs.append(("old_live_at_gate", len(old_live)))

        # a node with old allocs disconnects (window active)
        victims = [a for a in old_live
                   if not (a.deployment_status
                           and a.deployment_status.canary)]
        victim_node = victims[0].node_id
        nd = h.state.node_by_id(victim_node).copy()
        nd.status = NODE_STATUS_DOWN
        h.state.upsert_node(h.get_next_index(), nd)
        reeval(v1)
        unknown = [a for a in allocs() if a.client_status == "unknown"]
        obs.append(("unknown", len(unknown)))
        covered = [a for a in live() if a.client_status != "unknown"
                   and not (a.deployment_status
                            and a.deployment_status.canary)]
        obs.append(("covered_during_disconnect", len(covered)))

        # reconnect inside the window
        nd2 = h.state.node_by_id(victim_node).copy()
        nd2.status = NODE_STATUS_READY
        h.state.upsert_node(h.get_next_index(), nd2)
        reeval(v1)
        obs.append(("restored", len(
            [a for a in allocs()
             if a.id in {x.id for x in unknown}
             and a.desired_status == "run"
             and a.client_status != "unknown"])))
        non_canary_names = [a.name for a in live()
                            if not (a.deployment_status
                                    and a.deployment_status.canary)
                            and a.client_status != "unknown"]
        obs.append(("no_dup_names",
                    len(non_canary_names) == len(set(non_canary_names))))

        # drain another node HOSTING A NON-CANARY OLD ALLOC (the same
        # structural role in both runs; the concrete node differs by
        # placement, which is fine — the observations below are
        # placement-independent)
        other = next(a.node_id for a in live()
                     if a.node_id != victim_node
                     and a.job.version == 0
                     and not (a.deployment_status
                              and a.deployment_status.canary))
        nd3 = h.state.node_by_id(other).copy()
        nd3.drain_strategy = DrainStrategy(deadline_sec=60)
        h.state.upsert_node(h.get_next_index(), nd3)
        for a in h.state.allocs_by_node(other):
            if a.terminal_status():
                continue
            a2 = a.copy()
            a2.desired_transition = DesiredTransition(migrate=True)
            h.state.upsert_allocs(h.get_next_index(), [a2])
        reeval(v1)
        mark_all_running()
        still_on_drained = [a for a in live() if a.node_id == other]
        obs.append(("drained_cleared", len(still_on_drained) == 0))
        non_canary_live = [a for a in live()
                           if not (a.deployment_status
                                   and a.deployment_status.canary)]
        obs.append(("non_canary_coverage", len(non_canary_live)))
        # the canary gate held throughout: no non-canary v1 placements
        leaked = [a for a in non_canary_live if a.job.version == 1]
        obs.append(("gate_held", len(leaked) == 0))
        return obs

    for seed in (5, 17):
        host = run("binpack", seed)
        tpu = run(SCHED_ALG_TPU, seed)
        assert host == tpu, f"seed {seed}:\n host={host}\n tpu ={tpu}"


def test_fuzz_concurrent_workers_alloc_rejection_parity():
    """VERDICT r4 #7: K workers plan DIFFERENT jobs from ONE stale
    snapshot (the per-core worker model, ref nomad/worker.go); plans
    land on the serial applier which re-checks against latest state
    (ref plan_apply.go:638). Node-level rejection parity alone can hide
    stacking pathologies — the r4 gap came from full-stack nodes being
    likelier rejected — so the ALLOC-weighted rate (wasted placement
    work) must also hold: tpu <= host * 1.1 across seeds."""
    import bench
    from nomad_tpu.server.fsm import RaftLog
    from nomad_tpu.server.plan_apply import Planner

    from nomad_tpu.solver import microbatch

    # PR-7 noted this test as a load flake: node ids and eval ids came
    # from urandom, so every run sampled a DIFFERENT shuffle/jitter
    # stream and the parity band occasionally clipped under an unlucky
    # draw. Pinning both (node ids key store iteration order; eval ids
    # seed the per-eval stack rng, DET001) makes each seed's rates a
    # constant — the parity claim is now exact, not statistical. The
    # microbatch reset drops in-flight hints a loaded suite may have
    # leaked (coalescing changes timing, never bits, but a leaked hint
    # makes lone solves wait out the batch window under load).
    microbatch.reset()

    def rates(algorithm, seed, n_nodes=400, n_jobs=6, tasks=300):
        random.seed(seed)
        fsm = bench._seed_fsm(n_nodes, algorithm, seed=seed + 7,
                              pin_ids=f"fz{seed}-")
        planner = Planner(RaftLog(fsm), fsm.state)
        jobs = []
        for j in range(n_jobs):
            job = bench._mk_batch_job(f"conc-{j}", tasks, cpu=400, mem=700)
            bench._register(fsm, job)
            jobs.append(job)
        stale = fsm.state.snapshot()    # every "worker" plans from here
        rn = tn = ra = ta = 0
        for j, job in enumerate(jobs):
            shim, _ = bench._run_eval(
                fsm, planner, job, snap=stale,
                eval_id=f"fuzz-{algorithm}-{seed}-{j}")
            for plan, result in shim.submissions:
                if result is None:
                    continue
                tn += len(plan.node_allocation)
                rn += len(result.rejected_nodes)
                ta += sum(len(v) for v in plan.node_allocation.values())
                ra += sum(len(plan.node_allocation[n])
                          for n in set(result.rejected_nodes))
        assert tn and ta, "sim produced no contention at all"
        return rn / tn, ra / ta

    for seed in (1, 2, 3):
        node_tpu, alloc_tpu = rates(SCHED_ALG_TPU, seed)
        node_host, alloc_host = rates("binpack", seed)
        # the sim must actually contend, or parity is vacuous
        assert node_host > 0.01, f"seed {seed}: no contention"
        assert alloc_tpu <= alloc_host * 1.1 + 0.005, \
            f"seed {seed}: alloc-level rejection {alloc_tpu:.4f} vs " \
            f"host {alloc_host:.4f}"
        assert node_tpu <= node_host * 1.1 + 0.005, \
            f"seed {seed}: node-level rejection {node_tpu:.4f} vs " \
            f"host {node_host:.4f}"


# ------------------------------------------------- determinism (DET001)

def test_fixed_seed_bit_identical_placements():
    """ISSUE 2 acceptance: after the DET001 fix (per-eval rng seeded from
    the eval id, threaded from GenericStack through the solver's
    shuffle/jitter draws), identical (snapshot, eval, seed) inputs give
    BIT-IDENTICAL placements across two independent runs — for both
    depth regimes: jittered sampled-grid (count << nodes, the E-S order
    jitter actually draws) and deterministic full-curve (m > 3)."""

    def run(count: int, eval_id: str):
        random.seed(1234)       # global stream: must NOT matter anymore
        h = Harness()
        h.state.set_scheduler_config(
            h.get_next_index(),
            SchedulerConfiguration(scheduler_algorithm=SCHED_ALG_TPU))
        for i in range(16):
            n = mock.node()
            n.id = f"node-{i:04d}"          # pin ids so runs compare
            n.name = f"det-{i}"
            h.state.upsert_node(h.get_next_index(), n)
        job = mock.batch_job()
        job.id = job.name = f"det-job-{count}"
        tg = job.task_groups[0]
        tg.count = count
        tg.networks = []
        t = tg.tasks[0]
        t.resources.networks = []
        t.resources.cpu = 250
        t.resources.memory_mb = 128
        h.state.upsert_job(h.get_next_index(), job)
        ev = Evaluation(id=eval_id, job_id=job.id, type=job.type)
        h.process(lambda s, p: new_scheduler(job.type, s, p), ev)
        placed: dict[str, int] = {}
        for a in h.state.allocs_by_job("default", job.id):
            placed[a.node_id] = placed.get(a.node_id, 0) + 1
        return placed

    for count in (6, 48):       # jittered regime / deterministic regime
        a = run(count, "det-eval-1")
        # desync the global RNG between runs to prove independence
        random.seed(999)
        random.getrandbits(64)
        b = run(count, "det-eval-1")
        assert sum(a.values()) == count
        assert a == b, f"count={count}: run A {a} != run B {b}"
        # a DIFFERENT eval id decorrelates (the concurrent-worker
        # property the shuffle exists for) — placements are allowed to
        # differ, and for the jittered regime they essentially always do
        c = run(count, "det-eval-2")
        assert sum(c.values()) == count


# ---------------------------------------------- pipelined plan lifecycle

PIPELINE_ON = {"plan_pipeline_min_count": 1, "plan_pipeline_chunks": 3}


def test_fuzz_pipelined_path_matches_serial_invariants():
    """ISSUE 1 acceptance: the pipelined plan lifecycle is
    behavior-identical to serial under the differential fuzz invariants —
    3 seeds, chunked solve+commit forced down to tiny counts, vs the
    serial path on the same seed: all placed, no overcommit, and the
    host-model score within the same band the serial fuzz asserts."""
    from nomad_tpu.metrics import metrics
    for seed in (101, 202, 303):
        rng = np.random.default_rng(seed)
        n_nodes = int(rng.integers(6, 20))
        # m = 2*count/n > 3 keeps the solve in the deterministic
        # full-curve regime — the only regime the pipeline chunks (the
        # jittered sampled-grid regime stays serial by design)
        count = int(rng.integers(2 * n_nodes, 3 * n_nodes))
        c0 = metrics.counter("nomad.plan.pipeline.evals")
        h_pipe, job_p = run_scenario(SCHED_ALG_TPU, seed, n_nodes, count,
                                     cpu=250, mem=128,
                                     config_kwargs=PIPELINE_ON)
        assert metrics.counter("nomad.plan.pipeline.evals") > c0, \
            f"seed {seed}: pipelined path never engaged"
        h_ser, job_s = run_scenario(SCHED_ALG_TPU, seed, n_nodes, count,
                                    cpu=250, mem=128,
                                    config_kwargs={
                                        "plan_pipeline_enabled": False})
        check_committed(h_pipe, job_p, count)
        check_committed(h_ser, job_s, count)
        s_pipe = host_model_score(h_pipe.state, job_p, "worker")
        s_ser = host_model_score(h_ser.state, job_s, "worker")
        assert s_pipe >= s_ser * 0.9 - 1e-6, \
            f"seed {seed}: pipelined {s_pipe:.4f} < 0.9 * serial {s_ser:.4f}"


def test_pipeline_distinct_hosts_stays_serial():
    """distinct_hosts lowers to max_per_node=1, which binds per SOLVE —
    C chunked solves could stack C same-job instances on one node (the
    fed-forward collision count is only a soft penalty), so the pipeline
    must decline and the constraint must hold."""
    from nomad_tpu.metrics import metrics
    from nomad_tpu.structs import Constraint, OP_DISTINCT_HOSTS
    random.seed(5)
    h = Harness()
    h.state.set_scheduler_config(
        h.get_next_index(),
        SchedulerConfiguration(scheduler_algorithm=SCHED_ALG_TPU,
                               **PIPELINE_ON))
    for _ in range(12):
        h.state.upsert_node(h.get_next_index(), mock.node())
    job = mock.batch_job()
    job.constraints.append(Constraint(operand=OP_DISTINCT_HOSTS))
    tg = job.task_groups[0]
    tg.count = 10
    tg.networks = []
    tg.tasks[0].resources.networks = []
    h.state.upsert_job(h.get_next_index(), job)
    c0 = metrics.counter("nomad.plan.pipeline.evals")
    ev = Evaluation(job_id=job.id, type=job.type)
    h.process(lambda s, p: new_scheduler(job.type, s, p), ev)
    assert metrics.counter("nomad.plan.pipeline.evals") == c0, \
        "distinct_hosts eval took the pipelined path"
    allocs = [a for a in h.state.allocs_by_job("default", job.id)
              if not a.terminal_status()]
    assert len(allocs) == 10
    assert len({a.node_id for a in allocs}) == 10


def test_pipeline_single_chunk_stays_serial():
    """plan_pipeline_chunks=1 validates (>= 1) and is honored as "stay
    serial" — a one-chunk pipeline commits nothing early, so silently
    running 2 chunks would contradict the validated config."""
    from nomad_tpu.metrics import metrics
    c0 = metrics.counter("nomad.plan.pipeline.evals")
    h, job = run_scenario(SCHED_ALG_TPU, 7, 10, 20, cpu=250, mem=128,
                          config_kwargs={"plan_pipeline_min_count": 1,
                                         "plan_pipeline_chunks": 1})
    assert metrics.counter("nomad.plan.pipeline.evals") == c0
    check_committed(h, job, 20)


def test_pipeline_env_flag_forces_serial():
    """NOMAD_PLAN_PIPELINE=0 overrides an enabled config — the operator's
    serial-fallback escape hatch."""
    import os

    from nomad_tpu.metrics import metrics
    os.environ["NOMAD_PLAN_PIPELINE"] = "0"
    try:
        c0 = metrics.counter("nomad.plan.pipeline.evals")
        h, job = run_scenario(SCHED_ALG_TPU, 7, 10, 20, cpu=250, mem=128,
                              config_kwargs=PIPELINE_ON)
        assert metrics.counter("nomad.plan.pipeline.evals") == c0
        check_committed(h, job, 20)
    finally:
        del os.environ["NOMAD_PLAN_PIPELINE"]


def _uniform_cluster_fsm(algorithm: str, n_nodes: int, config_kwargs=None):
    """NomadFSM + real serial applier state with n_nodes UNIFORM mock
    nodes (3900 usable cpu / 7936 usable mem each after reservation)."""
    from nomad_tpu.server.fsm import NomadFSM

    fsm = NomadFSM()
    s = fsm.state
    s.set_scheduler_config(
        1, SchedulerConfiguration(scheduler_algorithm=algorithm,
                                  **(config_kwargs or {})))
    idx = 2
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.name = f"uni-{i}"
        s.upsert_node(idx, n)
        nodes.append(n)
        idx += 1
    return fsm, nodes


def test_pipelined_commit_ordering_concurrent_writer_parity():
    """ISSUE 1 satellite: a concurrent state write lands between chunk N's
    commit and the later chunks' commits; the applier's latest-state
    re-check must reject those placements and the eval must
    refresh-and-retry EXACTLY as the serial path does — same committed
    count, same rejection count, same final eval disposition.

    9 uniform nodes x 10 tasks each, count=90 (every node is needed), so
    the hog alloc injected on a still-empty node after the first apply is
    guaranteed to collide with a later chunk (pipelined) / the one plan
    (serial)."""
    import bench
    from nomad_tpu.server.fsm import RaftLog
    from nomad_tpu.server.plan_apply import Planner

    def hog_for(state):
        """A full-node competitor alloc on a node with no allocs yet."""
        hog_job = mock.batch_job()
        hog_job.id = hog_job.name = "hog"
        t = hog_job.task_groups[0].tasks[0]
        t.resources.cpu = 3900
        t.resources.memory_mb = 512
        t.resources.networks = []
        hog_job.task_groups[0].networks = []
        empty = next(n for n in state.iter_nodes()
                     if not state.allocs_by_node(n.id))
        return mock.alloc_for(hog_job, empty)

    class InjectingPlanner(Planner):
        def __init__(self, raft, state, fire_after: int):
            super().__init__(raft, state)
            self._applies = 0
            self._fire_after = fire_after
            self.fired = False

        def apply_plan(self, plan):
            if not self.fired and self._applies == self._fire_after:
                s = self.state
                s.upsert_allocs(s.latest_index() + 1, [hog_for(s)])
                self.fired = True
            self._applies += 1
            return super().apply_plan(plan)

    def run(pipelined: bool, seed: int):
        random.seed(seed)
        cfg = dict(PIPELINE_ON) if pipelined \
            else {"plan_pipeline_enabled": False}
        fsm, _ = _uniform_cluster_fsm(SCHED_ALG_TPU, 9, cfg)
        s = fsm.state
        # pipelined: hog lands after chunk 1 of 3 commits; serial: hog
        # lands after the snapshot but before the single plan applies —
        # the same concurrent-writer race, phrased per path
        planner = InjectingPlanner(RaftLog(fsm), s,
                                   fire_after=1 if pipelined else 0)
        job = bench._mk_batch_job("ordering", 90, cpu=390, mem=512)
        s.upsert_job(s.latest_index() + 1, job)
        shim, sched = bench._run_eval(fsm, planner, job)
        assert planner.fired, "interleaved write never fired"
        committed = [a for a in s.iter_allocs() if a.job_id == "ordering"]
        rejected = sum(len(r.rejected_nodes)
                       for _, r in shim.all_submissions() if r is not None)
        # overcommit check against committed state
        view = s.usage.view()
        assert not bool((view.used > view.cap + 1e-3).any())
        evals = [e for e in s.evals_by_job("default", "ordering")]
        status = sorted(e.status for e in evals if e.status)
        hog_live = bool([a for a in s.iter_allocs()
                         if a.job_id == "hog"
                         and not a.terminal_status()])
        return len(committed), rejected, status, hog_live

    obs_pipe = run(True, 1234)
    obs_serial = run(False, 1234)
    assert obs_pipe[1] >= 1, f"no rejection surfaced: {obs_pipe}"
    assert obs_pipe == obs_serial, \
        f"pipelined {obs_pipe} != serial {obs_serial}"
