"""Device plugin manager tests (modeled on client/devicemanager tests,
plugins/device, and scheduler/device_test.go end-to-end behavior)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.client.devicemanager import (
    ContainerReservation, StaticDevicePlugin,
)
from nomad_tpu.structs import RequestedDevice


def wait_until(fn, timeout=15.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def test_static_plugin_fingerprint_and_reserve():
    p = StaticDevicePlugin("nvidia", "gpu", "1080ti", ["GPU-0", "GPU-1"])
    groups = p.fingerprint()
    assert len(groups) == 1
    g = groups[0]
    assert g.id_tuple() == ("nvidia", "gpu", "1080ti")
    assert [i.id for i in g.instances] == ["GPU-0", "GPU-1"]
    res = p.reserve(["GPU-1"])
    assert res.envs == {"NVIDIA_GPU_VISIBLE_DEVICES": "GPU-1"}
    with pytest.raises(ValueError, match="unknown device ids"):
        p.reserve(["GPU-9"])


def test_unhealthy_instances_fingerprint():
    p = StaticDevicePlugin("v", "fpga", "x1", ["a", "b"])
    p.unhealthy.add("b")
    g = p.fingerprint()[0]
    health = {i.id: i.healthy for i in g.instances}
    assert health == {"a": True, "b": False}
    assert p.stats() == {"a": {"healthy": True}, "b": {"healthy": False}}


def test_device_scheduling_end_to_end():
    """A job asking for a device gets specific instance ids assigned by the
    scheduler and sees them in its env; a second ask beyond capacity
    blocks."""
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=2))
    a.start()
    try:
        a.client.register_device_plugin(
            StaticDevicePlugin("fake", "gpu", "model-x",
                               ["GPU-0", "GPU-1"]))
        assert wait_until(
            lambda: (n := a.server.state.node_by_id(a.client.node.id))
            is not None and n.ready() and n.node_resources.devices)

        job = mock.job()
        job.id = job.name = "gpujob"
        tg = job.task_groups[0]
        tg.count = 1
        task = tg.tasks[0]
        task.driver = "raw_exec"
        task.config = {"command": "/bin/sh",
                       "args": ["-c", "env > local/env.txt; sleep 30"]}
        task.resources.networks = []
        task.resources.cpu = 50
        task.resources.memory_mb = 32
        task.resources.devices = [RequestedDevice(name="fake/gpu", count=2)]
        a.server.job_register(job)
        assert wait_until(lambda: any(
            al.client_status == "running"
            for al in a.server.state.allocs_by_job("default", "gpujob")))
        alloc = [al for al in
                 a.server.state.allocs_by_job("default", "gpujob")
                 if al.client_status == "running"][0]
        devs = alloc.allocated_resources.tasks[task.name].devices
        assert len(devs) == 1
        assert sorted(devs[0].device_ids) == ["GPU-0", "GPU-1"]
        # the task env carries the visibility variable
        import os
        env_file = os.path.join(a.client.alloc_dir_root, alloc.id,
                                task.name, "local", "env.txt")
        assert wait_until(lambda: os.path.exists(env_file), timeout=10)

        def env_has_devices():
            with open(env_file) as f:
                content = f.read()
            return "FAKE_GPU_VISIBLE_DEVICES=GPU-0,GPU-1" in content \
                or "FAKE_GPU_VISIBLE_DEVICES=GPU-1,GPU-0" in content
        assert wait_until(env_has_devices, timeout=10)

        # all instances used: a second device job can't place
        job2 = mock.job()
        job2.id = job2.name = "gpujob2"
        tg2 = job2.task_groups[0]
        tg2.count = 1
        t2 = tg2.tasks[0]
        t2.driver = "mock_driver"
        t2.config = {"run_for": 30}
        t2.resources.networks = []
        t2.resources.cpu = 50
        t2.resources.memory_mb = 32
        t2.resources.devices = [RequestedDevice(name="fake/gpu", count=1)]
        a.server.job_register(job2)
        assert wait_until(lambda: any(
            e.status == "blocked"
            for e in a.server.state.evals_by_job("default", "gpujob2")),
            timeout=15)
        assert not a.server.state.allocs_by_job("default", "gpujob2")
    finally:
        a.shutdown()


def test_client_stats_include_devices():
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=0))
    a.start()
    try:
        a.client.register_device_plugin(
            StaticDevicePlugin("fake", "gpu", "m", ["g0"]))
        stats = a.client.host_stats()
        assert stats["DeviceStats"] == {"fake/gpu/m": {"g0": {"healthy": True}}}
    finally:
        a.shutdown()
