"""Web UI serving tests: /ui loads, / redirects, API endpoints the UI
consumes respond (the Mirage-style smoke test of the SPA contract)."""
import json
import urllib.request

import pytest

from nomad_tpu.agent import Agent, AgentConfig


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(dev_mode=True, http_port=0, client_enabled=False))
    a.start()
    yield a
    a.shutdown()


def test_ui_served(agent):
    with urllib.request.urlopen(agent.http_addr + "/ui", timeout=10) as r:
        body = r.read().decode()
    assert r.status == 200
    assert "<title>nomad-tpu</title>" in body
    # the SPA's API surface is referenced
    for path in ("/jobs", "/nodes", "/event/stream", "/agent/members"):
        assert path in body


def test_root_redirects_to_ui(agent):
    with urllib.request.urlopen(agent.http_addr + "/", timeout=10) as r:
        assert "<title>nomad-tpu</title>" in r.read().decode()


def test_ui_api_contract(agent):
    """Every endpoint the UI fetches exists and returns JSON."""
    for path in ("/v1/jobs?namespace=*", "/v1/nodes",
                 "/v1/services?namespace=*", "/v1/agent/members"):
        with urllib.request.urlopen(agent.http_addr + path,
                                    timeout=10) as r:
            json.loads(r.read())
