"""Web UI serving tests: /ui loads, / redirects, API endpoints the UI
consumes respond (the Mirage-style smoke test of the SPA contract)."""
import json
import urllib.request

import pytest

from nomad_tpu.agent import Agent, AgentConfig


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(dev_mode=True, http_port=0, client_enabled=False))
    a.start()
    yield a
    a.shutdown()


def test_ui_served(agent):
    with urllib.request.urlopen(agent.http_addr + "/ui", timeout=10) as r:
        body = r.read().decode()
    assert r.status == 200
    assert "<title>nomad-tpu</title>" in body
    # the SPA's API surface is referenced
    for path in ("/jobs", "/nodes", "/event/stream", "/agent/members"):
        assert path in body


def test_root_redirects_to_ui(agent):
    with urllib.request.urlopen(agent.http_addr + "/", timeout=10) as r:
        assert "<title>nomad-tpu</title>" in r.read().decode()


def test_ui_api_contract(agent):
    """Every endpoint the UI fetches exists and returns JSON."""
    for path in ("/v1/jobs?namespace=*", "/v1/nodes",
                 "/v1/services?namespace=*", "/v1/agent/members",
                 "/v1/deployments?namespace=*",
                 "/v1/evaluations?namespace=*"):
        with urllib.request.urlopen(agent.http_addr + path,
                                    timeout=10) as r:
            json.loads(r.read())


def test_ui_references_all_views(agent):
    with urllib.request.urlopen(agent.http_addr + "/ui", timeout=10) as r:
        body = r.read().decode()
    for view in ("jobs", "deployments", "nodes", "topology", "services",
                 "events", "evals", "alloc", "tailLogs", "runExec",
                 "depAction", "Versions", "traces", "metrics"):
        assert view in body, f"UI missing view/function {view}"
    # topology utilization meters + ACL token plumbing
    for frag in ("NodeResources", "X-Nomad-Token", "tokenbox",
                 "class=\"meter\""):
        assert frag in body, f"UI missing {frag}"
    # ISSUE 7: eval waterfall panel + histogram-bucket rendering
    for frag in ("/traces", "wftrack", "linked_spans", "class=\"hist\"",
                 "buckets", "format=chrome"):
        assert frag in body, f"UI missing trace/metrics fragment {frag}"


# ------------------------------------------- live-cluster UI data contract

@pytest.fixture(scope="module")
def live_agent(tmp_path_factory):
    a = Agent(AgentConfig(dev_mode=True, http_port=0,
                          data_dir=str(tmp_path_factory.mktemp("uiagent"))))
    a.start()
    yield a
    a.shutdown()


def _get(agent, path):
    with urllib.request.urlopen(agent.http_addr + path, timeout=15) as r:
        return json.loads(r.read())


def _post(agent, path, body):
    req = urllib.request.Request(
        agent.http_addr + path, data=json.dumps(body).encode(),
        method="POST", headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=15) as r:
        return json.loads(r.read())


def _wait(fn, timeout=15.0):
    import time
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            out = fn()
            if out:
                return out
        except Exception:   # noqa: BLE001
            pass
        import time as _t
        _t.sleep(0.2)
    raise AssertionError("condition never became true")


def test_ui_browses_running_cluster(live_agent):
    """The data the UI renders is real: submit a job, then walk the
    exact fetches the views make — job detail, alloc detail with task
    states, log follow frames, deployments, topology, exec."""
    import base64
    job = {"Job": {
        "ID": "ui-e2e", "Name": "ui-e2e", "Type": "service",
        "Datacenters": ["dc1"],
        "Update": {"MaxParallel": 1, "HealthCheck": "task_states",
                   "MinHealthyTimeSec": 0.01},
        "TaskGroups": [{
            "Name": "g", "Count": 1,
            "Update": {"MaxParallel": 1, "HealthCheck": "task_states",
                       "MinHealthyTimeSec": 0.01},
            "Tasks": [{
                "Name": "t", "Driver": "raw_exec",
                "Config": {"command": "/bin/sh",
                           "args": ["-c",
                                    "i=0; while true; do echo ui-line-$i;"
                                    " i=$((i+1)); sleep 0.2; done"]},
                "Resources": {"CPU": 50, "MemoryMB": 32}}]}]}}
    _post(live_agent, "/v1/jobs", job)

    allocs = _wait(lambda: [
        a for a in _get(live_agent, "/v1/job/ui-e2e/allocations")
        if a["ClientStatus"] == "running"])
    alloc_id = allocs[0]["ID"]

    # alloc view: task states present
    a = _get(live_agent, f"/v1/allocation/{alloc_id}")
    assert a["TaskStates"]["t"]["State"] == "running"

    # log follow frame: base64 data + advancing offset
    out = _wait(lambda: _get(
        live_agent, f"/v1/client/fs/logs/{alloc_id}"
                    f"?task=t&type=stdout&follow=true&offset=0&wait=5"))
    data = base64.b64decode(out["Data"])
    assert b"ui-line-0" in data
    assert out["Offset"] > 0

    # deployments view: the service job created one
    deps = _get(live_agent, "/v1/deployments?namespace=*")
    assert any(d["JobID"] == "ui-e2e" for d in deps)

    # topology view: node allocations include ours
    nodes = _get(live_agent, "/v1/nodes")
    node_allocs = _get(live_agent,
                       f"/v1/node/{nodes[0]['ID']}/allocations")
    assert any(x["ID"] == alloc_id for x in node_allocs)

    # exec panel round trip (the runExec fetch sequence)
    sid = _post(live_agent, f"/v1/client/allocation/{alloc_id}/exec",
                {"Task": "t", "Cmd": ["/bin/sh", "-c", "echo from-ui"]}
                )["SessionID"]
    collected = b""
    for _ in range(20):
        chunk = _get(live_agent, f"/v1/client/exec-session/{sid}?wait=1")
        collected += base64.b64decode(chunk["Stdout"])
        if chunk["Exited"] and not chunk["Stdout"]:
            break
    assert b"from-ui" in collected


def test_ui_deployment_detail_and_run_views(agent):
    """The r3-missing views exist: deployment detail (per-TG health,
    promote/pause/fail), job editor with Plan/Run, per-task event
    timeline, resource charts."""
    with urllib.request.urlopen(agent.http_addr + "/ui", timeout=10) as r:
        body = r.read().decode()
    for frag in ("async deployment(id)", "async run()", "planJob",
                 "submitJob", "_renderDiff", "Task timeline",
                 "class=\"timeline\"", "barrow", "depAction",
                 "DesiredCanaries", "jobs/parse", "/plan"):
        assert frag in body, f"UI missing {frag}"


def test_ui_run_flow_endpoints(agent):
    """The editor's round trip: parse HCL -> plan -> submit."""
    hcl = ('job "uirun" { group "g" { task "t" { driver = "mock_driver" '
           'config { run_for = "1s" } } } }')
    req = urllib.request.Request(
        agent.http_addr + "/v1/jobs/parse",
        data=json.dumps({"JobHCL": hcl}).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    job = json.load(urllib.request.urlopen(req, timeout=10))
    assert job["ID"] == "uirun"
    req = urllib.request.Request(
        agent.http_addr + "/v1/job/uirun/plan",
        data=json.dumps({"Job": job, "Diff": True}).encode(),
        method="POST", headers={"Content-Type": "application/json"})
    plan = json.load(urllib.request.urlopen(req, timeout=10))
    assert "Diff" in plan and "FailedTGAllocs" in plan
    # plan is a dry run: the job must NOT be registered
    try:
        urllib.request.urlopen(agent.http_addr + "/v1/job/uirun",
                               timeout=10)
        assert False, "plan registered the job"
    except urllib.error.HTTPError as e:
        assert e.code == 404
