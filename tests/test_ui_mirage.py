"""Mirage-analog UI test tier (VERDICT r4 #8; ref ui/mirage/): canned
cluster state behind the REAL /v1 API, with each SPA view's fetch +
transform pipeline replayed and asserted — the data a view renders must
exist, field for field, in what the API serves. (No JS engine ships in
this image, so the render functions' DATA CONTRACT is the testable
surface; the templates are pure functions of these payloads.)
"""
import json
import re
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.client.csimanager import HostPathCSIPlugin
from nomad_tpu.integrations.services import ServiceIntention
from nomad_tpu.structs import (
    CSIVolume, CSIVolumeClaim, ScalingPolicy, CLAIM_WRITE,
)

from test_csi import wait_until


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    """Dev agent seeded with fixture state for every UI view: a running
    service job, CSI plugin + claimed volume, scaling policy,
    deployment, service catalog rows."""
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=2))
    a.start()
    base = str(tmp_path_factory.mktemp("csi"))
    a.client.register_csi_plugin("hostpath", HostPathCSIPlugin(base))
    assert wait_until(
        lambda: a.server.state.node_by_id(a.client.node.id) is not None
        and a.server.state.node_by_id(a.client.node.id).ready())
    a.server.csi_volume_register([
        CSIVolume(id="ui-vol", namespace="default", plugin_id="hostpath",
                  name="ui-vol")])
    # a claim so the volume detail view has rows
    a.server.csi_volume_claim("default", "ui-vol", CSIVolumeClaim(
        alloc_id="a" * 36, node_id=a.client.node.id, mode=CLAIM_WRITE))

    job = mock.job()
    job.id = job.name = "ui-job"
    tg = job.task_groups[0]
    tg.count = 1
    tg.scaling = ScalingPolicy(min=1, max=5, enabled=True)
    tg.tasks[0].driver = "mock_driver"
    tg.tasks[0].config = {"run_for": "60s"}
    tg.tasks[0].resources.networks = []
    a.server.job_register(job)
    assert wait_until(lambda: any(
        al.client_status == "running"
        for al in a.server.state.allocs_by_job("default", "ui-job")))
    a.server.intention_upsert(ServiceIntention(
        source="web-svc", destination="db-svc", action="deny"))
    yield a
    a.shutdown()


def _get(a, path):
    with urllib.request.urlopen(a.http_addr + path, timeout=10) as r:
        return json.loads(r.read())


def _post(a, path, body):
    req = urllib.request.Request(
        a.http_addr + path, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def _require(obj: dict, fields: list, view: str):
    for f in fields:
        assert f in obj, f"{view}: API payload lacks {f!r} " \
            f"(the view renders it); has {sorted(obj)}"


# ---------------------------------------------------------- view contracts

def test_volumes_view_contract(agent):
    vols = _get(agent, "/v1/volumes?namespace=*")
    assert any(v["ID"] == "ui-vol" for v in vols)
    _require(vols[0], ["ID", "Namespace", "PluginID", "Schedulable",
                       "AccessMode", "CurrentReaders", "CurrentWriters",
                       "NodesHealthy", "ControllerRequired",
                       "ControllersHealthy"], "volumes")
    plugins = _get(agent, "/v1/plugins")
    assert any(p["ID"] == "hostpath" for p in plugins)
    _require(plugins[0], ["ID", "Provider", "ControllerRequired",
                          "NodesExpected", "NodesHealthy"], "volumes")


def test_volume_detail_view_contract(agent):
    v = _get(agent, "/v1/volume/csi/ui-vol?namespace=default")
    _require(v, ["Name", "PluginID", "AccessMode", "AttachmentMode",
                 "ControllerRequired", "NodesHealthy",
                 "WriteClaims"], "volume")
    # the claims table walks WriteClaims/ReadClaims entries
    claims = v["WriteClaims"]
    assert claims, "fixture claim missing"
    claim = next(iter(claims.values()))
    _require(claim, ["NodeID", "State"], "volume claims")
    # secrets must never be served to the UI
    assert "Secrets" not in v


def test_scaling_view_contract(agent):
    pols = _get(agent, "/v1/scaling/policies?namespace=*")
    assert pols, "fixture scaling policy missing"
    _require(pols[0], ["ID", "Target", "Type", "Enabled"], "scaling")
    assert pols[0]["Target"].get("Job") == "ui-job"
    detail = _get(agent, f"/v1/scaling/policy/{pols[0]['ID']}")
    assert detail.get("ID") == pols[0]["ID"]


def test_topology_view_contract(agent):
    nodes = _get(agent, "/v1/nodes")
    _require(nodes[0], ["ID", "Name", "Status",
                        "SchedulingEligibility"], "topology")
    node = _get(agent, f"/v1/node/{nodes[0]['ID']}")
    # utilization meters divide allocated by NodeResources
    assert node.get("NodeResources"), "topology needs NodeResources"
    allocs = _get(agent, f"/v1/node/{nodes[0]['ID']}/allocations")
    assert isinstance(allocs, list)


def test_job_editor_plan_preview_flow(agent):
    """The Run-Job editor path exactly as the SPA drives it (weak r4 #5:
    this flow had no test): parse HCL -> dry-run plan with Diff ->
    rendered diff walk -> submit -> eval."""
    hcl = '''
job "ui-job" {
  datacenters = ["dc1"]
  group "web" {
    count = 3
    task "web" {
      driver = "mock_driver"
      config { run_for = "60s" }
      resources { cpu = 100 memory = 64 }
    }
  }
}
'''
    job = _post(agent, "/v1/jobs/parse", {"JobHCL": hcl})
    jid = job.get("ID") or job.get("Id")
    assert jid == "ui-job"
    plan = _post(agent, f"/v1/job/{jid}/plan?namespace=default",
                 {"Job": job, "Diff": True})
    diff = plan.get("Diff")
    assert diff and diff["Type"] == "Edited"

    # replay _renderDiff's walk: every node it renders must carry the
    # fields it reads, and the count bump must surface as a field delta
    lines = []

    def walk(d, indent):
        assert "Type" in d
        lines.append(f"{'  ' * indent}{d.get('Type')} {d.get('Name', '')}")
        for f in d.get("Fields") or []:
            assert {"Type", "Name", "Old", "New"} <= set(f)
            if f["Type"] != "None":
                lines.append(
                    f"{'  ' * indent}  {f['Type']} {f['Name']}: "
                    f"{f['Old']} => {f['New']}")
        for o in d.get("Objects") or []:
            walk(o, indent + 1)
        for tg in d.get("TaskGroups") or []:
            walk(tg, indent + 1)
        for t in d.get("Tasks") or []:
            walk(t, indent + 1)
    walk(diff, 0)
    rendered = "\n".join(lines)
    assert "Edited Count: 1 => 3" in rendered, rendered
    # nothing was submitted by the dry run
    assert _get(agent, "/v1/job/ui-job?namespace=default")[
        "TaskGroups"][0]["Count"] == 1

    # submit applies it and mints an eval (the SPA's submitJob())
    req = urllib.request.Request(
        agent.http_addr + "/v1/jobs?namespace=default",
        data=json.dumps({"Job": job}).encode(), method="PUT",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        resp = json.loads(r.read())
    assert resp.get("eval_id") or resp.get("EvalID")
    assert _get(agent, "/v1/job/ui-job?namespace=default")[
        "TaskGroups"][0]["Count"] == 3


def test_spa_views_reference_only_served_fields(agent):
    """Static cross-check: each view's api() endpoints appear in the SPA
    source, and the volumes/scaling nav routes exist (a renamed route
    silently 404s to the jobs view otherwise)."""
    with urllib.request.urlopen(agent.http_addr + "/ui", timeout=10) as r:
        body = r.read().decode()
    for frag in ("async volumes()", "async volume(", "async scaling()",
                 '"#/volumes"', '"#/scaling"', "/volumes?namespace=*",
                 "/plugins", "/scaling/policies?namespace=*",
                 "WriteClaims", "CurrentReaders", "NodesHealthy",
                 # topo-viz refinements: per-job coloring + legend
                 "jobHue", "legendrow", "AllocatedCPU"):
        assert frag in body, f"SPA missing {frag}"
    # nav links present
    assert re.search(r'href="#/volumes"', body)
    assert re.search(r'href="#/scaling"', body)


def test_jobs_wildcard_listing_contract(agent):
    """The SPA jobs view fetches /jobs?namespace=* — the wildcard must
    list across ALL namespaces (regression: it used to match the
    literal namespace \"*\" and render an empty jobs table; mapping
    \"*\" to just \"default\" would hide other namespaces' jobs)."""
    agent.server.namespace_upsert([{"name": "ui-team"}])
    other = mock.job()
    other.id = other.name = "ui-other-ns"
    other.namespace = "ui-team"
    other.task_groups[0].tasks[0].driver = "mock_driver"
    other.task_groups[0].tasks[0].resources.networks = []
    agent.server.job_register(other)
    jobs = _get(agent, "/v1/jobs?namespace=*")
    ids = {j["ID"] for j in jobs}
    assert {"ui-job", "ui-other-ns"} <= ids, ids
    _require(jobs[0], ["ID", "Namespace", "Type", "Priority",
                       "Status"], "jobs")
    # scoped listing still filters
    assert {j["ID"] for j in _get(agent, "/v1/jobs?namespace=ui-team")} \
        == {"ui-other-ns"}
