"""CSI subsystem tests (modeled on nomad/csi_endpoint_test.go,
nomad/state/state_store_test.go CSI cases, nomad/volumewatcher tests, and
client csimanager/csi_hook tests)."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.client import Client
from nomad_tpu.client.csimanager import HostPathCSIPlugin
from nomad_tpu.server import Server
from nomad_tpu.structs import (
    CSIVolume, CSIVolumeClaim, Node, VolumeRequest,
    ACCESS_MODE_MULTI_NODE_READER, ACCESS_MODE_SINGLE_NODE_WRITER,
    CLAIM_READ, CLAIM_STATE_READY_TO_FREE, CLAIM_WRITE,
)


def wait_until(fn, timeout=15.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


@pytest.fixture
def server():
    s = Server(num_workers=0)
    s.start()
    yield s
    s.shutdown()


def _csi_node(plugin="hostpath", healthy=True):
    node = mock.node()
    node.csi_node_plugins = {plugin: {"healthy": healthy,
                                      "provider": plugin,
                                      "provider_version": "0.1.0"}}
    return node


def _vol(vol_id="vol0", plugin="hostpath",
         access=ACCESS_MODE_SINGLE_NODE_WRITER):
    return CSIVolume(id=vol_id, name=vol_id, plugin_id=plugin,
                     access_mode=access)


def test_plugin_aggregation_from_nodes(server):
    n1, n2 = _csi_node(), _csi_node(healthy=False)
    server.node_register(n1)
    server.node_register(n2)
    plugins = server.csi_plugin_list()
    assert len(plugins) == 1
    p = plugins[0]
    assert p.id == "hostpath"
    assert len(p.nodes) == 2 and p.nodes_healthy == 1
    # node deregistration removes its contribution
    server.raft.apply("NodeDeregisterRequestType", {"node_ids": [n2.id]})
    p = server.csi_plugin_get("hostpath")
    assert len(p.nodes) == 1 and p.nodes_healthy == 1


def test_volume_register_claim_lifecycle(server):
    server.node_register(_csi_node())
    server.csi_volume_register([_vol()])
    vol = server.csi_volume_get("default", "vol0")
    assert vol.schedulable
    # write claim taken; second writer refused (single-node-writer)
    c1 = CSIVolumeClaim(alloc_id="a1", node_id="n1", mode=CLAIM_WRITE)
    server.csi_volume_claim("default", "vol0", c1)
    with pytest.raises(ValueError, match="free write claims"):
        server.csi_volume_claim("default", "vol0", CSIVolumeClaim(
            alloc_id="a2", node_id="n1", mode=CLAIM_WRITE))
    # in-use deregister refused without force
    with pytest.raises(ValueError, match="in use"):
        server.csi_volume_deregister("default", "vol0")
    # release -> free again
    server.csi_volume_claim("default", "vol0", CSIVolumeClaim(
        alloc_id="a1", state=CLAIM_STATE_READY_TO_FREE))
    vol = server.csi_volume_get("default", "vol0")
    assert not vol.in_use()
    server.csi_volume_deregister("default", "vol0")
    assert server.csi_volume_get("default", "vol0") is None


def test_multi_reader_access_mode(server):
    server.node_register(_csi_node())
    server.csi_volume_register([_vol("rvol",
                                     access=ACCESS_MODE_MULTI_NODE_READER)])
    for aid in ("a1", "a2", "a3"):
        server.csi_volume_claim("default", "rvol", CSIVolumeClaim(
            alloc_id=aid, mode=CLAIM_READ))
    vol = server.csi_volume_get("default", "rvol")
    assert len(vol.read_claims) == 3
    with pytest.raises(ValueError, match="write"):
        server.csi_volume_claim("default", "rvol", CSIVolumeClaim(
            alloc_id="w1", mode=CLAIM_WRITE))


def test_volume_unschedulable_without_healthy_plugin(server):
    server.csi_volume_register([_vol("lonely", plugin="missing")])
    vol = server.csi_volume_get("default", "lonely")
    assert not vol.schedulable
    with pytest.raises(ValueError, match="not schedulable"):
        server.csi_volume_claim("default", "lonely", CSIVolumeClaim(
            alloc_id="a1", mode=CLAIM_WRITE))


def test_volume_watcher_reaps_terminal_alloc_claims(server):
    from nomad_tpu.structs import Allocation
    server.node_register(_csi_node())
    server.csi_volume_register([_vol("reap")])
    alloc = mock.alloc()
    alloc.client_status = "complete"
    alloc.desired_status = "stop"
    server.state.upsert_allocs(server.raft.barrier() + 1, [alloc])
    server.csi_volume_claim("default", "reap", CSIVolumeClaim(
        alloc_id=alloc.id, mode=CLAIM_WRITE))
    # claim has no live node: the watcher force-chains the detach machine
    # (taken -> node-detached -> ready-to-free) in one pass
    assert server.volume_watcher.reap_once() >= 1
    vol = server.csi_volume_get("default", "reap")
    assert not vol.in_use()
    # claims of live allocs survive
    live = mock.alloc()
    live.client_status = "running"
    server.state.upsert_allocs(server.raft.barrier() + 1, [live])
    server.csi_volume_claim("default", "reap", CSIVolumeClaim(
        alloc_id=live.id, mode=CLAIM_WRITE))
    assert server.volume_watcher.reap_once() == 0


def test_csi_survives_snapshot_restore(server):
    server.node_register(_csi_node())
    server.csi_volume_register([_vol("snapvol")])
    blob = server.snapshot_save()
    s2 = Server(num_workers=0)
    s2.start()
    try:
        s2.snapshot_restore(blob)
        assert s2.csi_volume_get("default", "snapvol") is not None
        assert s2.csi_plugin_get("hostpath") is not None
    finally:
        s2.shutdown()


def test_scheduler_filters_nodes_without_plugin(server):
    """CSIVolumeChecker: only nodes fingerprinting the volume's plugin are
    feasible."""
    good = _csi_node()
    bad = mock.node()
    server.node_register(good)
    server.node_register(bad)
    server.csi_volume_register([_vol("schedvol")])
    job = mock.job()
    job.id = job.name = "csijob"
    tg = job.task_groups[0]
    tg.count = 2
    tg.volumes = {"data": VolumeRequest(name="data", type="csi",
                                        source="schedvol")}
    tg.tasks[0].driver = "mock_driver"
    tg.tasks[0].resources.networks = []
    server.job_register(job)
    # run the scheduler synchronously via the harness against the server's
    # state (testing.go pattern)
    from nomad_tpu.scheduler import new_scheduler
    from nomad_tpu.scheduler.testing import Harness
    ev = server.state.evals_by_job("default", "csijob")[0]
    h = Harness(server.state.fork())
    h.process(lambda state, planner: new_scheduler(
        "service", state, planner), ev)
    assert h.plans
    placed_nodes = [nid for plan in h.plans
                    for nid, allocs in plan.node_allocation.items()
                    for _ in allocs]
    assert placed_nodes
    assert all(nid == good.id for nid in placed_nodes)


def test_end_to_end_hostpath_volume():
    """A job with a CSI volume runs against the dev agent: the hostpath
    plugin publishes the volume into the alloc dir and data persists across
    allocs."""
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=2))
    a.start()
    try:
        csi_base = os.path.join(a.config.data_dir, "csi-hostpath")
        a.client.register_csi_plugin("hostpath",
                                     HostPathCSIPlugin(csi_base))
        assert wait_until(
            lambda: (a.server.csi_plugin_get("hostpath") or
                     None) is not None
            and a.server.csi_plugin_get("hostpath").nodes_healthy == 1)
        a.server.csi_volume_register([_vol("appdata")])

        job = mock.job()
        job.id = job.name = "csirun"
        tg = job.task_groups[0]
        tg.count = 1
        tg.volumes = {"data": VolumeRequest(name="data", type="csi",
                                            source="appdata")}
        task = tg.tasks[0]
        task.driver = "raw_exec"
        task.config = {"command": "/bin/sh",
                       "args": ["-c",
                                "echo persisted > ../volumes/data/state.txt; sleep 30"]}
        task.resources.networks = []
        task.resources.cpu = 50
        task.resources.memory_mb = 32
        a.server.job_register(job)
        assert wait_until(lambda: any(
            al.client_status == "running"
            for al in a.server.state.allocs_by_job("default", "csirun")))
        alloc = [al for al in a.server.state.allocs_by_job("default", "csirun")
                 if al.client_status == "running"][0]
        # claim registered server-side
        vol = a.server.csi_volume_get("default", "appdata")
        assert alloc.id in vol.write_claims
        # the write landed in the backing hostpath volume dir
        backing = os.path.join(csi_base, "appdata", "state.txt")
        assert wait_until(lambda: os.path.exists(backing), timeout=10)
        # stop the job -> claim released by the alloc runner postrun
        a.server.job_deregister("default", "csirun")
        assert wait_until(
            lambda: not a.server.csi_volume_get("default",
                                                "appdata").in_use(),
            timeout=20)
        with open(backing) as f:
            assert f.read().strip() == "persisted"
    finally:
        a.shutdown()


def test_scheduler_rejects_claimed_single_writer_volume(server):
    """A single-node-writer volume with an existing write claim is not
    schedulable for another writer (ADVICE r1 #2; ref feasible.go
    CSIVolumeChecker + csi.go WriteFreeClaims)."""
    server.node_register(_csi_node())
    vol = _vol("busyvol")
    vol.write_claims["some-alloc"] = CSIVolumeClaim(
        alloc_id="some-alloc", node_id="n1", mode=CLAIM_WRITE)
    server.csi_volume_register([vol])
    job = mock.job()
    job.id = job.name = "busyjob"
    tg = job.task_groups[0]
    tg.volumes = {"data": VolumeRequest(name="data", type="csi",
                                        source="busyvol")}
    tg.tasks[0].driver = "mock_driver"
    tg.tasks[0].resources.networks = []
    server.job_register(job)
    from nomad_tpu.scheduler import new_scheduler
    from nomad_tpu.scheduler.testing import Harness
    ev = server.state.evals_by_job("default", "busyjob")[0]
    h = Harness(server.state.fork())
    h.process(lambda state, planner: new_scheduler(
        "service", state, planner), ev)
    placed = [a for plan in h.plans
              for allocs in plan.node_allocation.values() for a in allocs]
    assert not placed
    # a read-only request against the same volume is still feasible
    job2 = mock.job()
    job2.id = job2.name = "readjob"
    tg2 = job2.task_groups[0]
    tg2.volumes = {"data": VolumeRequest(name="data", type="csi",
                                         source="busyvol", read_only=True)}
    tg2.tasks[0].driver = "mock_driver"
    tg2.tasks[0].resources.networks = []
    server.job_register(job2)
    ev2 = server.state.evals_by_job("default", "readjob")[0]
    h2 = Harness(server.state.fork())
    h2.process(lambda state, planner: new_scheduler(
        "service", state, planner), ev2)
    placed2 = [a for plan in h2.plans
               for allocs in plan.node_allocation.values() for a in allocs]
    assert placed2
    # claims held by the scheduled job itself are exempt: a rolling update
    # or reschedule of the claim holder must still place (ref feasible.go)
    holder = mock.alloc()
    holder.id = "some-alloc"
    holder.namespace = "default"
    holder.job_id = "busyjob"
    server.state.upsert_allocs(99, [holder])
    h3 = Harness(server.state.fork())
    h3.process(lambda state, planner: new_scheduler(
        "service", state, planner), ev)
    placed3 = [a for plan in h3.plans
               for allocs in plan.node_allocation.values() for a in allocs]
    assert placed3


def test_volume_detach_releases_node_claims(server):
    """DELETE /v1/volume/csi/<id>/detach?node=N releases every claim held
    by allocs on that node (ref csi_endpoint.go CSIVolume.Unpublish +
    command/volume_detach.go)."""
    import urllib.request

    from nomad_tpu import mock
    from nomad_tpu.agent import Agent, AgentConfig
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=0))
    a.start()
    try:
        s = a.server
        node = _csi_node()
        s.node_register(node)
        s.csi_volume_register([_vol("det0")])
        job = mock.job()
        alloc = mock.alloc_for(job, node)
        s.state.upsert_job(s.state.latest_index() + 1, job)
        s.state.upsert_allocs(s.state.latest_index() + 1, [alloc])
        s.csi_volume_claim("default", "det0", CSIVolumeClaim(
            alloc_id=alloc.id, node_id=node.id, mode=CLAIM_WRITE))
        vol = s.state.csi_volume_by_id("default", "det0")
        assert alloc.id in vol.write_claims
        req = urllib.request.Request(
            a.http_addr + f"/v1/volume/csi/det0/detach?node={node.id}",
            method="DELETE")
        with urllib.request.urlopen(req, timeout=10) as resp:
            import json as _json
            out = _json.loads(resp.read())
        assert out["NumReleased"] == 1
        vol = s.state.csi_volume_by_id("default", "det0")
        # the claim is released (freed now or parked for the reaper)
        assert alloc.id not in vol.write_claims or \
            vol.write_claims[alloc.id].state != "taken"
    finally:
        a.shutdown()


# ---------------- unpublish state machine (VERDICT r3 #5) ----------------

class _FakeCSIPlugin(HostPathCSIPlugin):
    """Records every unpublish RPC; can inject failures."""

    name = "fake"
    requires_controller = True

    def __init__(self, base_dir):
        super().__init__(base_dir)
        self.node_unpublished: list = []
        self.controller_unpublished: list = []
        self.fail_node = 0
        self.fail_controller = 0

    def node_unpublish_volume(self, volume_id, target_path):
        if self.fail_node > 0:
            self.fail_node -= 1
            raise RuntimeError("injected node unpublish failure")
        self.node_unpublished.append(volume_id)
        super().node_unpublish_volume(volume_id, target_path)

    def controller_unpublish_volume(self, volume_id, node_id):
        if self.fail_controller > 0:
            self.fail_controller -= 1
            raise RuntimeError("injected controller unpublish failure")
        self.controller_unpublished.append((volume_id, node_id))


def _cluster_with_fake_plugin(tmp_path, fail_node=0, fail_controller=0):
    server = Server(num_workers=2, gc_interval=9999)
    server.start()
    client = Client(server, data_dir=str(tmp_path / "c0"))
    plugin = _FakeCSIPlugin(str(tmp_path / "csi"))
    plugin.fail_node = fail_node
    plugin.fail_controller = fail_controller
    client.start()
    client.register_csi_plugin("fake", plugin, controller=True)
    assert wait_until(lambda: (
        (p := server.csi_plugin_get("fake")) is not None
        and p.nodes_healthy == 1 and p.controllers_healthy == 1))
    server.csi_volume_register([_vol("data", plugin="fake")])
    return server, client, plugin


def _terminal_claim(server, client, vol="data"):
    """A write claim whose alloc is already terminal (the client died
    before releasing — the exact case the watcher exists for)."""
    alloc = mock.alloc()
    alloc.node_id = client.node.id
    alloc.client_status = "complete"
    alloc.desired_status = "stop"
    server.state.upsert_allocs(server.raft.barrier() + 1, [alloc])
    server.state.csi_volume_claim(
        server.raft.barrier() + 1, "default", vol,
        CSIVolumeClaim(alloc_id=alloc.id, node_id=client.node.id,
                       mode=CLAIM_WRITE))
    return alloc


def test_unpublish_node_then_controller_then_free(tmp_path):
    """Full detach machine: node unpublish on the claimed node, then
    controller unpublish, then the claim frees — each step gated on the
    plugin RPC succeeding (ref volume_watcher.go + csi/client.go)."""
    server, client, plugin = _cluster_with_fake_plugin(tmp_path)
    try:
        _terminal_claim(server, client)
        assert server.csi_volume_get("default", "data").in_use()

        # watcher alone can't free it: node round not confirmed yet
        server.volume_watcher.reap_once()
        vol = server.csi_volume_get("default", "data")
        assert vol.in_use()
        claim = list(vol.write_claims.values())[0]
        assert claim.state == "taken"

        # client pull performs node unpublish then (same node hosts the
        # controller) the controller round — order is enforced by the
        # pending queries gating on claim state
        assert client.csi_manager.reconcile_claims() >= 1
        assert plugin.node_unpublished == ["data"]
        if not plugin.controller_unpublished:
            assert client.csi_manager.reconcile_claims() >= 1
        assert plugin.controller_unpublished == [("data", client.node.id)]
        claim = list(server.csi_volume_get(
            "default", "data").write_claims.values())[0]
        assert claim.state == "controller-detached"

        # watcher frees only now
        assert server.volume_watcher.reap_once() >= 1
        assert not server.csi_volume_get("default", "data").in_use()
    finally:
        client.shutdown()
        server.shutdown()


def test_unpublish_failure_leaves_claim_recoverable(tmp_path):
    """Failure injection: a failing node unpublish leaves the claim in
    `taken` (volume still unschedulable for new writers); the retry on
    the next pull succeeds and the machine completes."""
    server, client, plugin = _cluster_with_fake_plugin(tmp_path,
                                                       fail_node=1)
    try:
        _terminal_claim(server, client)
        # first pull: injected failure -> claim unchanged
        client.csi_manager.reconcile_claims()
        claim = list(server.csi_volume_get(
            "default", "data").write_claims.values())[0]
        assert claim.state == "taken", "failed unpublish must not advance"
        assert plugin.node_unpublished == []

        # retry succeeds and the machine runs to completion
        client.csi_manager.reconcile_claims()     # node round
        client.csi_manager.reconcile_claims()     # controller round
        server.volume_watcher.reap_once()
        assert not server.csi_volume_get("default", "data").in_use()
        assert plugin.node_unpublished == ["data"]
    finally:
        client.shutdown()
        server.shutdown()


def test_unpublish_skips_node_round_when_node_gone(tmp_path):
    """The claimed node left the cluster: the watcher force-advances past
    the node round, but the CONTROLLER round still requires its RPC."""
    server, client, plugin = _cluster_with_fake_plugin(tmp_path)
    try:
        alloc = _terminal_claim(server, client)
        gone_node = alloc.node_id
        # re-point the claim at a node that does not exist
        vol = server.csi_volume_get("default", "data")
        server.state.csi_volume_claim(
            server.raft.barrier() + 1, "default", "data",
            CSIVolumeClaim(alloc_id=alloc.id, node_id="no-such-node",
                           mode=CLAIM_WRITE))
        server.volume_watcher.reap_once()
        claim = list(server.csi_volume_get(
            "default", "data").write_claims.values())[0]
        assert claim.state == "node-detached"
        assert plugin.node_unpublished == []      # no node RPC possible
        # controller confirmation still gates the free
        assert server.csi_volume_get("default", "data").in_use()
        client.csi_manager.reconcile_claims()
        assert plugin.controller_unpublished
        server.volume_watcher.reap_once()
        assert not server.csi_volume_get("default", "data").in_use()
    finally:
        client.shutdown()
        server.shutdown()


def test_controllerless_plugin_frees_after_node_round(tmp_path):
    """Plugins without requires_controller skip the controller round."""
    server = Server(num_workers=2, gc_interval=9999)
    server.start()
    client = Client(server, data_dir=str(tmp_path / "c0"))
    plugin = HostPathCSIPlugin(str(tmp_path / "csi"))
    client.start()
    client.register_csi_plugin("hostpath", plugin)
    try:
        assert wait_until(lambda: (
            (p := server.csi_plugin_get("hostpath")) is not None
            and p.nodes_healthy == 1))
        server.csi_volume_register([_vol("hp")])
        _terminal_claim(server, client, vol="hp")
        client.csi_manager.reconcile_claims()     # node round
        server.volume_watcher.reap_once()         # -> free, no controller
        assert not server.csi_volume_get("default", "hp").in_use()
    finally:
        client.shutdown()
        server.shutdown()


def test_normal_stop_of_controller_volume_keeps_controller_round(tmp_path):
    """The COMMON path (alloc stops, client releases) must not skip the
    controller unpublish for requires_controller plugins: unmount_all
    releases to node-detached; the claim frees only after the controller
    RPC runs."""
    server, client, plugin = _cluster_with_fake_plugin(tmp_path)
    try:
        alloc = mock.alloc()
        alloc.node_id = client.node.id
        server.state.upsert_allocs(server.raft.barrier() + 1, [alloc])

        class Req:
            name = "data"
            source = "data"
            read_only = False
        path = client.csi_manager.mount_volume(alloc, Req())
        assert os.path.islink(path)

        # alloc stops normally -> postrun unmounts + releases
        done = alloc.copy()
        done.client_status = "complete"
        done.desired_status = "stop"
        server.state.upsert_allocs(server.raft.barrier() + 1, [done])
        client.csi_manager.unmount_all(alloc)
        vol = server.csi_volume_get("default", "data")
        assert vol.in_use(), "claim must NOT free before the controller round"
        claim = list(vol.write_claims.values())[0]
        assert claim.state == "node-detached"
        # controller round completes it
        client.csi_manager.reconcile_claims()
        server.volume_watcher.reap_once()
        assert not server.csi_volume_get("default", "data").in_use()
        assert plugin.controller_unpublished
    finally:
        client.shutdown()
        server.shutdown()
