"""CSI subsystem tests (modeled on nomad/csi_endpoint_test.go,
nomad/state/state_store_test.go CSI cases, nomad/volumewatcher tests, and
client csimanager/csi_hook tests)."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.client.csimanager import HostPathCSIPlugin
from nomad_tpu.server import Server
from nomad_tpu.structs import (
    CSIVolume, CSIVolumeClaim, Node, VolumeRequest,
    ACCESS_MODE_MULTI_NODE_READER, ACCESS_MODE_SINGLE_NODE_WRITER,
    CLAIM_READ, CLAIM_STATE_READY_TO_FREE, CLAIM_WRITE,
)


def wait_until(fn, timeout=15.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


@pytest.fixture
def server():
    s = Server(num_workers=0)
    s.start()
    yield s
    s.shutdown()


def _csi_node(plugin="hostpath", healthy=True):
    node = mock.node()
    node.csi_node_plugins = {plugin: {"healthy": healthy,
                                      "provider": plugin,
                                      "provider_version": "0.1.0"}}
    return node


def _vol(vol_id="vol0", plugin="hostpath",
         access=ACCESS_MODE_SINGLE_NODE_WRITER):
    return CSIVolume(id=vol_id, name=vol_id, plugin_id=plugin,
                     access_mode=access)


def test_plugin_aggregation_from_nodes(server):
    n1, n2 = _csi_node(), _csi_node(healthy=False)
    server.node_register(n1)
    server.node_register(n2)
    plugins = server.csi_plugin_list()
    assert len(plugins) == 1
    p = plugins[0]
    assert p.id == "hostpath"
    assert len(p.nodes) == 2 and p.nodes_healthy == 1
    # node deregistration removes its contribution
    server.raft.apply("NodeDeregisterRequestType", {"node_ids": [n2.id]})
    p = server.csi_plugin_get("hostpath")
    assert len(p.nodes) == 1 and p.nodes_healthy == 1


def test_volume_register_claim_lifecycle(server):
    server.node_register(_csi_node())
    server.csi_volume_register([_vol()])
    vol = server.csi_volume_get("default", "vol0")
    assert vol.schedulable
    # write claim taken; second writer refused (single-node-writer)
    c1 = CSIVolumeClaim(alloc_id="a1", node_id="n1", mode=CLAIM_WRITE)
    server.csi_volume_claim("default", "vol0", c1)
    with pytest.raises(ValueError, match="free write claims"):
        server.csi_volume_claim("default", "vol0", CSIVolumeClaim(
            alloc_id="a2", node_id="n1", mode=CLAIM_WRITE))
    # in-use deregister refused without force
    with pytest.raises(ValueError, match="in use"):
        server.csi_volume_deregister("default", "vol0")
    # release -> free again
    server.csi_volume_claim("default", "vol0", CSIVolumeClaim(
        alloc_id="a1", state=CLAIM_STATE_READY_TO_FREE))
    vol = server.csi_volume_get("default", "vol0")
    assert not vol.in_use()
    server.csi_volume_deregister("default", "vol0")
    assert server.csi_volume_get("default", "vol0") is None


def test_multi_reader_access_mode(server):
    server.node_register(_csi_node())
    server.csi_volume_register([_vol("rvol",
                                     access=ACCESS_MODE_MULTI_NODE_READER)])
    for aid in ("a1", "a2", "a3"):
        server.csi_volume_claim("default", "rvol", CSIVolumeClaim(
            alloc_id=aid, mode=CLAIM_READ))
    vol = server.csi_volume_get("default", "rvol")
    assert len(vol.read_claims) == 3
    with pytest.raises(ValueError, match="write"):
        server.csi_volume_claim("default", "rvol", CSIVolumeClaim(
            alloc_id="w1", mode=CLAIM_WRITE))


def test_volume_unschedulable_without_healthy_plugin(server):
    server.csi_volume_register([_vol("lonely", plugin="missing")])
    vol = server.csi_volume_get("default", "lonely")
    assert not vol.schedulable
    with pytest.raises(ValueError, match="not schedulable"):
        server.csi_volume_claim("default", "lonely", CSIVolumeClaim(
            alloc_id="a1", mode=CLAIM_WRITE))


def test_volume_watcher_reaps_terminal_alloc_claims(server):
    from nomad_tpu.structs import Allocation
    server.node_register(_csi_node())
    server.csi_volume_register([_vol("reap")])
    alloc = mock.alloc()
    alloc.client_status = "complete"
    alloc.desired_status = "stop"
    server.state.upsert_allocs(server.raft.barrier() + 1, [alloc])
    server.csi_volume_claim("default", "reap", CSIVolumeClaim(
        alloc_id=alloc.id, mode=CLAIM_WRITE))
    assert server.volume_watcher.reap_once() == 1
    vol = server.csi_volume_get("default", "reap")
    assert not vol.in_use()
    # claims of live allocs survive
    live = mock.alloc()
    live.client_status = "running"
    server.state.upsert_allocs(server.raft.barrier() + 1, [live])
    server.csi_volume_claim("default", "reap", CSIVolumeClaim(
        alloc_id=live.id, mode=CLAIM_WRITE))
    assert server.volume_watcher.reap_once() == 0


def test_csi_survives_snapshot_restore(server):
    server.node_register(_csi_node())
    server.csi_volume_register([_vol("snapvol")])
    blob = server.snapshot_save()
    s2 = Server(num_workers=0)
    s2.start()
    try:
        s2.snapshot_restore(blob)
        assert s2.csi_volume_get("default", "snapvol") is not None
        assert s2.csi_plugin_get("hostpath") is not None
    finally:
        s2.shutdown()


def test_scheduler_filters_nodes_without_plugin(server):
    """CSIVolumeChecker: only nodes fingerprinting the volume's plugin are
    feasible."""
    good = _csi_node()
    bad = mock.node()
    server.node_register(good)
    server.node_register(bad)
    server.csi_volume_register([_vol("schedvol")])
    job = mock.job()
    job.id = job.name = "csijob"
    tg = job.task_groups[0]
    tg.count = 2
    tg.volumes = {"data": VolumeRequest(name="data", type="csi",
                                        source="schedvol")}
    tg.tasks[0].driver = "mock_driver"
    tg.tasks[0].resources.networks = []
    server.job_register(job)
    # run the scheduler synchronously via the harness against the server's
    # state (testing.go pattern)
    from nomad_tpu.scheduler import new_scheduler
    from nomad_tpu.scheduler.testing import Harness
    ev = server.state.evals_by_job("default", "csijob")[0]
    h = Harness(server.state.fork())
    h.process(lambda state, planner: new_scheduler(
        "service", state, planner), ev)
    assert h.plans
    placed_nodes = [nid for plan in h.plans
                    for nid, allocs in plan.node_allocation.items()
                    for _ in allocs]
    assert placed_nodes
    assert all(nid == good.id for nid in placed_nodes)


def test_end_to_end_hostpath_volume():
    """A job with a CSI volume runs against the dev agent: the hostpath
    plugin publishes the volume into the alloc dir and data persists across
    allocs."""
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=2))
    a.start()
    try:
        csi_base = os.path.join(a.config.data_dir, "csi-hostpath")
        a.client.register_csi_plugin("hostpath",
                                     HostPathCSIPlugin(csi_base))
        assert wait_until(
            lambda: (a.server.csi_plugin_get("hostpath") or
                     None) is not None
            and a.server.csi_plugin_get("hostpath").nodes_healthy == 1)
        a.server.csi_volume_register([_vol("appdata")])

        job = mock.job()
        job.id = job.name = "csirun"
        tg = job.task_groups[0]
        tg.count = 1
        tg.volumes = {"data": VolumeRequest(name="data", type="csi",
                                            source="appdata")}
        task = tg.tasks[0]
        task.driver = "raw_exec"
        task.config = {"command": "/bin/sh",
                       "args": ["-c",
                                "echo persisted > ../volumes/data/state.txt; sleep 30"]}
        task.resources.networks = []
        task.resources.cpu = 50
        task.resources.memory_mb = 32
        a.server.job_register(job)
        assert wait_until(lambda: any(
            al.client_status == "running"
            for al in a.server.state.allocs_by_job("default", "csirun")))
        alloc = [al for al in a.server.state.allocs_by_job("default", "csirun")
                 if al.client_status == "running"][0]
        # claim registered server-side
        vol = a.server.csi_volume_get("default", "appdata")
        assert alloc.id in vol.write_claims
        # the write landed in the backing hostpath volume dir
        backing = os.path.join(csi_base, "appdata", "state.txt")
        assert wait_until(lambda: os.path.exists(backing), timeout=10)
        # stop the job -> claim released by the alloc runner postrun
        a.server.job_deregister("default", "csirun")
        assert wait_until(
            lambda: not a.server.csi_volume_get("default",
                                                "appdata").in_use(),
            timeout=20)
        with open(backing) as f:
            assert f.read().strip() == "persisted"
    finally:
        a.shutdown()


def test_scheduler_rejects_claimed_single_writer_volume(server):
    """A single-node-writer volume with an existing write claim is not
    schedulable for another writer (ADVICE r1 #2; ref feasible.go
    CSIVolumeChecker + csi.go WriteFreeClaims)."""
    server.node_register(_csi_node())
    vol = _vol("busyvol")
    vol.write_claims["some-alloc"] = CSIVolumeClaim(
        alloc_id="some-alloc", node_id="n1", mode=CLAIM_WRITE)
    server.csi_volume_register([vol])
    job = mock.job()
    job.id = job.name = "busyjob"
    tg = job.task_groups[0]
    tg.volumes = {"data": VolumeRequest(name="data", type="csi",
                                        source="busyvol")}
    tg.tasks[0].driver = "mock_driver"
    tg.tasks[0].resources.networks = []
    server.job_register(job)
    from nomad_tpu.scheduler import new_scheduler
    from nomad_tpu.scheduler.testing import Harness
    ev = server.state.evals_by_job("default", "busyjob")[0]
    h = Harness(server.state.fork())
    h.process(lambda state, planner: new_scheduler(
        "service", state, planner), ev)
    placed = [a for plan in h.plans
              for allocs in plan.node_allocation.values() for a in allocs]
    assert not placed
    # a read-only request against the same volume is still feasible
    job2 = mock.job()
    job2.id = job2.name = "readjob"
    tg2 = job2.task_groups[0]
    tg2.volumes = {"data": VolumeRequest(name="data", type="csi",
                                         source="busyvol", read_only=True)}
    tg2.tasks[0].driver = "mock_driver"
    tg2.tasks[0].resources.networks = []
    server.job_register(job2)
    ev2 = server.state.evals_by_job("default", "readjob")[0]
    h2 = Harness(server.state.fork())
    h2.process(lambda state, planner: new_scheduler(
        "service", state, planner), ev2)
    placed2 = [a for plan in h2.plans
               for allocs in plan.node_allocation.values() for a in allocs]
    assert placed2
    # claims held by the scheduled job itself are exempt: a rolling update
    # or reschedule of the claim holder must still place (ref feasible.go)
    holder = mock.alloc()
    holder.id = "some-alloc"
    holder.namespace = "default"
    holder.job_id = "busyjob"
    server.state.upsert_allocs(99, [holder])
    h3 = Harness(server.state.fork())
    h3.process(lambda state, planner: new_scheduler(
        "service", state, planner), ev)
    placed3 = [a for plan in h3.plans
               for allocs in plan.node_allocation.values() for a in allocs]
    assert placed3


def test_volume_detach_releases_node_claims(server):
    """DELETE /v1/volume/csi/<id>/detach?node=N releases every claim held
    by allocs on that node (ref csi_endpoint.go CSIVolume.Unpublish +
    command/volume_detach.go)."""
    import urllib.request

    from nomad_tpu import mock
    from nomad_tpu.agent import Agent, AgentConfig
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=0))
    a.start()
    try:
        s = a.server
        node = _csi_node()
        s.node_register(node)
        s.csi_volume_register([_vol("det0")])
        job = mock.job()
        alloc = mock.alloc_for(job, node)
        s.state.upsert_job(s.state.latest_index() + 1, job)
        s.state.upsert_allocs(s.state.latest_index() + 1, [alloc])
        s.csi_volume_claim("default", "det0", CSIVolumeClaim(
            alloc_id=alloc.id, node_id=node.id, mode=CLAIM_WRITE))
        vol = s.state.csi_volume_by_id("default", "det0")
        assert alloc.id in vol.write_claims
        req = urllib.request.Request(
            a.http_addr + f"/v1/volume/csi/det0/detach?node={node.id}",
            method="DELETE")
        with urllib.request.urlopen(req, timeout=10) as resp:
            import json as _json
            out = _json.loads(resp.read())
        assert out["NumReleased"] == 1
        vol = s.state.csi_volume_by_id("default", "det0")
        # the claim is released (freed now or parked for the reaper)
        assert alloc.id not in vol.write_claims or \
            vol.write_claims[alloc.id].state != "taken"
    finally:
        a.shutdown()
