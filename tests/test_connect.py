"""Connect-analog tests (VERDICT r3 #6): admission-time sidecar injection
(ref nomad/job_endpoint_hooks.go) and the mesh data path through the
proxy driver (ref envoy_bootstrap_hook.go; data plane is the in-process
TCP proxy)."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.integrations.connect import PROXY_PREFIX, connect_admission
from nomad_tpu.structs import NetworkResource, Port, Service


def wait_until(fn, timeout=20.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def _connect_job(job_id, svc_name, port_label="http", upstreams=()):
    job = mock.job()
    job.id = job.name = job_id
    tg = job.task_groups[0]
    tg.count = 1
    tg.networks = [NetworkResource(dynamic_ports=[Port(label=port_label)])]
    tg.services = [Service(
        name=svc_name, port_label=port_label,
        connect={"SidecarService": {
            "Proxy": {"Upstreams": [
                {"DestinationName": d, "LocalBindPort": p}
                for d, p in upstreams]}}})]
    task = tg.tasks[0]
    task.driver = "raw_exec"
    task.resources.networks = []
    task.resources.cpu = 50
    task.resources.memory_mb = 32
    return job


# ------------------------------------------------------------- admission

def test_admission_injects_proxy_task_and_port():
    job = _connect_job("adm", "api-svc")
    connect_admission(job)
    tg = job.task_groups[0]
    names = [t.name for t in tg.tasks]
    assert PROXY_PREFIX + "api-svc" in names
    proxy = tg.lookup_task(PROXY_PREFIX + "api-svc")
    assert proxy.driver == "connect_proxy"
    assert proxy.lifecycle.hook == "prestart" and proxy.lifecycle.sidecar
    # dynamic ingress port added; service re-pointed at the proxy
    labels = [p.label for p in tg.networks[0].dynamic_ports]
    assert PROXY_PREFIX + "api-svc" in labels
    assert tg.services[0].port_label == PROXY_PREFIX + "api-svc"
    assert proxy.config["local_service_port_label"] == "http"


def test_admission_is_idempotent():
    job = _connect_job("idem", "api-svc")
    connect_admission(job)
    before = len(job.task_groups[0].tasks)
    connect_admission(job)          # job re-register path
    assert len(job.task_groups[0].tasks) == before
    labels = [p.label for p in job.task_groups[0].networks[0].dynamic_ports]
    assert labels.count(PROXY_PREFIX + "api-svc") == 1


def test_admission_wires_upstream_env():
    job = _connect_job("ups", "web-svc", upstreams=[("api-svc", 21105)])
    connect_admission(job)
    tg = job.task_groups[0]
    web = [t for t in tg.tasks if not t.name.startswith(PROXY_PREFIX)][0]
    assert web.env["NOMAD_UPSTREAM_ADDR_API_SVC"] == "127.0.0.1:21105"
    proxy = tg.lookup_task(PROXY_PREFIX + "web-svc")
    assert proxy.config["upstreams"] == [
        {"destination": "api-svc", "local_bind_port": 21105}]


def test_jobspec_parses_sidecar_upstreams():
    from nomad_tpu.jobspec import parse as parse_job
    hcl = '''
job "mesh" {
  group "web" {
    network { port "http" {} }
    service {
      name = "web-svc"
      port = "http"
      connect {
        sidecar_service {
          proxy {
            upstreams {
              destination_name = "api-svc"
              local_bind_port  = 21106
            }
          }
        }
      }
    }
    task "web" {
      driver = "raw_exec"
      config { command = "/bin/true" }
    }
  }
}
'''
    job = parse_job(hcl)
    svc = job.task_groups[0].services[0]
    assert svc.connect["SidecarService"]["Proxy"]["Upstreams"] == [
        {"DestinationName": "api-svc", "LocalBindPort": 21106}]


# ------------------------------------------------------------ mesh e2e

def test_two_service_connect_job_mesh_path(tmp_path):
    """The verdict's acceptance: a two-service connect job in the dev
    agent — the downstream reaches the upstream THROUGH the sidecars
    (downstream local bind -> downstream proxy -> upstream ingress proxy
    -> upstream service)."""
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=2))
    a.start()
    try:
        assert wait_until(
            lambda: a.server.state.node_by_id(a.client.node.id) is not None
            and a.server.state.node_by_id(a.client.node.id).ready())

        api = _connect_job("api", "api-svc")
        api.task_groups[0].tasks[0].config = {
            "command": "/bin/sh",
            "args": ["-c",
                     "cd local && echo hello-mesh > index.html && "
                     "exec python3 -m http.server $NOMAD_PORT_http "
                     "--bind 127.0.0.1"]}
        a.server.job_register(api)
        assert wait_until(lambda: any(
            al.client_status == "running"
            for al in a.server.state.allocs_by_job("default", "api")))
        # the catalog entry points at the PROXY ingress, not the service
        assert wait_until(lambda: bool(
            a.server.service_instances("default", "api-svc")))
        inst = a.server.service_instances("default", "api-svc")[0]
        api_alloc = [al for al in a.server.state.allocs_by_job(
            "default", "api") if al.client_status == "running"][0]
        tr = api_alloc.allocated_resources.tasks
        proxy_ports = [p.value
                       for t in tr.values() for n in t.networks
                       for p in n.dynamic_ports
                       if p.label == PROXY_PREFIX + "api-svc"]
        shared = api_alloc.allocated_resources.shared
        for n in shared.networks or []:
            proxy_ports += [p.value for p in n.dynamic_ports
                            if p.label == PROXY_PREFIX + "api-svc"]
        assert inst.port in proxy_ports, \
            "service must register at the sidecar ingress port"

        out = str(tmp_path / "mesh-out.txt")
        web = _connect_job("web", "web-svc",
                           upstreams=[("api-svc", 21107)])
        web.task_groups[0].tasks[0].config = {
            "command": "/bin/sh",
            "args": ["-c",
                     "for i in $(seq 1 100); do "
                     "python3 -c \"import urllib.request,os,sys;"
                     "addr=os.environ['NOMAD_UPSTREAM_ADDR_API_SVC'];"
                     "open('%s','w').write(urllib.request.urlopen("
                     "'http://'+addr+'/index.html',timeout=2)"
                     ".read().decode())\" && break; sleep 0.2; done; "
                     "sleep 60" % out]}
        a.server.job_register(web)
        assert wait_until(lambda: os.path.exists(out)
                          and "hello-mesh" in open(out).read(), timeout=30), \
            "downstream could not reach upstream through the sidecars"

        # the bytes actually traversed BOTH proxies
        from nomad_tpu.client.driver import ConnectProxyDriver
        proxy_driver = a.client.drivers["connect_proxy"]
        stats = [proxy_driver.inspect_task(tid)
                 for tid in list(proxy_driver._tasks)]
        assert sum(s["connections"] for s in stats) >= 2, stats
    finally:
        a.shutdown()
