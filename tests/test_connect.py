"""Connect-analog tests (VERDICT r3 #6): admission-time sidecar injection
(ref nomad/job_endpoint_hooks.go) and the mesh data path through the
proxy driver (ref envoy_bootstrap_hook.go; data plane is the in-process
TCP proxy)."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.integrations.connect import PROXY_PREFIX, connect_admission
from nomad_tpu.structs import NetworkResource, Port, Service


def wait_until(fn, timeout=20.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def _connect_job(job_id, svc_name, port_label="http", upstreams=()):
    job = mock.job()
    job.id = job.name = job_id
    tg = job.task_groups[0]
    tg.count = 1
    tg.networks = [NetworkResource(dynamic_ports=[Port(label=port_label)])]
    tg.services = [Service(
        name=svc_name, port_label=port_label,
        connect={"SidecarService": {
            "Proxy": {"Upstreams": [
                {"DestinationName": d, "LocalBindPort": p}
                for d, p in upstreams]}}})]
    task = tg.tasks[0]
    task.driver = "raw_exec"
    task.resources.networks = []
    task.resources.cpu = 50
    task.resources.memory_mb = 32
    return job


# ------------------------------------------------------------- admission

def test_admission_injects_proxy_task_and_port():
    job = _connect_job("adm", "api-svc")
    connect_admission(job)
    tg = job.task_groups[0]
    names = [t.name for t in tg.tasks]
    assert PROXY_PREFIX + "api-svc" in names
    proxy = tg.lookup_task(PROXY_PREFIX + "api-svc")
    assert proxy.driver == "connect_proxy"
    assert proxy.lifecycle.hook == "prestart" and proxy.lifecycle.sidecar
    # dynamic ingress port added; service re-pointed at the proxy
    labels = [p.label for p in tg.networks[0].dynamic_ports]
    assert PROXY_PREFIX + "api-svc" in labels
    assert tg.services[0].port_label == PROXY_PREFIX + "api-svc"
    assert proxy.config["local_service_port_label"] == "http"


def test_admission_is_idempotent():
    job = _connect_job("idem", "api-svc")
    connect_admission(job)
    before = len(job.task_groups[0].tasks)
    connect_admission(job)          # job re-register path
    assert len(job.task_groups[0].tasks) == before
    labels = [p.label for p in job.task_groups[0].networks[0].dynamic_ports]
    assert labels.count(PROXY_PREFIX + "api-svc") == 1


def test_admission_wires_upstream_env():
    job = _connect_job("ups", "web-svc", upstreams=[("api-svc", 21105)])
    connect_admission(job)
    tg = job.task_groups[0]
    web = [t for t in tg.tasks if not t.name.startswith(PROXY_PREFIX)][0]
    assert web.env["NOMAD_UPSTREAM_ADDR_API_SVC"] == "127.0.0.1:21105"
    proxy = tg.lookup_task(PROXY_PREFIX + "web-svc")
    assert proxy.config["upstreams"] == [
        {"destination": "api-svc", "local_bind_port": 21105}]


def test_jobspec_parses_sidecar_upstreams():
    from nomad_tpu.jobspec import parse as parse_job
    hcl = '''
job "mesh" {
  group "web" {
    network { port "http" {} }
    service {
      name = "web-svc"
      port = "http"
      connect {
        sidecar_service {
          proxy {
            upstreams {
              destination_name = "api-svc"
              local_bind_port  = 21106
            }
          }
        }
      }
    }
    task "web" {
      driver = "raw_exec"
      config { command = "/bin/true" }
    }
  }
}
'''
    job = parse_job(hcl)
    svc = job.task_groups[0].services[0]
    assert svc.connect["SidecarService"]["Proxy"]["Upstreams"] == [
        {"DestinationName": "api-svc", "LocalBindPort": 21106}]


# ------------------------------------------------------------ mesh e2e

def test_two_service_connect_job_mesh_path(tmp_path):
    """The verdict's acceptance: a two-service connect job in the dev
    agent — the downstream reaches the upstream THROUGH the sidecars
    (downstream local bind -> downstream proxy -> upstream ingress proxy
    -> upstream service)."""
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=2))
    a.start()
    try:
        assert wait_until(
            lambda: a.server.state.node_by_id(a.client.node.id) is not None
            and a.server.state.node_by_id(a.client.node.id).ready())

        api = _connect_job("api", "api-svc")
        api.task_groups[0].tasks[0].config = {
            "command": "/bin/sh",
            "args": ["-c",
                     "cd local && echo hello-mesh > index.html && "
                     "exec python3 -m http.server $NOMAD_PORT_http "
                     "--bind 127.0.0.1"]}
        a.server.job_register(api)
        assert wait_until(lambda: any(
            al.client_status == "running"
            for al in a.server.state.allocs_by_job("default", "api")))
        # the catalog entry points at the PROXY ingress, not the service
        assert wait_until(lambda: bool(
            a.server.service_instances("default", "api-svc")))
        inst = a.server.service_instances("default", "api-svc")[0]
        api_alloc = [al for al in a.server.state.allocs_by_job(
            "default", "api") if al.client_status == "running"][0]
        tr = api_alloc.allocated_resources.tasks
        proxy_ports = [p.value
                       for t in tr.values() for n in t.networks
                       for p in n.dynamic_ports
                       if p.label == PROXY_PREFIX + "api-svc"]
        shared = api_alloc.allocated_resources.shared
        for n in shared.networks or []:
            proxy_ports += [p.value for p in n.dynamic_ports
                            if p.label == PROXY_PREFIX + "api-svc"]
        assert inst.port in proxy_ports, \
            "service must register at the sidecar ingress port"

        out = str(tmp_path / "mesh-out.txt")
        web = _connect_job("web", "web-svc",
                           upstreams=[("api-svc", 21107)])
        web.task_groups[0].tasks[0].config = {
            "command": "/bin/sh",
            "args": ["-c",
                     "for i in $(seq 1 100); do "
                     "python3 -c \"import urllib.request,os,sys;"
                     "addr=os.environ['NOMAD_UPSTREAM_ADDR_API_SVC'];"
                     "d=urllib.request.urlopen("
                     "'http://'+addr+'/index.html',timeout=2)"
                     ".read().decode();"
                     "assert 'hello-mesh' in d;"
                     "open('%s','w').write(d)\" && break; sleep 0.2; done; "
                     "sleep 60" % out]}
        a.server.job_register(web)
        assert wait_until(lambda: os.path.exists(out)
                          and "hello-mesh" in open(out).read(), timeout=30), \
            "downstream could not reach upstream through the sidecars"

        # the bytes actually traversed BOTH proxies
        from nomad_tpu.client.driver import ConnectProxyDriver
        proxy_driver = a.client.drivers["connect_proxy"]
        stats = [proxy_driver.inspect_task(tid)
                 for tid in list(proxy_driver._tasks)]
        assert sum(s["connections"] for s in stats) >= 2, stats
    finally:
        a.shutdown()


# ------------------------------------------- intentions (mesh authz)

def test_intention_precedence_and_default_allow():
    from nomad_tpu.integrations.services import (
        ServiceIntention, intention_allowed)
    rules = [
        ServiceIntention(source="*", destination="*", action="deny"),
        ServiceIntention(source="web-svc", destination="*", action="allow"),
        ServiceIntention(source="web-svc", destination="db-svc",
                         action="deny"),
    ]
    # exact/exact outranks exact/* outranks */*
    assert not intention_allowed(rules, "default", "web-svc", "db-svc")
    assert intention_allowed(rules, "default", "web-svc", "api-svc")
    assert not intention_allowed(rules, "default", "other", "api-svc")
    # no rules at all -> default allow
    assert intention_allowed([], "default", "a", "b")
    # namespace isolation
    assert intention_allowed(rules, "team-a", "other", "api-svc")


def test_intentions_replicate_and_survive_snapshot():
    from nomad_tpu.server import Server
    from nomad_tpu.integrations.services import ServiceIntention
    s = Server(num_workers=0)
    s.start()
    try:
        s.intention_upsert(ServiceIntention(
            source="web-svc", destination="db-svc", action="deny"))
        assert not s.intention_allowed("default", "web-svc", "db-svc")
        assert s.intention_allowed("default", "web-svc", "cache-svc")
        blob = s.snapshot_save()
        s2 = Server(num_workers=0)
        s2.start()
        try:
            s2.snapshot_restore(blob)
            assert not s2.intention_allowed("default", "web-svc", "db-svc")
            assert len(s2.intention_list()) == 1
            s2.intention_delete("default", "web-svc", "db-svc")
            assert s2.intention_allowed("default", "web-svc", "db-svc")
        finally:
            s2.shutdown()
    finally:
        s.shutdown()


def test_mesh_denied_by_intention(tmp_path):
    """End to end: a deny intention makes the downstream's sidecar refuse
    the upstream connection; deleting it restores the mesh path. The
    fetch loop verifies CONTENT before accepting success, so an unrelated
    listener on a recycled port can't satisfy it."""
    from nomad_tpu.integrations.services import ServiceIntention
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=2))
    a.start()
    try:
        assert wait_until(
            lambda: a.server.state.node_by_id(a.client.node.id) is not None
            and a.server.state.node_by_id(a.client.node.id).ready())
        api = _connect_job("api2", "api-svc2")
        api.task_groups[0].tasks[0].config = {
            "command": "/bin/sh",
            "args": ["-c",
                     "cd local && echo mesh-ok > index.html && "
                     "exec python3 -m http.server $NOMAD_PORT_http "
                     "--bind 127.0.0.1"]}
        a.server.job_register(api)
        assert wait_until(lambda: bool(
            a.server.service_instances("default", "api-svc2")))

        a.server.intention_upsert(ServiceIntention(
            source="web-svc2", destination="api-svc2", action="deny"))

        out = str(tmp_path / "deny-out.txt")
        web = _connect_job("web2", "web-svc2",
                           upstreams=[("api-svc2", 21119)])
        web.task_groups[0].tasks[0].config = {
            "command": "/bin/sh",
            "args": ["-c",
                     "for i in $(seq 1 200); do "
                     "python3 -c \"import urllib.request,os;"
                     "addr=os.environ['NOMAD_UPSTREAM_ADDR_API_SVC2'];"
                     "d=urllib.request.urlopen("
                     "'http://'+addr+'/index.html',timeout=1)"
                     ".read().decode();"
                     "assert 'mesh-ok' in d;"
                     "open('%s','w').write(d)\" && break; sleep 0.2; done; "
                     "sleep 60" % out]}
        a.server.job_register(web)
        assert wait_until(lambda: any(
            al.client_status == "running"
            for al in a.server.state.allocs_by_job("default", "web2")))
        import time as _t
        _t.sleep(2.5)
        assert not os.path.exists(out), \
            "mesh connection succeeded despite a deny intention"

        # lift the intention: the retry loop gets through
        a.server.intention_delete("default", "web-svc2", "api-svc2")
        assert wait_until(lambda: os.path.exists(out)
                          and "mesh-ok" in open(out).read(), timeout=40), \
            "mesh did not recover after the intention was removed"
    finally:
        a.shutdown()


def test_intentions_http_api():
    import json
    import urllib.request
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=0,
                          client_enabled=False))
    a.start()
    try:
        def call(method, path, body=None):
            req = urllib.request.Request(a.http_addr + path,
                data=json.dumps(body).encode() if body is not None
                else None, method=method,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.loads(r.read() or "null")
        call("POST", "/v1/intentions", {"Source": "a", "Destination": "b",
                                        "Action": "deny"})
        rules = call("GET", "/v1/intentions")
        assert [(r["Source"], r["Destination"], r["Action"])
                for r in rules] == [("a", "b", "deny")]
        assert not a.server.intention_allowed("default", "a", "b")
        call("DELETE", "/v1/intention/a/b")
        assert call("GET", "/v1/intentions") == []
        assert a.server.intention_allowed("default", "a", "b")
    finally:
        a.shutdown()


# ------------------------------------------------- expose admission hook

def test_expose_admission_rewrites_check_and_proxy(tmp_path):
    """ref nomad/job_endpoint_hook_expose_check.go:21: an http check with
    expose=true gets its own dynamic listener port, the proxy task gets
    the expose config, and the check is rewritten to the listener."""
    job = _connect_job("exp", "exp-svc")
    job.task_groups[0].services[0].checks = [
        {"type": "http", "path": "/health", "expose": True,
         "interval": 1.0},
        {"type": "tcp"},                        # not exposable: untouched
    ]
    connect_admission(job)
    tg = job.task_groups[0]
    chk = tg.services[0].checks[0]
    assert chk["port_label"] == "svc_expose_check_exp-svc_0"
    labels = [p.label for p in tg.networks[0].dynamic_ports]
    assert "svc_expose_check_exp-svc_0" in labels
    proxy = tg.lookup_task(PROXY_PREFIX + "exp-svc")
    assert proxy.config["expose"] == [
        {"path": "/health",
         "listener_port_label": "svc_expose_check_exp-svc_0",
         "local_path_port_label": "http"}]
    assert "port_label" not in tg.services[0].checks[1]
    # idempotent on re-admission (job re-register)
    connect_admission(job)
    assert [p.label for p in tg.networks[0].dynamic_ports].count(
        "svc_expose_check_exp-svc_0") == 1


def test_exposed_check_serves_through_sidecar(tmp_path):
    """VERDICT r4 #6 done-when: a job with an exposed HTTP check passes
    its check THROUGH the sidecar in the dev agent — and the expose
    listener serves ONLY the check path (403 elsewhere)."""
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=2))
    a.start()
    try:
        assert wait_until(
            lambda: a.server.state.node_by_id(a.client.node.id) is not None
            and a.server.state.node_by_id(a.client.node.id).ready())
        job = _connect_job("expjob", "exp-svc")
        job.task_groups[0].services[0].checks = [
            {"type": "http", "path": "/health.txt", "expose": True,
             "interval": 0.5}]
        job.task_groups[0].tasks[0].config = {
            "command": "/bin/sh",
            "args": ["-c",
                     "cd local && echo ok > health.txt && "
                     "echo top-secret > secret.txt && "
                     "exec python3 -m http.server $NOMAD_PORT_http "
                     "--bind 127.0.0.1"]}
        a.server.job_register(job)
        assert wait_until(lambda: any(
            al.client_status == "running"
            for al in a.server.state.allocs_by_job("default", "expjob")))
        alloc = [al for al in a.server.state.allocs_by_job(
            "default", "expjob") if al.client_status == "running"][0]
        # the expose listener's allocated port
        expose_port = 0
        tr = alloc.allocated_resources.tasks
        for t in tr.values():
            for n in t.networks:
                for p in n.dynamic_ports:
                    if p.label.startswith("svc_expose_check_"):
                        expose_port = p.value
        for n in alloc.allocated_resources.shared.networks or []:
            for p in n.dynamic_ports:
                if p.label.startswith("svc_expose_check_"):
                    expose_port = p.value
        assert expose_port, "no expose port allocated"
        import http.client as hc

        def fetch(path):
            conn = hc.HTTPConnection("127.0.0.1", expose_port, timeout=3)
            conn.request("GET", path)
            r = conn.getresponse()
            body = r.read()
            conn.close()
            return r.status, body

        def check_path_up():
            try:
                return fetch("/health.txt")[0] == 200
            except OSError:
                return False
        assert wait_until(check_path_up, timeout=20), \
            "exposed check path not reachable through the sidecar"
        status, body = fetch("/health.txt")
        assert status == 200 and b"ok" in body
        # only the exposed path is served
        status, _ = fetch("/secret.txt")
        assert status == 403
        # keep-alive/pipelining cannot smuggle a second request past the
        # path filter: the listener forwards exactly ONE screened request
        # per connection (connection: close), so a pipelined follow-up
        # for the secret never reaches the service
        import socket as sk
        raw = sk.create_connection(("127.0.0.1", expose_port), timeout=3)
        raw.sendall(b"GET /health.txt HTTP/1.1\r\nhost: x\r\n\r\n"
                    b"GET /secret.txt HTTP/1.1\r\nhost: x\r\n\r\n")
        got = b""
        raw.settimeout(3)
        try:
            while True:
                chunk = raw.recv(65536)
                if not chunk:
                    break
                got += chunk
        except OSError:
            pass
        raw.close()
        assert b"top-secret" not in got, "pipelined bypass leaked"
        assert got.count(b"HTTP/1.") == 1, "second response served"
        # and the CHECK actually passes through the listener: the service
        # stays passing in the catalog
        assert wait_until(lambda: any(
            i.status == "passing"
            for i in a.server.service_instances("default", "exp-svc")),
            timeout=20)
    finally:
        a.shutdown()


def test_sidecar_gets_service_identity_token():
    """sids hook (ref taskrunner/sids_hook.go): the injected connect
    proxy task receives a service-identity token in secrets/si_token,
    scoped to the service it fronts; non-sidecar tasks cannot derive
    one."""
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=2))
    a.start()
    try:
        assert wait_until(
            lambda: a.server.state.node_by_id(a.client.node.id) is not None
            and a.server.state.node_by_id(a.client.node.id).ready())
        job = _connect_job("sids", "sids-svc")
        job.task_groups[0].tasks[0].config = {
            "command": "/bin/sh", "args": ["-c", "sleep 60"]}
        a.server.job_register(job)
        assert wait_until(lambda: any(
            al.client_status == "running"
            for al in a.server.state.allocs_by_job("default", "sids")))
        alloc = [al for al in a.server.state.allocs_by_job(
            "default", "sids") if al.client_status == "running"][0]
        from nomad_tpu.integrations.connect import PROXY_PREFIX
        tok_path = os.path.join(a.client.alloc_dir_root, alloc.id,
                                PROXY_PREFIX + "sids-svc", "secrets",
                                "si_token")
        assert wait_until(lambda: os.path.exists(tok_path), timeout=10), \
            "sidecar did not receive an SI token"
        with open(tok_path) as f:
            token = f.read().strip()
        assert token
        # the server minted it scoped to the service identity
        import pytest as _pt
        with _pt.raises(Exception):
            a.server.derive_si_token(alloc.id, "web")   # not a sidecar
    finally:
        a.shutdown()
