"""Artifact fetching + client disconnect hardening + fingerprinter tests
(VERDICT r2 next #7; ref taskrunner/artifact_hook.go,
client/heartbeatstop.go, client/fingerprint/)."""
import hashlib
import http.server
import os
import tarfile
import threading
import time
import zipfile

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client
from nomad_tpu.client.artifact import ArtifactError, fetch_artifact
from nomad_tpu.client.fingerprint import fingerprint_node
from nomad_tpu.server import Server
from nomad_tpu.structs import ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED
from nomad_tpu.structs.job import TaskArtifact

from test_client import _job, wait_until


# ------------------------------------------------------------ fetch unit

def test_fetch_local_file(tmp_path):
    src = tmp_path / "payload.bin"
    src.write_bytes(b"hello artifact")
    task_dir = tmp_path / "task"
    art = TaskArtifact(getter_source=str(src), relative_dest="local/")
    dest = fetch_artifact(art, str(task_dir))
    assert (task_dir / "local" / "payload.bin").read_bytes() == \
        b"hello artifact"
    assert os.path.normpath(dest) == str(task_dir / "local")


def test_fetch_checksum_ok_and_mismatch(tmp_path):
    src = tmp_path / "data.txt"
    src.write_bytes(b"checked content")
    digest = hashlib.sha256(b"checked content").hexdigest()
    task_dir = tmp_path / "task"
    art = TaskArtifact(getter_source=str(src),
                       getter_options={"checksum": f"sha256:{digest}"})
    fetch_artifact(art, str(task_dir))
    bad = TaskArtifact(getter_source=str(src),
                       getter_options={"checksum": "sha256:" + "0" * 64})
    with pytest.raises(ArtifactError, match="checksum mismatch"):
        fetch_artifact(bad, str(task_dir))


def test_fetch_unpacks_tarball(tmp_path):
    inner = tmp_path / "bin.sh"
    inner.write_text("#!/bin/sh\necho hi\n")
    tar_path = tmp_path / "tool.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(inner, arcname="bin.sh")
    task_dir = tmp_path / "task"
    art = TaskArtifact(getter_source=str(tar_path), relative_dest="local/")
    fetch_artifact(art, str(task_dir))
    assert (task_dir / "local" / "bin.sh").exists()
    assert not (task_dir / "local" / "tool.tar.gz").exists()  # staging gone


def test_fetch_unpacks_zip_and_blocks_escape(tmp_path):
    zpath = tmp_path / "tool.zip"
    with zipfile.ZipFile(zpath, "w") as zf:
        zf.writestr("ok.txt", "fine")
    task_dir = tmp_path / "task"
    art = TaskArtifact(getter_source=str(zpath))
    fetch_artifact(art, str(task_dir))
    assert (task_dir / "local" / "ok.txt").read_text() == "fine"

    evil = tmp_path / "evil.tar"
    with tarfile.open(evil, "w") as tf:
        info = tarfile.TarInfo("../../escape.txt")
        data = b"bad"
        info.size = len(data)
        import io
        tf.addfile(info, io.BytesIO(data))
    with pytest.raises(ArtifactError, match="escapes dest"):
        fetch_artifact(TaskArtifact(getter_source=str(evil)),
                       str(tmp_path / "task2"))


def test_fetch_http_source(tmp_path):
    payload = b"served over http"
    (tmp_path / "srv").mkdir()
    (tmp_path / "srv" / "file.dat").write_bytes(payload)

    import functools
    quiet = type("H", (http.server.SimpleHTTPRequestHandler,), {
        "log_message": lambda self, *a: None})
    handler = functools.partial(quiet, directory=str(tmp_path / "srv"))
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        port = srv.server_address[1]
        art = TaskArtifact(
            getter_source=f"http://127.0.0.1:{port}/file.dat")
        task_dir = tmp_path / "task"
        fetch_artifact(art, str(task_dir))
        assert (task_dir / "local" / "file.dat").read_bytes() == payload
    finally:
        srv.shutdown()


def test_fetch_missing_source_errors(tmp_path):
    art = TaskArtifact(getter_source=str(tmp_path / "nope.bin"))
    with pytest.raises(ArtifactError, match="not found"):
        fetch_artifact(art, str(tmp_path / "task"))


def test_fetch_rejects_destination_escape(tmp_path):
    src = tmp_path / "x.bin"
    src.write_bytes(b"x")
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    for dest in ("../outside", "local/../.."):
        art = TaskArtifact(getter_source=str(src), relative_dest=dest)
        with pytest.raises(ArtifactError, match="escapes the task dir"):
            fetch_artifact(art, str(task_dir))
    # absolute destinations are reinterpreted as task-relative, not host
    art = TaskArtifact(getter_source=str(src), relative_dest="/etc/cron.d")
    fetch_artifact(art, str(task_dir))
    assert (task_dir / "etc" / "cron.d" / "x.bin").exists()
    assert not os.path.exists("/etc/cron.d/x.bin")
    # sibling-prefix dirs must not satisfy the containment check
    (tmp_path / "task-evil").mkdir()
    art = TaskArtifact(getter_source=str(src),
                       relative_dest="../task-evil")
    with pytest.raises(ArtifactError, match="escapes the task dir"):
        fetch_artifact(art, str(task_dir))


# --------------------------------------------------- end-to-end with agent

@pytest.fixture
def cluster(tmp_path):
    server = Server(num_workers=2, gc_interval=9999)
    server.start()
    client = Client(server, data_dir=str(tmp_path / "client"))
    client.start()
    assert wait_until(
        lambda: server.state.node_by_id(client.node.id) is not None
        and server.state.node_by_id(client.node.id).ready())
    yield server, client
    client.shutdown()
    server.shutdown()


def test_job_with_artifact_runs_end_to_end(cluster, tmp_path):
    """A raw_exec job that executes a fetched script — the artifact is
    genuinely needed, so completion proves the download happened."""
    server, client = cluster
    script = tmp_path / "fetched.sh"
    script.write_text("#!/bin/sh\necho from-artifact > artifact_ran.txt\n")
    script.chmod(0o755)

    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.count = 1
    task = tg.tasks[0]
    task.driver = "raw_exec"
    task.config = {"command": "/bin/sh", "args": ["local/fetched.sh"]}
    task.artifacts = [TaskArtifact(getter_source=str(script),
                                   relative_dest="local/")]
    task.resources.networks = []
    task.resources.cpu = 100
    task.resources.memory_mb = 32
    server.job_register(job)
    assert wait_until(lambda: any(
        a.client_status == ALLOC_CLIENT_COMPLETE
        for a in server.state.allocs_by_job("default", job.id)))


def test_job_with_bad_artifact_fails_task(cluster, tmp_path):
    server, client = cluster
    job = _job(run_for=60.0, jtype="batch")
    job.task_groups[0].tasks[0].artifacts = [
        TaskArtifact(getter_source=str(tmp_path / "does-not-exist.tgz"))]
    server.job_register(job)
    assert wait_until(lambda: any(
        a.client_status == ALLOC_CLIENT_FAILED
        for a in server.state.allocs_by_job("default", job.id)))


def test_stop_after_client_disconnect_stops_alloc(cluster):
    """Client half of stop_after_client_disconnect (ref
    client/heartbeatstop.go): sever the client's RPC heartbeats and the
    opted-in alloc must be killed locally."""
    server, client = cluster
    job = _job(run_for=120.0)
    job.task_groups[0].stop_after_client_disconnect_sec = 1.0
    server.job_register(job)
    assert wait_until(lambda: client.num_allocs() == 1)
    ar = next(iter(client.alloc_runners.values()))
    assert wait_until(lambda: any(
        ts.state == "running" for ts in ar.alloc.task_states.values()))

    # sever heartbeats: fail the RPC from now on
    real = client.rpc.node_update_status
    client.rpc.node_update_status = \
        lambda *a, **k: (_ for _ in ()).throw(ConnectionError("partition"))
    client._heartbeat_ttl = 0.3
    client._last_heartbeat_ok = time.monotonic()
    try:
        assert wait_until(lambda: all(
            ts.state == "dead" for ts in ar.alloc.task_states.values()),
            timeout=15.0)
    finally:
        client.rpc.node_update_status = real


def test_alloc_without_optin_survives_disconnect(cluster):
    server, client = cluster
    job = _job(run_for=120.0)          # no stop_after_client_disconnect
    server.job_register(job)
    assert wait_until(lambda: client.num_allocs() == 1)
    ar = next(iter(client.alloc_runners.values()))
    assert wait_until(lambda: any(
        ts.state == "running" for ts in ar.alloc.task_states.values()))
    real = client.rpc.node_update_status
    client.rpc.node_update_status = \
        lambda *a, **k: (_ for _ in ()).throw(ConnectionError("partition"))
    client._heartbeat_ttl = 0.3
    client._last_heartbeat_ok = time.monotonic() - 30.0
    try:
        time.sleep(2.5)
        assert any(ts.state == "running"
                   for ts in ar.alloc.task_states.values())
    finally:
        client.rpc.node_update_status = real


# ----------------------------------------------------------- fingerprints

def test_fingerprint_node_attributes(tmp_path):
    node = fingerprint_node(data_dir=str(tmp_path))
    a = node.attributes
    for key in ("arch", "cpu.numcores", "cpu.totalcompute",
                "memory.totalbytes", "kernel.name", "nomad.version",
                "os.signals", "unique.storage.volume",
                "unique.storage.bytesfree", "unique.network.ip-address",
                "unique.network.interface"):
        assert key in a, f"missing fingerprint attribute {key}"
    assert int(a["unique.storage.bytesfree"]) > 0
    assert node.node_resources.memory.memory_mb > 0
    assert node.node_resources.cpu.cpu_shares > 0
    assert "SIGTERM" in a["os.signals"]


def test_fingerprint_cloud_env_injectable(tmp_path):
    answers = {
        "http://169.254.169.254/latest/meta-data/instance-type": "m5.large",
        "http://169.254.169.254/latest/meta-data/placement/availability-zone":
            "us-east-1a",
        "http://169.254.169.254/latest/meta-data/local-ipv4": "10.0.0.7",
    }

    def fake_get(url, headers, timeout):
        if url in answers:
            return answers[url]
        raise OSError("no metadata")

    node = fingerprint_node(data_dir=str(tmp_path),
                            cfg={"metadata_get": fake_get})
    assert node.attributes["platform"] == "aws"
    assert node.attributes["platform.aws.instance-type"] == "m5.large"


def test_fingerprint_no_cloud_is_clean(tmp_path):
    def fake_get(url, headers, timeout):
        raise OSError("air-gapped")
    node = fingerprint_node(data_dir=str(tmp_path),
                            cfg={"metadata_get": fake_get})
    assert "platform.aws.instance-type" not in node.attributes
    assert "platform.gce.machine-type" not in node.attributes


def test_fingerprint_gce_canned_metadata(tmp_path):
    base = "http://169.254.169.254/computeMetadata/v1/instance/"
    answers = {
        base + "machine-type": "projects/1/machineTypes/n2-standard-8",
        base + "zone": "projects/1/zones/us-central1-a",
        base + "hostname": "vm1.c.proj.internal",
        base + "id": "123456",
    }

    def fake_get(url, headers, timeout):
        if url in answers:
            assert headers.get("Metadata-Flavor") == "Google"
            return answers[url]
        raise OSError("404")

    node = fingerprint_node(data_dir=str(tmp_path),
                            cfg={"metadata_get": fake_get})
    assert node.attributes["platform"] == "gce"
    assert node.attributes["platform.gce.machine-type"].endswith(
        "n2-standard-8")
    assert node.attributes["unique.platform.gce.hostname"] == \
        "vm1.c.proj.internal"
    # aws attributes must not leak in
    assert not any(k.startswith("platform.aws") for k in node.attributes)


def test_fingerprint_azure_canned_metadata(tmp_path):
    base = "http://169.254.169.254/metadata/instance/compute/"
    q = "?api-version=2019-06-04&format=text"
    answers = {
        base + "vmSize" + q: "Standard_D4s_v3",
        base + "location" + q: "eastus",
        base + "name" + q: "vm-7",
        base + "vmId" + q: "abc-123",
    }

    def fake_get(url, headers, timeout):
        if url in answers:
            assert headers.get("Metadata") == "true"
            return answers[url]
        raise OSError("404")

    node = fingerprint_node(data_dir=str(tmp_path),
                            cfg={"metadata_get": fake_get})
    assert node.attributes["platform"] == "azure"
    assert node.attributes["platform.azure.compute.vm-size"] == \
        "Standard_D4s_v3"
    assert node.attributes["platform.azure.compute.location"] == "eastus"
    assert node.attributes["unique.platform.azure.compute.vm-id"] == \
        "abc-123"


def test_fingerprint_first_cloud_wins(tmp_path):
    """Only one platform is published even if several probes would
    answer (fingerprinters run in order; later clouds see the gate)."""
    def fake_get(url, headers, timeout):
        return "anything"
    node = fingerprint_node(data_dir=str(tmp_path),
                            cfg={"metadata_get": fake_get})
    assert node.attributes["platform"] == "aws"
    assert not any(k.startswith("platform.gce") for k in node.attributes)
    assert not any(k.startswith("platform.azure") for k in node.attributes)


def test_fingerprint_cni_config_dir(tmp_path):
    cni = tmp_path / "cni"
    cni.mkdir()
    (cni / "10-bridge.conflist").write_text(
        '{"name": "mynet", "cniVersion": "1.0.0", "plugins": []}')
    (cni / "ignored.txt").write_text("nope")
    (cni / "bad.conf").write_text("{not json")

    def no_cloud(url, headers, timeout):
        raise OSError("air-gapped")

    node = fingerprint_node(data_dir=str(tmp_path),
                            cfg={"metadata_get": no_cloud,
                                 "cni_config_dir": str(cni)})
    assert node.attributes["plugins.cni.network.mynet"] == "1.0.0"
