"""ACL tests (modeled on acl/acl_test.go, acl/policy_test.go, and
nomad/acl_endpoint_test.go behavioral coverage)."""
import pytest

from nomad_tpu.acl import (
    ACL, PolicyParseError, parse_acl, parse_policy,
    NS_DENY, NS_LIST_JOBS, NS_READ_JOB, NS_SUBMIT_JOB,
)


READ_POLICY = '''
namespace "default" {
  policy = "read"
}
node { policy = "read" }
'''

WRITE_POLICY = '''
namespace "default" {
  policy = "write"
}
namespace "prod-*" {
  policy       = "read"
  capabilities = ["scale-job"]
}
node     { policy = "write" }
operator { policy = "write" }
agent    { policy = "read" }
'''


def test_parse_policy_read():
    pol = parse_policy(READ_POLICY)
    assert pol.namespaces[0].name == "default"
    assert NS_READ_JOB in pol.namespaces[0].capabilities
    assert NS_SUBMIT_JOB not in pol.namespaces[0].capabilities
    assert pol.node == "read"


def test_parse_policy_invalid():
    with pytest.raises(PolicyParseError):
        parse_policy('namespace "x" { policy = "banana" }')
    with pytest.raises(PolicyParseError):
        parse_policy('namespace "x" { capabilities = ["nope"] }')
    with pytest.raises(PolicyParseError):
        parse_policy('widget { policy = "read" }')


def test_acl_checks():
    acl = parse_acl([READ_POLICY])
    assert acl.allow_namespace_operation("default", NS_READ_JOB)
    assert acl.allow_namespace_operation("default", NS_LIST_JOBS)
    assert not acl.allow_namespace_operation("default", NS_SUBMIT_JOB)
    assert not acl.allow_namespace_operation("other", NS_READ_JOB)
    assert acl.allow_node_read()
    assert not acl.allow_node_write()
    assert not acl.allow_operator_read()


def test_acl_merge_broader_wins():
    acl = parse_acl([READ_POLICY, WRITE_POLICY])
    assert acl.allow_namespace_operation("default", NS_SUBMIT_JOB)
    assert acl.allow_node_write()
    assert acl.allow_agent_read() and not acl.allow_agent_write()


def test_acl_deny_wins():
    deny = 'namespace "default" { policy = "deny" }\nnode { policy = "deny" }'
    acl = parse_acl([WRITE_POLICY, deny])
    assert not acl.allow_namespace_operation("default", NS_READ_JOB)
    assert not acl.allow_node_read()


def test_glob_namespace_most_specific():
    pol = '''
    namespace "*" { policy = "read" }
    namespace "prod-*" { policy = "deny" }
    namespace "prod-api" { policy = "write" }
    '''
    acl = parse_acl([pol])
    assert acl.allow_namespace_operation("dev", NS_READ_JOB)
    assert not acl.allow_namespace_operation("prod-web", NS_READ_JOB)
    assert acl.allow_namespace_operation("prod-api", NS_SUBMIT_JOB)


def test_management_allows_everything():
    acl = ACL(management=True)
    assert acl.allow_namespace_operation("anything", NS_SUBMIT_JOB)
    assert acl.allow_operator_write()
    assert acl.is_management()


def test_host_volume_policy():
    pol = 'host_volume "ssd-*" { policy = "write" }'
    acl = parse_acl([pol])
    assert acl.allow_host_volume_operation("ssd-1", "mount-readwrite")
    assert not acl.allow_host_volume_operation("hdd-1", "mount-readonly")


# --------------------------------------------------------- server + HTTP

@pytest.fixture(scope="module")
def acl_agent():
    from nomad_tpu.agent import Agent, AgentConfig
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=1,
                          acl_enabled=True))
    a.start()
    yield a
    a.shutdown()


def _call(agent, method, path, body=None, token=""):
    import json as _json
    import urllib.request
    import urllib.error
    url = agent.http_addr + path
    data = _json.dumps(body).encode() if body is not None else None
    headers = {"Content-Type": "application/json"}
    if token:
        headers["X-Nomad-Token"] = token
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            return resp.status, _json.loads(resp.read() or "null")
    except urllib.error.HTTPError as e:
        return e.code, _json.loads(e.read() or "{}")


def test_acl_bootstrap_and_enforcement(acl_agent):
    # anonymous requests are denied when ACLs are on
    code, _ = _call(acl_agent, "GET", "/v1/jobs")
    assert code == 403
    # bootstrap produces a management token; second bootstrap fails
    code, boot = _call(acl_agent, "POST", "/v1/acl/bootstrap")
    assert code == 200 and boot["Type"] == "management"
    root = boot["SecretID"]
    acl_agent._test_root_token = root   # for later tests in this module
    code, _ = _call(acl_agent, "POST", "/v1/acl/bootstrap")
    assert code == 403
    # management token can list jobs
    code, jobs = _call(acl_agent, "GET", "/v1/jobs", token=root)
    assert code == 200

    # create a read-only policy + client token
    code, _ = _call(acl_agent, "PUT", "/v1/acl/policy/readonly",
                    {"Rules": READ_POLICY}, token=root)
    assert code == 200
    code, tok = _call(acl_agent, "PUT", "/v1/acl/token",
                      {"Name": "ro", "Type": "client",
                       "Policies": ["readonly"]}, token=root)
    assert code == 200
    ro = tok["SecretID"]

    # read-only token: list ok, submit denied, node read ok, drain denied
    code, _ = _call(acl_agent, "GET", "/v1/jobs", token=ro)
    assert code == 200
    from nomad_tpu import mock
    from nomad_tpu.api_codec import to_api
    job = mock.job()
    code, _ = _call(acl_agent, "PUT", "/v1/jobs", {"Job": to_api(job)},
                    token=ro)
    assert code == 403
    code, _ = _call(acl_agent, "GET", "/v1/nodes", token=ro)
    assert code == 200
    code, _ = _call(acl_agent, "GET", "/v1/operator/scheduler/configuration",
                    token=ro)
    assert code == 403
    # bogus token 403s
    code, _ = _call(acl_agent, "GET", "/v1/jobs", token="bogus-secret")
    assert code == 403
    # token self
    code, me = _call(acl_agent, "GET", "/v1/acl/token/self", token=ro)
    assert code == 200 and me["Name"] == "ro"
    # management can submit
    code, _ = _call(acl_agent, "PUT", "/v1/jobs", {"Job": to_api(job)},
                    token=root)
    assert code == 200


def test_namespace_crud(acl_agent):
    root = acl_agent._test_root_token
    # anonymous token listing denied
    code, _ = _call(acl_agent, "GET", "/v1/acl/tokens")
    assert code == 403
    code, toks = _call(acl_agent, "GET", "/v1/acl/tokens", token=root)
    assert code == 200 and len(toks) >= 2
    # namespace CRUD requires management
    code, _ = _call(acl_agent, "PUT", "/v1/namespace/team-a",
                    {"Description": "team A"})
    assert code == 403
    code, _ = _call(acl_agent, "PUT", "/v1/namespace/team-a",
                    {"Description": "team A"}, token=root)
    assert code == 200
    code, nss = _call(acl_agent, "GET", "/v1/namespaces", token=root)
    assert code == 200 and any(n["Name"] == "team-a" for n in nss)
    code, _ = _call(acl_agent, "DELETE", "/v1/namespace/default", token=root)
    assert code == 400   # default not deletable
    code, _ = _call(acl_agent, "DELETE", "/v1/namespace/team-a", token=root)
    assert code == 200


def test_acl_snapshot_restore_roundtrip(acl_agent):
    """ACL tables survive FSM snapshot/restore (checkpoint/resume)."""
    from nomad_tpu.server.fsm import NomadFSM
    blob = acl_agent.server.fsm.snapshot_bytes()
    fresh = NomadFSM()
    fresh.restore_bytes(blob)
    toks = fresh.state.iter_acl_tokens()
    assert any(t.type == "management" for t in toks)
    pol = fresh.state.acl_policy_by_name("readonly")
    assert pol is not None and "namespace" in pol.rules
    # secret index rebuilt
    root = acl_agent._test_root_token
    assert fresh.state.acl_token_by_secret(root) is not None


def test_monitor_fails_closed_on_client_only_agent(tmp_path):
    """/v1/agent/monitor must not leak live logs on a client-only agent
    with ACLs enabled: no server means no token resolution, so fail
    closed with 501 like the other client endpoints (ADVICE r1 #1;
    ref command/agent/agent_endpoint.go requires agent:read)."""
    from nomad_tpu.agent import Agent, AgentConfig

    server_agent = Agent(AgentConfig(
        data_dir=str(tmp_path / "server"), http_port=0, rpc_port=0,
        client_enabled=False))
    server_agent.start()
    try:
        rpc_addr = server_agent.server.rpc_addr
        client_agent = Agent(AgentConfig(
            data_dir=str(tmp_path / "client"), http_port=0,
            server_enabled=False, servers=(rpc_addr,),
            acl_enabled=True, node_name="mon-node"))
        client_agent.start()
        try:
            code, _ = _call(client_agent, "GET", "/v1/agent/monitor")
            assert code == 501
        finally:
            client_agent.shutdown()
    finally:
        server_agent.shutdown()
