"""Jobspec parser + job diff tests (modeled on jobspec2/parse_test.go and
structs/diff_test.go behavioral coverage)."""
import pytest

from nomad_tpu.jobspec import ParseError, duration, parse
from nomad_tpu.structs import Job, Task, TaskGroup
from nomad_tpu.structs.diff import job_diff


BASIC = '''
job "web" {
  datacenters = ["dc1"]
  type        = "service"

  group "frontend" {
    count = 2
    task "server" {
      driver = "mock"
      config {
        run_for = "10s"
      }
      resources {
        cpu    = 250
        memory = 128
      }
    }
  }
}
'''


def test_parse_basic():
    job = parse(BASIC)
    assert job.id == "web" and job.name == "web"
    assert job.type == "service"
    tg = job.task_groups[0]
    assert tg.name == "frontend" and tg.count == 2
    t = tg.tasks[0]
    assert t.driver == "mock"
    assert t.config["run_for"] == "10s"
    assert t.resources.cpu == 250 and t.resources.memory_mb == 128


def test_duration_parsing():
    assert duration("30s") == 30.0
    assert duration("1h30m") == 5400.0
    assert duration("250ms") == 0.25
    assert duration("2d") == 172800.0
    assert duration(15) == 15.0
    with pytest.raises(ParseError):
        duration("bogus")


def test_variables_and_locals():
    src = '''
    variable "count" {
      type    = number
      default = 3
    }
    variable "prefix" { default = "app" }
    locals {
      full = "${var.prefix}-prod"
    }
    job "x" {
      group "${local.full}" {
        count = var.count * 2
        task "t" { driver = "mock" }
      }
    }
    '''
    # interpolation not allowed in labels; group name via label is literal —
    # use attributes instead
    src = src.replace('group "${local.full}"', 'group "g"')
    job = parse(src, {"count": "5"})
    assert job.task_groups[0].count == 10


def test_missing_required_variable():
    src = '''
    variable "req" { type = string }
    job "x" { group "g" { task "t" { driver = "mock" } } }
    '''
    with pytest.raises(ParseError, match="missing required variable"):
        parse(src)
    job = parse(src, {"req": "ok"})
    assert job.id == "x"


def test_undeclared_variable_override_rejected():
    with pytest.raises(ParseError, match="undeclared"):
        parse(BASIC, {"nope": "1"})


def test_runtime_interpolation_preserved():
    src = '''
    job "x" {
      constraint {
        attribute = "${attr.kernel.name}"
        value     = "linux"
      }
      group "g" {
        task "t" {
          driver = "mock"
          env {
            ADDR = "${NOMAD_ADDR_http}"
            HOST = "${node.unique.name}"
          }
        }
      }
    }
    '''
    job = parse(src)
    assert job.constraints[0].ltarget == "${attr.kernel.name}"
    env = job.task_groups[0].tasks[0].env
    assert env["ADDR"] == "${NOMAD_ADDR_http}"
    assert env["HOST"] == "${node.unique.name}"


def test_functions_and_expressions():
    src = '''
    job "x" {
      meta {
        a = join(",", ["x", "y"])
        b = format("%s-%d", upper("web"), 1 + 2)
        c = "${3 > 2 ? "yes" : "no"}"
        d = jsonencode({k = 1})
      }
      group "g" { task "t" { driver = "mock" } }
    }
    '''
    job = parse(src)
    assert job.meta["a"] == "x,y"
    assert job.meta["b"] == "WEB-3"
    assert job.meta["c"] == "yes"
    assert job.meta["d"] == '{"k": 1}'


def test_heredoc_template():
    src = '''
    job "x" {
      group "g" {
        task "t" {
          driver = "mock"
          template {
            data        = <<EOF
line one
line two
EOF
            destination = "local/out.txt"
          }
        }
      }
    }
    '''
    job = parse(src)
    tmpl = job.task_groups[0].tasks[0].templates[0]
    assert tmpl.embedded_tmpl == "line one\nline two\n"
    assert tmpl.dest_path == "local/out.txt"


def test_constraint_sugar_forms():
    src = '''
    job "x" {
      constraint {
        attribute = "${attr.driver.mock}"
        operator  = "is_set"
      }
      group "g" {
        constraint {
          distinct_hosts = true
        }
        task "t" { driver = "mock" }
      }
    }
    '''
    job = parse(src)
    assert job.constraints[0].operand == "is_set"
    assert job.task_groups[0].constraints[0].operand == "distinct_hosts"


def test_periodic_and_parameterized():
    src = '''
    job "cron" {
      type = "batch"
      periodic {
        cron             = "*/15 * * * *"
        prohibit_overlap = true
      }
      group "g" { task "t" { driver = "mock" } }
    }
    '''
    job = parse(src)
    assert job.periodic.spec == "*/15 * * * *"
    assert job.periodic.prohibit_overlap

    src2 = '''
    job "param" {
      type = "batch"
      parameterized {
        payload       = "required"
        meta_required = ["k"]
      }
      group "g" { task "t" { driver = "mock" } }
    }
    '''
    job2 = parse(src2)
    assert job2.parameterized.payload == "required"
    assert job2.parameterized.meta_required == ["k"]


def test_parse_errors():
    with pytest.raises(ParseError):
        parse("job \"x\" {")           # unterminated block
    with pytest.raises(ParseError):
        parse("nothing_here = 1")      # no job block
    with pytest.raises(ParseError):
        parse('job "a" {} job "b" {}')  # two jobs


# ------------------------------------------------------------------- diff

def _mk(count=2, cpu=100):
    return Job(id="j", name="j", task_groups=[
        TaskGroup(name="g", count=count,
                  tasks=[Task(name="t", driver="mock")])])


def test_job_diff_none():
    d = job_diff(_mk(), _mk())
    assert d["Type"] == "None"


def test_job_diff_edited_count():
    d = job_diff(_mk(count=2), _mk(count=5))
    assert d["Type"] == "Edited"
    tg = d["TaskGroups"][0]
    assert tg["Type"] == "Edited"
    counts = [f for f in tg["Fields"] if f["Name"] == "Count"]
    assert counts and counts[0]["Old"] == "2" and counts[0]["New"] == "5"


def test_job_diff_added_group():
    new = _mk()
    new.task_groups.append(TaskGroup(name="extra", count=1,
                                     tasks=[Task(name="t2", driver="mock")]))
    d = job_diff(_mk(), new)
    added = [g for g in d["TaskGroups"] if g["Name"] == "extra"]
    assert added and added[0]["Type"] == "Added"


def test_job_diff_new_job():
    d = job_diff(None, _mk())
    assert d["Type"] == "Added"
    d2 = job_diff(_mk(), None)
    assert d2["Type"] == "Deleted"


def test_job_diff_contextual_includes_unchanged():
    """ref structs/diff.go contextual=true: unchanged fields ride along
    as Type None so `plan -verbose` can show the full object."""
    d = job_diff(_mk(count=2), _mk(count=5), contextual=True)
    assert d["Type"] == "Edited"
    tg = d["TaskGroups"][0]
    by_name = {f["Name"]: f for f in tg["Fields"]}
    assert by_name["Count"]["Type"] == "Edited"
    # the unchanged group name appears as context
    assert by_name["Name"]["Type"] == "None"
    assert by_name["Name"]["Old"] == by_name["Name"]["New"] == "g"
    # unchanged tasks appear with Type None too
    assert tg["Tasks"] and tg["Tasks"][0]["Type"] == "None"


def test_job_diff_contextual_unchanged_job_stays_none():
    d = job_diff(_mk(), _mk(), contextual=True)
    assert d["Type"] == "None"
    # groups present as context but not marked changed
    assert d["TaskGroups"] and all(
        g["Type"] == "None" for g in d["TaskGroups"])


def test_distinct_property_sugar():
    src = '''
    job "x" {
      group "g" {
        constraint {
          distinct_property = "${meta.rack}"
          value             = "2"
        }
        task "t" { driver = "mock" }
      }
    }
    '''
    c = parse(src).task_groups[0].constraints[0]
    assert c.operand == "distinct_property"
    assert c.ltarget == "${meta.rack}"
    assert c.rtarget == "2"


def test_distinct_hosts_false_skipped():
    src = '''
    job "x" {
      group "g" {
        constraint {
          distinct_hosts = false
        }
        task "t" { driver = "mock" }
      }
    }
    '''
    assert parse(src).task_groups[0].constraints == []


def test_bool_constraint_value_renders_hcl_style():
    src = '''
    job "x" {
      constraint {
        attribute = "${attr.driver.docker}"
        value     = true
      }
      group "g" { task "t" { driver = "mock" } }
    }
    '''
    assert parse(src).constraints[0].rtarget == "true"


def test_variable_without_default_is_required():
    src = '''
    variable "image" {}
    job "x" { group "g" { task "t" { driver = "mock" } } }
    '''
    with pytest.raises(ParseError, match="missing required variable"):
        parse(src)
    assert parse(src, {"image": "i"}).id == "x"


def test_job_diff_nested_network_service_granularity():
    """VERDICT r4 #9 (ref structs/diff.go nested object diffs): editing
    an identity-less network/check renders as ONE Edited object with
    field-level deltas — similarity pairing — not a Deleted+Added pair;
    keyed children (ports by Label) still diff by identity."""
    import copy

    from nomad_tpu.structs import NetworkResource, Port, Service
    old = _mk()
    tg = old.task_groups[0]
    tg.networks = [NetworkResource(dynamic_ports=[Port(label="http")],
                                   mbits=10)]
    tg.services = [Service(name="web", port_label="http",
                           checks=[{"type": "http", "path": "/a",
                                    "interval": 10}])]
    new = copy.deepcopy(old)
    new.task_groups[0].networks[0].mbits = 20
    new.task_groups[0].networks[0].dynamic_ports.append(
        Port(label="admin"))
    new.task_groups[0].services[0].checks[0]["path"] = "/b"
    d = job_diff(old, new)
    objs = {o["Name"]: o for o in d["TaskGroups"][0]["Objects"]}
    net = objs["Networks"]
    assert net["Type"] == "Edited"
    mbits = [f for f in net["Fields"] if f["Name"] == "Mbits"]
    assert mbits == [{"Type": "Edited", "Name": "Mbits",
                      "Old": "10", "New": "20"}]
    ports = [o for o in net["Objects"] if o["Name"] == "DynamicPorts"]
    assert [p["Type"] for p in ports] == ["Added"]        # just `admin`
    svc = objs["Services"]
    checks = [o for o in svc["Objects"] if o["Name"] == "Checks"]
    assert len(checks) == 1 and checks[0]["Type"] == "Edited"
    path = [f for f in checks[0]["Fields"] if f["Name"] == "path"]
    assert path == [{"Type": "Edited", "Name": "path",
                     "Old": "/a", "New": "/b"}]


def test_job_diff_dissimilar_objects_stay_added_deleted():
    """A genuinely replaced object (similarity < 0.5) still renders as
    Deleted + Added, not a nonsense merged edit."""
    import copy

    from nomad_tpu.structs import Service
    old = _mk()
    old.task_groups[0].services = [Service(
        name="alpha", port_label="http", tags=["a", "b"])]
    new = copy.deepcopy(old)
    new.task_groups[0].services = [Service(
        name="omega", port_label="grpc", tags=["x"],
        checks=[{"type": "tcp"}])]
    d = job_diff(old, new)
    svcs = [o for o in d["TaskGroups"][0]["Objects"]
            if o["Name"] == "Services"]
    assert sorted(s["Type"] for s in svcs) == ["Added", "Deleted"]


def test_job_diff_renamed_identity_object_is_destroy_create():
    """A RENAMED service (identity-keyed) must render Deleted+Added like
    the reference's keyed diffs — similarity pairing applies only to
    identity-less objects (a rename is a destroy+create of the
    registered instance, and an in-place edit would hide that)."""
    import copy

    from nomad_tpu.structs import Service
    old = _mk()
    old.task_groups[0].services = [Service(
        name="alpha", port_label="http", tags=["a"])]
    new = copy.deepcopy(old)
    new.task_groups[0].services[0].name = "beta"
    d = job_diff(old, new)
    svcs = [o for o in d["TaskGroups"][0]["Objects"]
            if o["Name"] == "Services"]
    assert sorted(s["Type"] for s in svcs) == ["Added", "Deleted"]
