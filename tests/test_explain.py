"""Placement explainability (ISSUE 11): the tensor path's elimination
attribution must be bit-consistent with the host iterator stack.

The oracle is a FRESH GenericStack select over the identical (post-eval)
cluster state: a failing select walks every candidate through
FeasibilityWrapper -> DistinctHosts -> BinPack exactly once with fresh
per-class caches — the same first-walk semantics the tensor path's
single per-(eval, TG) lowering has — so every AllocMetric count
(nodes evaluated / filtered with reasons / per-class / exhausted per
dimension) must match EXACTLY, not approximately.

Also pinned here: placements are bit-identical with explain on vs off,
the sharded tier's psum reduce matches the solo reduce bit-for-bit
(kernel-level AND end-to-end on the tier-1 virtual 8-device mesh), the
winning rows' score metadata lands on placed allocs, and the operator
debug bundle (endpoint + CLI archive) is capturable on a live dev agent.
"""
import json
import random
import tarfile

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.metrics import metrics
from nomad_tpu.scheduler import new_scheduler
from nomad_tpu.solver import backend, explain, microbatch, state_cache
from nomad_tpu.structs import (
    Constraint, Evaluation, OP_DISTINCT_HOSTS, SchedulerConfiguration,
    SCHED_ALG_TPU,
)

from test_solver import Harness


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("NOMAD_EXPLAIN", raising=False)
    monkeypatch.delenv("NOMAD_SOLVER_BACKEND", raising=False)
    backend.reset()
    state_cache.reset()
    microbatch.reset()
    explain.configure(enabled=None)
    explain.reset()
    yield
    backend.reset()
    state_cache.reset()
    microbatch.reset()
    explain.configure(enabled=None)
    explain.reset()


# ------------------------------------------------------------- scenarios

def build_and_run(algorithm, seed, n_nodes, count, ask_cpu, ask_mem, *,
                  constraint=False, distinct_hosts=False, hetero=False,
                  node_class=False, eval_id=None):
    """One seeded cluster + batch job through the full scheduler path,
    with pinned eval id so identical inputs replay bit-identically."""
    random.seed(seed)
    rng = np.random.default_rng(seed)
    h = Harness()
    h.state.set_scheduler_config(
        h.get_next_index(),
        SchedulerConfiguration(scheduler_algorithm=algorithm))
    for i in range(n_nodes):
        n = mock.node()
        if hetero:
            n.node_resources.cpu.cpu_shares = int(
                rng.choice([4000, 16000]))
            n.node_resources.memory.memory_mb = int(
                rng.choice([8192, 65536]))
        rack = "r1" if rng.random() < 0.5 else "r2"
        n.attributes["custom.rack"] = rack
        if node_class:
            n.node_class = f"class-{rack}"
        n.compute_class()
        h.state.upsert_node(h.get_next_index(), n)
    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.count = count
    tg.networks = []
    task = tg.tasks[0]
    task.resources.cpu = ask_cpu
    task.resources.memory_mb = ask_mem
    task.resources.networks = []
    if constraint:
        tg.constraints = list(tg.constraints) + [Constraint(
            ltarget="${attr.custom.rack}", rtarget="r1", operand="=")]
    if distinct_hosts:
        tg.constraints = list(tg.constraints) + [Constraint(
            operand=OP_DISTINCT_HOSTS)]
    h.state.upsert_job(h.get_next_index(), job)
    ev = Evaluation(id=eval_id or f"explain-ev-{seed}", job_id=job.id,
                    type=job.type)
    h.process(lambda s, p: new_scheduler(job.type, s, p), ev)
    return h, job, tg


def oracle_failed_metric(h, job, tg):
    """The iterator-stack oracle: one fresh GenericStack select over the
    harness's (post-eval) state. A failing select exhausts the source,
    so ctx.metrics afterwards is the host stack's full attribution."""
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.stack import GenericStack, SelectOptions
    from nomad_tpu.scheduler.util import ready_nodes_in_dcs
    snap = h.state.snapshot()
    ctx = EvalContext(snap)
    stack = GenericStack(True, ctx, rng=random.Random(0))
    ready, by_dc = ready_nodes_in_dcs(snap, job.datacenters)
    stack.set_nodes(ready)
    stack.set_job(job)
    option = stack.select(tg, SelectOptions())
    assert option is None, "oracle unexpectedly placed — bad scenario"
    m = ctx.metrics
    m.nodes_available = by_dc
    return m


def assert_metric_parity(tensor_m, oracle_m):
    """Field-exact equality on everything the host stack can attribute.
    (score_meta is tensor-path-extra: the host records no score metadata
    on a failed placement.)"""
    assert tensor_m.nodes_evaluated == oracle_m.nodes_evaluated
    assert tensor_m.nodes_filtered == oracle_m.nodes_filtered
    assert dict(tensor_m.constraint_filtered) == \
        dict(oracle_m.constraint_filtered)
    assert dict(tensor_m.class_filtered) == dict(oracle_m.class_filtered)
    assert tensor_m.nodes_exhausted == oracle_m.nodes_exhausted
    assert dict(tensor_m.dimension_exhausted) == \
        dict(oracle_m.dimension_exhausted)
    assert dict(tensor_m.class_exhausted) == \
        dict(oracle_m.class_exhausted)
    assert dict(tensor_m.nodes_available) == dict(oracle_m.nodes_available)


def _failed(h, tg):
    ev = h.evals[-1]
    assert tg.name in ev.failed_tg_allocs, \
        f"expected a failed placement for {tg.name}"
    return ev.failed_tg_allocs[tg.name]


# -------------------------------------------------- rejection attribution

def test_rejected_eval_reports_full_attribution():
    """The acceptance surface: a rejected eval on the tensor path says
    WHY — nodes evaluated, per-dimension exhaustion, blocked eval carries
    the same metric."""
    h, job, tg = build_and_run(SCHED_ALG_TPU, 3, n_nodes=4, count=5,
                               ask_cpu=9000, ask_mem=64)
    m = _failed(h, tg)
    assert m.nodes_evaluated == 4
    assert m.nodes_exhausted == 4
    assert m.dimension_exhausted == {"cpu": 4}
    # the blocked eval the scheduler queued carries the same attribution
    blocked = [e for e in h.created_evals if e.status == "blocked"]
    assert blocked and tg.name in blocked[0].failed_tg_allocs
    assert blocked[0].failed_tg_allocs[tg.name].dimension_exhausted == \
        {"cpu": 4}
    # and the ring retained a rejected record for the debug bundle
    recent = explain.recent(8)
    assert any(r["rejected"] and r["dim_exhausted"] == {"cpu": 4}
               for r in recent)


def test_memory_binding_dimension_attributed():
    h, job, tg = build_and_run(SCHED_ALG_TPU, 4, n_nodes=3, count=2,
                               ask_cpu=100, ask_mem=32768)
    m = _failed(h, tg)
    assert m.dimension_exhausted == {"memory": 3}
    assert_metric_parity(m, oracle_failed_metric(h, job, tg))


# ------------------------------------------------------ oracle parity fuzz

@pytest.mark.parametrize("seed", [1, 5, 9, 13])
def test_parity_fuzz_greedy_regime_constraints(seed):
    """count=1 rejections through the greedy kernel with irregular
    constraint filtering: concrete first-in-class reasons + cached
    'computed class ineligible' repeats must match the wrapper's."""
    h, job, tg = build_and_run(
        SCHED_ALG_TPU, seed, n_nodes=6 + seed % 5, count=1,
        ask_cpu=20000, ask_mem=64, constraint=True, hetero=True,
        node_class=True)
    assert_metric_parity(_failed(h, tg), oracle_failed_metric(h, job, tg))


@pytest.mark.parametrize("seed", [2, 6, 10])
def test_parity_fuzz_jittered_depth_regime(seed):
    """count in (1, n]: the sampled-grid jittered depth regime (m <= 3).
    Pure exhaustion rejections, heterogeneous binding dimensions."""
    h, job, tg = build_and_run(
        SCHED_ALG_TPU, seed, n_nodes=16, count=2,
        ask_cpu=20000, ask_mem=70000, hetero=True, node_class=True)
    m = _failed(h, tg)
    assert m.nodes_exhausted == 16
    assert sum(m.dimension_exhausted.values()) == 16
    assert_metric_parity(m, oracle_failed_metric(h, job, tg))


@pytest.mark.parametrize("seed", [3, 7, 11])
def test_parity_fuzz_deterministic_depth_regime_partial_placement(seed):
    """count >> capacity: the deterministic full-curve regime (m > 3)
    places what fits, the remainder is rejected — attribution describes
    the POST-solve state, exactly what a host re-walk over the committed
    cluster reports."""
    h, job, tg = build_and_run(
        SCHED_ALG_TPU, seed, n_nodes=4, count=24,
        ask_cpu=1900, ask_mem=512)
    allocs = h.state.allocs_by_job("default", job.id)
    assert 0 < len(allocs) < 24          # partially placed, rest failed
    m = _failed(h, tg)
    assert m.nodes_exhausted == 4
    assert_metric_parity(m, oracle_failed_metric(h, job, tg))


def test_parity_distinct_hosts_post_solve_filtering():
    """distinct_hosts with count > nodes: one instance lands per node,
    the remainder's rejection attributes every node to the
    distinct_hosts filter — exactly what DistinctHostsIterator reports
    on the committed cluster."""
    h, job, tg = build_and_run(
        SCHED_ALG_TPU, 8, n_nodes=6, count=9,
        ask_cpu=100, ask_mem=64, distinct_hosts=True)
    allocs = h.state.allocs_by_job("default", job.id)
    assert len(allocs) == 6
    assert len({a.node_id for a in allocs}) == 6
    m = _failed(h, tg)
    assert m.constraint_filtered.get(OP_DISTINCT_HOSTS) == 6
    assert m.nodes_exhausted == 0
    assert_metric_parity(m, oracle_failed_metric(h, job, tg))


# ------------------------------------------------------------ bit identity

def test_placements_bit_identical_explain_on_off():
    """Explain is a pure byproduct: same seed, same eval id, explain on
    vs off — identical committed placements and identical usage rows."""

    def run(enabled: bool):
        explain.configure(enabled=enabled)
        backend.reset()
        state_cache.reset()
        h, job, tg = build_and_run(SCHED_ALG_TPU, 21, n_nodes=6,
                                   count=10, ask_cpu=700, ask_mem=256,
                                   hetero=True, eval_id="bitid-ev")
        # node/job ids are fresh uuids per run: compare by the usage
        # index's stable insertion-order row + the instance index
        rows = h.state.usage.row
        allocs = h.state.allocs_by_job("default", job.id)
        placed = sorted((rows[a.node_id], a.name.rsplit(".", 1)[-1])
                        for a in allocs)
        usage = h.state.usage.used.tobytes()
        return placed, usage

    on_placed, on_usage = run(True)
    off_placed, off_usage = run(False)
    assert on_placed == off_placed
    assert on_usage == off_usage
    assert len(on_placed) == 10


def test_rejection_bit_identical_explain_on_off():
    def run(enabled: bool):
        explain.configure(enabled=enabled)
        backend.reset()
        state_cache.reset()
        h, job, tg = build_and_run(SCHED_ALG_TPU, 22, n_nodes=5,
                                   count=8, ask_cpu=1500, ask_mem=512,
                                   eval_id="bitid-rej-ev")
        rows = h.state.usage.row
        allocs = h.state.allocs_by_job("default", job.id)
        return sorted((rows[a.node_id], a.name.rsplit(".", 1)[-1])
                      for a in allocs)

    assert run(True) == run(False)


def test_env_kill_switch_disables_records(monkeypatch):
    monkeypatch.setenv("NOMAD_EXPLAIN", "0")
    h, job, tg = build_and_run(SCHED_ALG_TPU, 23, n_nodes=3, count=2,
                               ask_cpu=9000, ask_mem=64)
    assert explain.recent(8) == []
    # the rejection still carries the host fallback's own metric
    m = _failed(h, tg)
    assert m.nodes_evaluated == 3


# -------------------------------------------------------- placed metadata

def test_placed_allocs_carry_score_metadata():
    """`alloc status` explainability: placed allocs share a metrics
    object carrying nodes-evaluated and the winning rows' binpack
    scores from the device solve."""
    h, job, tg = build_and_run(SCHED_ALG_TPU, 31, n_nodes=5, count=4,
                               ask_cpu=300, ask_mem=128)
    allocs = h.state.allocs_by_job("default", job.id)
    assert len(allocs) == 4
    m = allocs[0].metrics
    assert m.nodes_evaluated == 5
    assert m.score_meta, "winning-row score metadata missing"
    assert all(0.0 <= sm["normalized_score"] <= 1.0 for sm in m.score_meta)
    placed_nodes = {a.node_id for a in allocs}
    assert {sm["node_id"] for sm in m.score_meta} <= placed_nodes
    assert m.scores             # node_id.binpack -> score


def test_placed_allocs_keep_filter_attribution():
    """With explain on, the irregular walk's filter counts are diverted
    into the scratch metric — they must still reach the metrics object
    stamped onto PLACED allocs (the pre-explain `alloc status` surface
    showed them; a default-on feature must not lose them)."""
    h, job, tg = build_and_run(SCHED_ALG_TPU, 33, n_nodes=8, count=2,
                               ask_cpu=100, ask_mem=64, constraint=True)
    allocs = h.state.allocs_by_job("default", job.id)
    assert len(allocs) == 2     # r1 nodes exist and fit
    m = allocs[0].metrics
    assert m.nodes_filtered > 0
    assert any("custom.rack" in r for r in m.constraint_filtered), \
        m.constraint_filtered


def test_preemption_candidacy_recorded():
    """Stage-5 observability: the batched preemption pass actually runs
    (low-priority victims occupy every node, preemption enabled for
    batch) and the record counts candidates / viable victim sets /
    rescued placements."""
    from nomad_tpu.structs import PreemptionConfig
    random.seed(77)
    h = Harness()
    h.state.set_scheduler_config(
        h.get_next_index(),
        SchedulerConfiguration(
            scheduler_algorithm=SCHED_ALG_TPU,
            preemption_config=PreemptionConfig(
                batch_scheduler_enabled=True)))
    for _ in range(3):
        h.state.upsert_node(h.get_next_index(), mock.node())

    def _job(priority, count, cpu):
        job = mock.batch_job()
        job.priority = priority
        tg = job.task_groups[0]
        tg.count = count
        tg.networks = []
        task = tg.tasks[0]
        task.resources.cpu = cpu
        task.resources.memory_mb = 128
        task.resources.networks = []
        return job, tg

    low, _ = _job(1, 3, 3000)
    h.state.upsert_job(h.get_next_index(), low)
    h.process(lambda s, p: new_scheduler(low.type, s, p),
              Evaluation(id="preempt-low-ev", job_id=low.id,
                         type=low.type))
    assert len(h.state.allocs_by_job("default", low.id)) == 3

    high, tg_h = _job(50, 2, 3000)
    h.state.upsert_job(h.get_next_index(), high)
    h.process(lambda s, p: new_scheduler(high.type, s, p),
              Evaluation(id="preempt-high-ev", job_id=high.id,
                         type=high.type))
    rec = [r for r in explain.recent(8)
           if r["eval_id"] == "preempt-high-ev" and r["tg"] == tg_h.name]
    assert rec, "no explain record for the preempting eval"
    p = rec[0]["preempt"]
    assert p["candidates"] == 3
    assert p["with_victims"] >= 1
    assert p["placed"] >= 1


# --------------------------------------------------------- sharded parity

def _reduce_args(seed=0, n=16, n_classes=4):
    rng = np.random.default_rng(seed)
    from nomad_tpu.solver.kernels import NUM_XR
    cap = np.zeros((n, NUM_XR), np.float32)
    cap[:, 0] = rng.choice([2000.0, 4000.0], n)
    cap[:, 1] = rng.choice([4096.0, 8192.0], n)
    cap[:, 2] = 50_000.0
    used = (cap * rng.uniform(0.0, 0.9, (n, NUM_XR))).astype(np.float32)
    ask = np.zeros(NUM_XR, np.float32)
    ask[0], ask[1] = 1500.0, 2048.0
    feas = rng.random(n) > 0.2
    coll = rng.integers(0, 2, n).astype(np.int32)
    placed = rng.integers(0, 3, n).astype(np.int32)
    cls = rng.integers(-1, n_classes, n).astype(np.int32)
    return (cap, used, ask, feas, coll, placed, cls, np.bool_(True))


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_numpy_reduce_twin_matches_jitted_bit_for_bit(seed):
    """The host-routing twin (explain.reduce_numpy) must return the SAME
    bits as the jitted reduce — it serves the same contract on CPU
    backends and the host tier."""
    from nomad_tpu.solver.kernels import explain_reduce
    args = _reduce_args(seed)
    jit_out = explain_reduce(*args, n_classes=4)
    np_out = explain.reduce_numpy(*args, n_classes=4)
    for a, b in zip(jit_out, np_out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sharded_explain_reduce_matches_solo_bit_for_bit(seed):
    """The psum form of the reduce (per-shard partials + collectives on
    the virtual 8-device mesh) returns the SAME bits as the solo jit."""
    from nomad_tpu.solver.kernels import explain_reduce
    from nomad_tpu.solver import sharding
    m = sharding.mesh()
    if m is None:
        pytest.skip("single-device world")
    args = _reduce_args(seed)
    solo = explain_reduce(*args, n_classes=4)
    shd = sharding.sharded_explain_reduce(m, n_classes=4)(*args)
    for a, b in zip(solo, shd):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_tier_end_to_end_attribution_matches_solo(monkeypatch):
    """Force the sharded tier: the solve's node-sharded placement vector
    feeds the mesh-spec'd reduce, and the rejected eval's AllocMetric is
    bit-consistent with the solo-tier run of the identical scenario."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("single-device world")

    def run():
        backend.reset()
        state_cache.reset()
        explain.reset()
        h, job, tg = build_and_run(SCHED_ALG_TPU, 41, n_nodes=16,
                                   count=3, ask_cpu=9000, ask_mem=64,
                                   eval_id="sharded-ev")
        return _failed(h, tg), [r for r in explain.recent(8)
                                if r["tg"] == tg.name][0]

    monkeypatch.setenv("NOMAD_SOLVER_BACKEND", "sharded")
    m_sharded, rec_sharded = run()
    assert rec_sharded["tier"] == "sharded"
    monkeypatch.delenv("NOMAD_SOLVER_BACKEND")
    m_solo, rec_solo = run()
    assert m_sharded.nodes_evaluated == m_solo.nodes_evaluated == 16
    assert dict(m_sharded.dimension_exhausted) == \
        dict(m_solo.dimension_exhausted)
    assert m_sharded.nodes_exhausted == m_solo.nodes_exhausted
    assert rec_sharded["dim_exhausted"] == rec_solo["dim_exhausted"]
    assert rec_sharded["n_feasible"] == rec_solo["n_feasible"]


# ------------------------------------------------------------ debug bundle

@pytest.fixture(scope="module")
def agent():
    from nomad_tpu.agent import Agent, AgentConfig
    a = Agent(AgentConfig(dev_mode=True, http_port=0, num_workers=1))
    a.start()
    yield a
    a.shutdown()


def _call(agent, path):
    import urllib.request
    with urllib.request.urlopen(agent.http_addr + path,
                                timeout=35) as resp:
        return json.loads(resp.read() or "null")


def test_operator_debug_endpoint_blocks(agent):
    b = _call(agent, "/v1/operator/debug")
    for key in ("Meta", "Status", "Metrics", "DeviceRuntime", "Traces",
                "Explains", "StateCache", "Breakers", "SchedulerConfig",
                "Raft"):
        assert key in b, f"bundle missing {key}"
    assert b["Meta"]["Name"]
    assert b["DeviceRuntime"]["devices"], "no device rows"
    assert "hits" in b["DeviceRuntime"]["compile_cache"]
    assert set(b["Breakers"]) == {"sharded", "pallas", "batch", "xla",
                                  "host"}
    assert "counters" in b["Metrics"]


def test_device_gauges_exported_in_prometheus(agent):
    import urllib.request
    agent.config.telemetry_prometheus = True
    with urllib.request.urlopen(
            agent.http_addr + "/v1/metrics?format=prometheus",
            timeout=35) as resp:
        text = resp.read().decode()
    assert "nomad_device_mem_bytes_in_use_d0" in text
    assert "nomad_device_live_buffers_d0" in text
    assert "nomad_compile_cache_hits" in text
    assert "nomad_compile_cache_misses" in text


def test_operator_debug_cli_archive_loadable(agent, tmp_path,
                                             monkeypatch):
    """`nomad-tpu operator debug` against the live dev agent produces a
    loadable tar.gz whose operator-debug.json carries the new blocks."""
    import types

    from nomad_tpu import cli as cli_mod
    monkeypatch.setenv("NOMAD_ADDR", agent.http_addr)
    out = tmp_path / "bundle.tar.gz"
    args = types.SimpleNamespace(duration="0.1", interval="0.25",
                                 output=str(out))
    cli_mod.cmd_operator_debug(args)
    assert out.exists()
    with tarfile.open(out, "r:gz") as tar:
        names = tar.getnames()
        debug_member = [n for n in names
                        if n.endswith("operator-debug.json")]
        assert debug_member, names
        payload = json.loads(
            tar.extractfile(debug_member[0]).read())
        assert "Explains" in payload and "DeviceRuntime" in payload
        index = [n for n in names if n.endswith("index.json")]
        manifest = json.loads(tar.extractfile(index[0]).read())
        assert "operator-debug.json" in manifest["Files"]
        assert any(n.endswith("metrics.prom") or
                   "metrics.prom" in manifest["Errors"] for n in names)
