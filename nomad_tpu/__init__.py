"""nomad_tpu — a TPU-native workload orchestrator.

A brand-new framework with the capabilities of HashiCorp Nomad
(reference: conorevans/nomad): Raft-style replicated control plane,
feasibility/scoring schedulers, node agents with pluggable task drivers —
with the server-side placement loop reformulated as a batched
constraint-satisfaction solve in JAX/XLA on TPU.

Layer map (mirrors reference layers, see SURVEY.md §1):
  structs/    shared data model + fit/scoring math (ref: nomad/structs/)
  state/      in-memory MVCC state store (ref: nomad/state/)
  scheduler/  CPU-reference schedulers, reconciler, stacks (ref: scheduler/)
  solver/     TPU batched placement solver (the north star; no ref equivalent)
  server/     control plane: broker, planner, workers, raft (ref: nomad/)
  client/     node agent: runners, fingerprint, drivers (ref: client/, drivers/)
  agent/      combined agent + HTTP API (ref: command/agent/)
  cli/        command line (ref: command/)
"""

__version__ = "0.1.0"
