"""nomad_tpu.obs — observability subsystem (ISSUES 7, 11): span-based
eval tracing with fan-in links, a bounded in-memory trace store, a
Chrome trace-event / Perfetto exporter, and device-runtime telemetry
(per-device memory watermarks, compile-cache counters, mesh layout).
See docs/OBSERVABILITY.md."""
from . import devruntime, trace                        # noqa: F401
from .trace import (                                   # noqa: F401
    NOOP_SPAN, Span, SpanCtx, Tracer, chain_summary, chrome_trace, tracer,
)

__all__ = ["trace", "tracer", "Tracer", "Span", "SpanCtx", "NOOP_SPAN",
           "chrome_trace", "chain_summary", "devruntime"]
