"""nomad_tpu.obs — observability subsystem (ISSUE 7): span-based eval
tracing with fan-in links, a bounded in-memory trace store, and a
Chrome trace-event / Perfetto exporter. See docs/OBSERVABILITY.md."""
from . import trace                                    # noqa: F401
from .trace import (                                   # noqa: F401
    NOOP_SPAN, Span, SpanCtx, Tracer, chain_summary, chrome_trace, tracer,
)

__all__ = ["trace", "tracer", "Tracer", "Span", "SpanCtx", "NOOP_SPAN",
           "chrome_trace", "chain_summary"]
