"""Device-runtime telemetry (ISSUE 11): what the accelerator runtime
itself is doing, surfaced next to the scheduler's own counters.

Three families, all bounded-cardinality:

  * per-device memory/buffer gauges from `jax.local_devices()`:
    `nomad.device.{mem_bytes_in_use,mem_peak_bytes,live_buffers}.d<N>`
    (ordinal-suffixed — the device count is a fixed property of the
    process, not an unbounded dimension);
  * compile-cache counters `nomad.compile_cache.{hits,misses}` fed by a
    jax monitoring listener (persistent compilation cache events) —
    zero when the running jax exposes no such events;
  * the mesh/shard layout snapshot (`sharding.mesh()`), so a debug
    bundle shows exactly how the node axis was partitioned when the
    capture ran.

Everything here is best-effort and exception-proof: telemetry must never
take down a scheduler, and the jax internals it reads vary across
versions. `install()` is idempotent; `refresh_gauges()` is called on
every /v1/metrics scrape and debug-bundle capture (pull-driven — no
background thread)."""
from __future__ import annotations

import threading

from ..metrics import metrics

_lock = threading.Lock()
_installed = False

# monitoring-event substrings -> our counter. jax records
# '/jax/compilation_cache/cache_hits' (and _misses) when the persistent
# compile cache is enabled; tolerate renames by substring match.
_EVENT_COUNTERS = (
    ("cache_hit", "nomad.compile_cache.hits"),
    ("cache_miss", "nomad.compile_cache.misses"),
)


def install() -> None:
    """Register the compile-cache monitoring listener (idempotent)."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    # the counters must exist even when no event ever fires, so the
    # prometheus exposition and the UI metrics page always carry them
    metrics.incr("nomad.compile_cache.hits", 0)
    metrics.incr("nomad.compile_cache.misses", 0)
    try:
        from jax import monitoring

        def _on_event(event: str, **kwargs) -> None:
            if "compilation_cache" not in event:
                return
            for needle, counter in _EVENT_COUNTERS:
                if needle in event:
                    metrics.incr(counter)
                    return

        monitoring.register_event_listener(_on_event)
    except Exception:       # noqa: BLE001 — telemetry is best-effort
        pass


def _device_rows() -> list[dict]:
    import jax
    rows = []
    live_by_device: dict = {}
    try:
        for arr in jax.live_arrays():
            for d in arr.devices():
                live_by_device[d.id] = live_by_device.get(d.id, 0) + 1
    except Exception:       # noqa: BLE001 — internal API drift
        live_by_device = {}
    for dev in jax.local_devices():
        stats = {}
        try:
            stats = dev.memory_stats() or {}
        except Exception:   # noqa: BLE001 — CPU backends have none
            stats = {}
        rows.append({
            "id": dev.id,
            "platform": dev.platform,
            "kind": getattr(dev, "device_kind", ""),
            "process_index": getattr(dev, "process_index", 0),
            "mem_bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "mem_peak_bytes": int(stats.get("peak_bytes_in_use", 0)),
            "mem_limit_bytes": int(stats.get("bytes_limit", 0)),
            "live_buffers": int(live_by_device.get(dev.id, 0)),
        })
    return rows


def _mesh_layout() -> dict:
    try:
        from ..solver import sharding
        m = sharding.mesh()
        if m is None:
            return {"sharded": False, "devices": 1}
        return {"sharded": True,
                "axis_names": list(m.axis_names),
                "shape": {k: int(v) for k, v in m.shape.items()},
                "devices": int(len(m.devices.flat)),
                "device_ids": [int(d.id) for d in m.devices.flat]}
    except Exception:       # noqa: BLE001
        return {"sharded": False, "devices": 0}


def refresh_gauges() -> list[dict]:
    """Re-sample the per-device gauges into the registry and return the
    rows. Called per scrape/capture — no background cadence to tune."""
    install()
    try:
        rows = _device_rows()
    except Exception:       # noqa: BLE001 — no jax, no gauges
        return []
    for row in rows:
        # the per-device suffix is a bounded dimension: device ordinals
        # are a fixed property of the process, not cluster entities
        suffix = f"d{row['id']}"
        # nomadlint: disable=OBS001 — bounded per-device ordinal suffix
        metrics.set_gauge(f"nomad.device.mem_bytes_in_use.{suffix}",
                          row["mem_bytes_in_use"])
        # nomadlint: disable=OBS001 — bounded per-device ordinal suffix
        metrics.set_gauge(f"nomad.device.mem_peak_bytes.{suffix}",
                          row["mem_peak_bytes"])
        # nomadlint: disable=OBS001 — bounded per-device ordinal suffix
        metrics.set_gauge(f"nomad.device.live_buffers.{suffix}",
                          row["live_buffers"])
    return rows


def snapshot() -> dict:
    """The debug-bundle block: devices + mesh layout + compile-cache
    counters + the solver's compile-cache configuration."""
    import os
    rows = refresh_gauges()
    return {
        "devices": rows,
        "mesh": _mesh_layout(),
        "compile_cache": {
            "hits": int(metrics.counter("nomad.compile_cache.hits")),
            "misses": int(metrics.counter("nomad.compile_cache.misses")),
            "persistent_dir": os.environ.get("NOMAD_COMPILE_CACHE", ""),
        },
    }
