"""Span-based eval tracing (ISSUE 7 tentpole): causal spans across the
full eval lifecycle, surviving thread handoffs and fan-in/fan-out.

PRs 1-6 made the hot path fast by *sharing* work across evals — a
micro-batched `jit(vmap)` dispatch serves K solves, a coalesced raft
entry carries up to 32 plans — which means a flat timer registry can no
longer say where one eval's latency went. This module restores that
attribution with the standard distributed-tracing model, adapted to an
in-process, multi-threaded control plane:

  * a TRACE per evaluation (or per leader-establish barrier), made of
    SPANS — named, timed, attributed, parented intervals;
  * context propagates by THREAD-LOCAL current-span plus an explicit
    eval-id registry, so a broker enqueue on one thread, the worker
    invoke on another, and the plan applier's commit on a third all
    attach to the same trace (`eval_ctx` + `use`);
  * FAN-IN is modeled with LINKS, not parents: the shared micro-batch
    dispatch span and the shared coalesced-commit span each carry links
    to every participating eval's span, and the store attaches the
    shared span to every linked trace so a per-eval fetch shows the
    shared work it rode (docs/OBSERVABILITY.md).

Sampling is head-based with error retention: `sample_rate` decides at
trace START whether a HEALTHY trace is kept; traces that end with any
non-"ok" status (faulted dispatch, failed eval, leadership lost) are
always retained, so the interesting ones survive a low rate. When
tracing is disabled every entry point is a cheap boolean check and a
shared no-op — the bench gates the enabled-mode overhead at <=5% of
stream throughput (tests/test_bench_regression.py).

Export is Chrome trace-event JSON (`chrome_trace`), loadable in
Perfetto / chrome://tracing; the agent serves it at /v1/traces and the
CLI renders a text waterfall (`nomad-tpu trace <eval-id>`).
"""
from __future__ import annotations

import itertools
import os
import random
import threading
import time
from contextlib import contextmanager
from typing import Optional

DEFAULT_CAPACITY = 2048      # retained (completed) traces
_LIVE_SLACK = 2              # live traces tolerated = slack * capacity

# statuses are free-form strings; "ok" is the only one head-sampling may
# drop. The lifecycle uses: ok, error, nack, leadership_lost, flushed,
# truncated, fanout, demoted.
STATUS_OK = "ok"


class SpanCtx:
    """A propagatable reference to a span: (trace_id, span_id). What the
    micro-batcher's lanes and the plan queue's pendings carry across
    threads, and what fan-in links point at."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"SpanCtx({self.trace_id[:8]}/{self.span_id[:8]})"


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0_perf",
                 "t0_wall", "attrs", "links", "thread", "_tracer", "_done")

    def __init__(self, tracer, name: str, trace_id: str, span_id: str,
                 parent_id: str, links, attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0_perf = time.perf_counter()
        self.t0_wall = time.time()
        self.attrs = dict(attrs) if attrs else {}
        self.links = [(c.trace_id, c.span_id) for c in links
                      if c is not None] if links else []
        self.thread = threading.current_thread().name
        self._tracer = tracer
        self._done = False

    def ctx(self) -> SpanCtx:
        return SpanCtx(self.trace_id, self.span_id)

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def end(self, status: str = STATUS_OK, **attrs) -> None:
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        self._tracer._end_span(self, status)


class _NoopSpan:
    """Shared do-nothing span: the disabled/unparented fast path."""

    __slots__ = ()

    def ctx(self):
        return None

    def annotate(self, **attrs):
        pass

    def end(self, status: str = STATUS_OK, **attrs):
        pass


NOOP_SPAN = _NoopSpan()


class _Trace:
    __slots__ = ("trace_id", "eval_id", "name", "status", "sampled",
                 "retain", "t0_perf", "t0_wall", "end_wall", "spans",
                 "linked", "open", "root", "attrs")

    def __init__(self, trace_id: str, eval_id: str, name: str,
                 sampled: bool, retain: bool):
        self.trace_id = trace_id
        self.eval_id = eval_id
        self.name = name
        self.status = ""
        self.sampled = sampled
        self.retain = retain
        self.t0_perf = time.perf_counter()
        self.t0_wall = time.time()
        self.end_wall = 0.0
        self.spans: list[dict] = []       # ended spans, append order
        self.linked: list[dict] = []      # shared fan-in spans linking here
        self.open = 0                     # spans started, not yet ended
        self.root: Optional[Span] = None
        self.attrs: dict = {}

    def as_dict(self) -> dict:
        return {
            "trace_id": self.trace_id, "eval_id": self.eval_id,
            "name": self.name, "status": self.status,
            "start_unix": self.t0_wall, "end_unix": self.end_wall,
            "duration_s": max(0.0, (self.end_wall or time.time())
                              - self.t0_wall),
            "attrs": {k: v for k, v in self.attrs.items()
                      if not str(k).startswith("_")},
            "spans": list(self.spans),
            "linked_spans": list(self.linked),
        }


class Tracer:
    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._enabled = os.environ.get("NOMAD_TRACE", "") != "0"
        self._sample_rate = 1.0
        # overload brownout multiplier (ISSUE 8, server/overload.py):
        # under pressure HEALTHY-trace head-sampling downshifts without
        # touching the operator's configured rate — error retention is
        # unaffected (non-ok endings are always kept), so the traces
        # that explain the overload survive it
        self._pressure_factor = 1.0
        self._capacity = DEFAULT_CAPACITY
        self._rng = random.Random()
        self._seq = itertools.count(1)
        self._id_prefix = f"{os.getpid() & 0xffff:04x}"
        self._live: dict[str, _Trace] = {}        # trace_id -> trace
        self._by_eval: dict[str, str] = {}        # eval_id -> trace_id
        self._done: dict[str, _Trace] = {}        # retained, insert order
        self._done_by_eval: dict[str, str] = {}
        self._leaked: list[dict] = []
        self.started = 0
        self.dropped = 0

    # --------------------------------------------------------- configuration

    def configure(self, enabled: Optional[bool] = None,
                  sample_rate: Optional[float] = None,
                  capacity: Optional[int] = None) -> None:
        """Hot-reloadable knobs (the worker pushes the raft-replicated
        SchedulerConfiguration telemetry_* values through here on every
        eval, same path as the micro-batcher's window). NOMAD_TRACE=0
        hard-disables regardless of config; NOMAD_TRACE=1 hard-enables."""
        env = os.environ.get("NOMAD_TRACE", "")
        if enabled is not None:
            self._enabled = bool(enabled) if env == "" else env != "0"
        if sample_rate is not None:
            self._sample_rate = min(1.0, max(0.0, float(sample_rate)))
        if capacity is not None and int(capacity) >= 1:
            self._capacity = int(capacity)

    def set_pressure_factor(self, factor: float) -> None:
        """Overload-controller lever: scales the head-sampling rate for
        healthy traces (1.0 = no downshift). Kept separate from
        configure() — the worker re-pushes the config rate every eval
        and must not erase the controller's downshift."""
        with self._lock:
            self._pressure_factor = min(1.0, max(0.0, float(factor)))

    def enabled(self) -> bool:
        return self._enabled

    # ------------------------------------------------------- current context

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> Optional[SpanCtx]:
        st = getattr(self._tls, "stack", None)
        if not st:
            return None
        top = st[-1]
        if isinstance(top, Span):
            return top.ctx()
        if isinstance(top, SpanCtx):
            return top
        return None

    @contextmanager
    def use(self, ctx):
        """Adopt `ctx` (a SpanCtx or Span, e.g. looked up by eval id) as
        this thread's current context — the cross-thread handoff seam
        (broker -> worker -> applier)."""
        if ctx is None or ctx is NOOP_SPAN or not self._enabled:
            yield
            return
        st = self._stack()
        st.append(ctx)
        try:
            yield
        finally:
            if st and st[-1] is ctx:
                st.pop()

    def annotate(self, **attrs) -> None:
        """Merge attributes into the current span, if any (used by deep
        layers — backend demotion chain, raft index assignment — that
        should not know whose span they run under)."""
        if not self._enabled or not attrs:
            return
        st = getattr(self._tls, "stack", None)
        if st:
            top = st[-1]
            if isinstance(top, Span):
                top.attrs.update(attrs)

    def annotate_list(self, key: str, value) -> None:
        """Append `value` to a list-valued attribute of the current span
        (demotion chains record every tier they fell through)."""
        if not self._enabled:
            return
        st = getattr(self._tls, "stack", None)
        if st:
            top = st[-1]
            if isinstance(top, Span):
                top.attrs.setdefault(key, []).append(value)

    # ------------------------------------------------------------ trace API

    def _new_id(self) -> str:
        return f"{self._id_prefix}{next(self._seq):012x}"

    def begin_eval(self, eval_id: str, name: str = "eval",
                   owner=None, **attrs) -> Optional[SpanCtx]:
        """Get-or-create the trace + root span for an evaluation
        (idempotent: the broker calls it at enqueue; the worker and the
        bench harness call it defensively at dequeue). Head sampling
        happens HERE; unsampled traces still record spans so an error
        ending can promote them to retention.

        `owner` scopes the trace to one broker/server: the tracer is
        process-global, and in-process multi-server tests re-run an eval
        on a NEW leader while the old leader's workers may still hold
        the previous trace — a different owner SUPERSEDES the stale
        trace (truncated, status `superseded`) instead of mixing two
        servers' spans into one timeline. `None` matches any owner."""
        if not self._enabled or not eval_id:
            return None
        stale = None
        with self._lock:
            tid = self._by_eval.get(eval_id)
            if tid is not None:
                tr = self._live.get(tid)
                if tr is not None and tr.root is not None:
                    old = tr.attrs.get("_owner")
                    if owner is None or old is None or old == owner:
                        return tr.root.ctx()
                    stale = tr
                    del self._by_eval[eval_id]
            rate = self._sample_rate * self._pressure_factor
            sampled = rate >= 1.0 or self._rng.random() < rate
            tid = self._new_id()
            tr = _Trace(tid, eval_id, name, sampled, retain=False)
            if owner is not None:
                tr.attrs["_owner"] = owner
            self._live[tid] = tr
            self._by_eval[eval_id] = tid
            self.started += 1
            self._evict_live_locked()
        if stale is not None:
            stale.attrs["truncated"] = True
            stale.root.end("superseded")
        root = Span(self, name, tid, self._new_id(), "", None, attrs)
        with self._lock:
            tr.root = root
            tr.open += 1
        return root.ctx()

    def eval_ctx(self, eval_id: str) -> Optional[SpanCtx]:
        if not self._enabled or not eval_id:
            return None
        with self._lock:
            tid = self._by_eval.get(eval_id)
            tr = self._live.get(tid) if tid else None
        if tr is None or tr.root is None:
            return None
        return tr.root.ctx()

    def mark_dequeued(self, eval_id: str, **attrs) -> None:
        """Record the broker queue-wait span: enqueue (trace start) to
        dequeue. Called by the broker with the lock already held —
        must stay allocation-light."""
        if not self._enabled:
            return
        with self._lock:
            tid = self._by_eval.get(eval_id)
            tr = self._live.get(tid) if tid else None
        if tr is None or tr.root is None:
            return
        self.record_span("broker.wait", tr.root.ctx(), tr.t0_perf,
                         t0_wall=tr.t0_wall, **attrs)

    def end_eval(self, eval_id: str, status: str = STATUS_OK,
                 truncate: bool = False, owner=None, **attrs) -> None:
        """End an eval's root span and complete its trace. `truncate`
        marks still-open child spans as truncated WITHOUT counting them
        as leaks — the flush/shutdown paths end traces whose worker
        threads may still be mid-span. `owner` must match the trace's
        begin_eval owner (both non-None) or the end is ignored: a
        deposed server's late completion must not close the trace its
        successor is writing."""
        if not self._enabled or not eval_id:
            return
        with self._lock:
            tid = self._by_eval.get(eval_id)
            tr = self._live.get(tid) if tid else None
            if tr is not None:
                old = tr.attrs.get("_owner")
                if owner is not None and old is not None and old != owner:
                    return
            self._by_eval.pop(eval_id, None)
        if tr is None or tr.root is None:
            return
        if attrs:
            tr.attrs.update(attrs)
        if truncate:
            tr.attrs["truncated"] = True
        tr.root.end(status)

    def begin_root(self, name: str, **attrs) -> Span:
        """A root span NOT tied to an eval (leader-establish barrier,
        failover promotion, revoke). Always retained."""
        if not self._enabled:
            return NOOP_SPAN
        with self._lock:
            tid = self._new_id()
            tr = _Trace(tid, "", name, sampled=True, retain=True)
            self._live[tid] = tr
            self.started += 1
            self._evict_live_locked()
        root = Span(self, name, tid, self._new_id(), "", None, attrs)
        with self._lock:
            tr.root = root
            tr.open += 1
        return root

    # ------------------------------------------------------------- span API

    def _resolve_parent(self, parent) -> Optional[SpanCtx]:
        if parent is None:
            parent = self.current()
        if isinstance(parent, Span):
            return parent.ctx()
        if isinstance(parent, SpanCtx):
            return parent
        return None

    def start_span(self, name: str, parent=None, links=(),
                   **attrs) -> object:
        """Manually-ended span. Returns NOOP_SPAN when tracing is off or
        there is no parent context (unit-test scheduler runs outside any
        trace must not mint orphan roots)."""
        if not self._enabled:
            return NOOP_SPAN
        ctx = self._resolve_parent(parent)
        if ctx is None:
            return NOOP_SPAN
        with self._lock:
            tr = self._live.get(ctx.trace_id)
            if tr is None:
                return NOOP_SPAN
            tr.open += 1
        return Span(self, name, ctx.trace_id, self._new_id(), ctx.span_id,
                    links, attrs)

    @contextmanager
    def span(self, name: str, parent=None, links=(), **attrs):
        """The standard instrumentation block: a child of the current
        (or given) context, made current for the block, ended with
        status ok/error on exit."""
        sp = self.start_span(name, parent=parent, links=links, **attrs)
        if sp is NOOP_SPAN:
            yield sp
            return
        st = self._stack()
        st.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.end("error", error=repr(e)[:200])
            raise
        finally:
            if st and st[-1] is sp:
                st.pop()
            sp.end()
        # (second end() is a no-op when the except path already ended it)

    def record_span(self, name: str, parent, start_perf: float,
                    links=(), status: str = STATUS_OK,
                    t0_wall: Optional[float] = None, **attrs) -> None:
        """An already-elapsed interval (queue waits measured at drain
        time): start given, end now."""
        if not self._enabled:
            return
        ctx = self._resolve_parent(parent)
        if ctx is None:
            return
        with self._lock:
            tr = self._live.get(ctx.trace_id)
            if tr is None:
                return
            tr.open += 1
        sp = Span(self, name, ctx.trace_id, self._new_id(), ctx.span_id,
                  links, attrs)
        sp.t0_perf = start_perf
        sp.t0_wall = t0_wall if t0_wall is not None else \
            time.time() - max(0.0, time.perf_counter() - start_perf)
        sp.end(status)

    # ------------------------------------------------------ span completion

    def _end_span(self, span: Span, status: str) -> None:
        dur = max(0.0, time.perf_counter() - span.t0_perf)
        rec = {"name": span.name, "id": span.span_id,
               "parent": span.parent_id, "trace": span.trace_id,
               "ts": span.t0_wall, "dur": dur, "status": status,
               "thread": span.thread, "attrs": span.attrs,
               "links": span.links}
        with self._lock:
            tr = self._live.get(span.trace_id)
            if tr is None:
                tr = self._done.get(span.trace_id)
            if tr is not None:
                tr.spans.append(rec)
                tr.open = max(0, tr.open - 1)
            # fan-in: attach the shared span to every linked trace so a
            # per-eval fetch shows the shared dispatch/commit it rode
            for (ltid, _lsid) in span.links:
                if ltid == span.trace_id:
                    continue
                ltr = self._live.get(ltid) or self._done.get(ltid)
                if ltr is not None:
                    ltr.linked.append(rec)
            if tr is not None and tr.root is span:
                self._complete_locked(tr, status)

    def _complete_locked(self, tr: _Trace, status: str) -> None:
        tr.status = status
        tr.end_wall = time.time()
        self._live.pop(tr.trace_id, None)
        if tr.open > 0 and not tr.attrs.get("truncated"):
            self._leaked.append({"trace": tr.trace_id, "name": tr.name,
                                 "eval_id": tr.eval_id, "open": tr.open})
        # forced retention is for INTERESTING endings (error, timeout,
        # leadership lost, faulted) — administrative endings (flush on
        # step-down, supersede by a new leader) would otherwise flood
        # the bounded store and evict the very error traces a low
        # sample rate is trying to protect
        interesting = status not in (STATUS_OK, "flushed", "superseded")
        keep = tr.retain or tr.sampled or interesting
        if not keep:
            self.dropped += 1
            return
        self._done[tr.trace_id] = tr
        if tr.eval_id:
            self._done_by_eval[tr.eval_id] = tr.trace_id
        while len(self._done) > self._capacity:
            old_tid, old = next(iter(self._done.items()))
            del self._done[old_tid]
            if old.eval_id and \
                    self._done_by_eval.get(old.eval_id) == old_tid:
                del self._done_by_eval[old.eval_id]

    def _evict_live_locked(self) -> None:
        # abandoned traces (evals whose worker died, shutdown races) must
        # not grow without bound; oldest live traces are dropped silently
        cap = self._capacity * _LIVE_SLACK
        while len(self._live) > cap:
            tid, tr = next(iter(self._live.items()))
            del self._live[tid]
            if tr.eval_id and self._by_eval.get(tr.eval_id) == tid:
                del self._by_eval[tr.eval_id]
            self.dropped += 1

    # --------------------------------------------------------------- readers

    def traces(self, limit: int = 200) -> list[dict]:
        """Most-recent-first summaries of retained traces."""
        with self._lock:
            done = list(self._done.values())
        out = []
        for tr in reversed(done[-limit:] if limit else done):
            out.append({
                "trace_id": tr.trace_id, "eval_id": tr.eval_id,
                "name": tr.name, "status": tr.status,
                "start_unix": tr.t0_wall,
                "duration_s": max(0.0, tr.end_wall - tr.t0_wall),
                "spans": len(tr.spans), "links": len(tr.linked),
            })
        return out

    def get(self, ref: str) -> Optional[dict]:
        """Fetch one trace by trace id, eval id, or unique prefix of
        either."""
        with self._lock:
            tid = self._done_by_eval.get(ref) or \
                (ref if ref in self._done else None)
            if tid is None and len(ref) >= 4:
                hits = {t for e, t in self._done_by_eval.items()
                        if e.startswith(ref)}
                hits |= {t for t in self._done if t.startswith(ref)}
                if len(hits) == 1:
                    tid = hits.pop()
            tr = self._done.get(tid) if tid else None
            return tr.as_dict() if tr is not None else None

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self._enabled,
                    "sample_rate": self._sample_rate,
                    "pressure_factor": self._pressure_factor,
                    "capacity": self._capacity,
                    "live": len(self._live), "retained": len(self._done),
                    "started": self.started, "dropped": self.dropped}

    def take_leaked(self) -> list[dict]:
        """Spans still open when their trace completed (the conftest
        span-leak gate). Reading clears the list."""
        with self._lock:
            out = self._leaked
            self._leaked = []
            return out

    def reset(self) -> None:
        with self._lock:
            self._live.clear()
            self._by_eval.clear()
            self._done.clear()
            self._done_by_eval.clear()
            self._leaked = []
            self.started = 0
            self.dropped = 0
            self._pressure_factor = 1.0


# ------------------------------------------------------------------ exports

def chrome_trace(traces: list[dict]) -> dict:
    """Chrome trace-event JSON (chrome://tracing / Perfetto "legacy
    chrome JSON"): one complete ("X") event per span on a per-thread
    track, plus flow ("s"/"f") events for every fan-in link so the
    shared micro-batch dispatch / coalesced commit visibly connects to
    each participating eval's lane."""
    events = []
    tids: dict[str, int] = {}
    span_at: dict[str, dict] = {}

    def tid_for(thread: str) -> int:
        if thread not in tids:
            tids[thread] = len(tids) + 1
        return tids[thread]

    seen = set()
    for tr in traces:
        for sp in list(tr.get("spans", ())) + list(tr.get(
                "linked_spans", ())):
            if sp["id"] in seen:
                continue
            seen.add(sp["id"])
            span_at[sp["id"]] = sp
            args = {"trace": sp["trace"], "status": sp["status"]}
            for k, v in (sp.get("attrs") or {}).items():
                args[str(k)] = v
            events.append({
                "ph": "X", "name": sp["name"], "cat": "eval",
                "pid": 1, "tid": tid_for(sp["thread"]),
                "ts": sp["ts"] * 1e6, "dur": max(sp["dur"], 1e-7) * 1e6,
                "args": args,
            })
    flow = itertools.count(1)
    for sp in span_at.values():
        for (_ltid, lsid) in sp.get("links", ()):
            src = span_at.get(lsid)
            if src is None:
                continue
            fid = next(flow)
            events.append({"ph": "s", "id": fid, "name": "fanin",
                           "cat": "link", "pid": 1,
                           "tid": tid_for(src["thread"]),
                           "ts": (src["ts"] + src["dur"] / 2) * 1e6})
            events.append({"ph": "f", "id": fid, "name": "fanin",
                           "cat": "link", "bp": "e", "pid": 1,
                           "tid": tid_for(sp["thread"]),
                           "ts": (sp["ts"] + sp["dur"] / 2) * 1e6})
    for thread, tid in tids.items():
        events.append({"ph": "M", "name": "thread_name", "pid": 1,
                       "tid": tid, "args": {"name": thread}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def chain_summary(tr: dict) -> dict:
    """Which lifecycle stages a retained eval trace covers — the
    completeness predicate behind the bench's trace_complete_frac and
    the chaos continuity tests. `complete` = root-to-commit: the worker
    invoked, a plan was submitted, and its commit outcome is attributed
    (a committed entry, a no-op, or an attributed failure). Fan-in
    coverage is reported separately because solo evals legitimately
    skip the micro-batcher and lone plans commit uncoalesced."""
    names = {}
    for sp in tr.get("spans", ()):
        names.setdefault(sp["name"], []).append(sp)
    linked = {}
    for sp in tr.get("linked_spans", ()):
        linked.setdefault(sp["name"], []).append(sp)
    submitted = "plan.submit" in names or "plan.commit_wait" in names
    committed = ("plan.commit_wait" in names
                 or "plan.commit" in linked or "plan.commit" in names)
    mb_waits = [w for w in names.get("solver.microbatch.wait", [])
                if not (w.get("attrs") or {}).get("solo")]
    mb_linked = all(w.get("links") for w in mb_waits) if mb_waits else None
    commit_waits = names.get("plan.commit_wait", [])
    commit_linked = any("plan.commit" in linked or w.get("links")
                        for w in commit_waits) if commit_waits else None
    return {
        "invoked": "worker.invoke" in names,
        "scheduled": "scheduler.process" in names
        or "scheduler.reconcile" in names,
        "submitted": submitted,
        "committed": committed,
        "microbatched": bool(mb_waits),
        "microbatch_linked": mb_linked,
        "commit_linked": commit_linked,
        "complete": ("worker.invoke" in names and submitted and committed
                     and bool(tr.get("status"))),
    }


tracer = Tracer()

# module-level forwarding API (instrumentation sites import the module,
# not the object — one process-wide tracer matches the one-store,
# one-device reality, exactly like solver/microbatch.py)
configure = tracer.configure
set_pressure_factor = tracer.set_pressure_factor
enabled = tracer.enabled
current = tracer.current
use = tracer.use
annotate = tracer.annotate
annotate_list = tracer.annotate_list
begin_eval = tracer.begin_eval
eval_ctx = tracer.eval_ctx
mark_dequeued = tracer.mark_dequeued
end_eval = tracer.end_eval
begin_root = tracer.begin_root
start_span = tracer.start_span
span = tracer.span
record_span = tracer.record_span
traces = tracer.traces
get = tracer.get
stats = tracer.stats
take_leaked = tracer.take_leaked
reset = tracer.reset
