"""TLS material + context construction for the RPC transport (behavioral
ref helper/tlsutil/config.go — server/client SSLContexts with mutual
verification — and the cert-generation side of `nomad tls` / test helpers).

Certificates follow the reference's naming scheme: servers present
``server.<region>.nomad``, clients ``client.<region>.nomad``, and peers
verify both the chain (shared CA) and, optionally, the role-and-region
name (``verify_server_hostname``)."""
from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
from dataclasses import dataclass, field
from typing import Optional


# ----------------------------------------------------------- cert generation

def _write(path: str, data: bytes) -> str:
    with open(path, "wb") as f:
        f.write(data)
    os.chmod(path, 0o600)
    return path


def generate_ca(out_dir: str, name: str = "nomad-tpu-ca"
                ) -> tuple[str, str]:
    """Self-signed CA. Returns (ca_cert_path, ca_key_path)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    subject = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(subject).issuer_name(subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=3650))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .add_extension(x509.KeyUsage(
                digital_signature=True, key_cert_sign=True, crl_sign=True,
                content_commitment=False, key_encipherment=False,
                data_encipherment=False, key_agreement=False,
                encipher_only=False, decipher_only=False), critical=True)
            .sign(key, hashes.SHA256()))
    os.makedirs(out_dir, exist_ok=True)
    cert_path = _write(os.path.join(out_dir, "ca.pem"),
                       cert.public_bytes(serialization.Encoding.PEM))
    key_path = _write(
        os.path.join(out_dir, "ca-key.pem"),
        key.private_bytes(serialization.Encoding.PEM,
                          serialization.PrivateFormat.PKCS8,
                          serialization.NoEncryption()))
    return cert_path, key_path


def generate_cert(out_dir: str, ca_cert: str, ca_key: str, name: str,
                  extra_sans: Optional[list[str]] = None
                  ) -> tuple[str, str]:
    """CA-signed leaf cert for `name` (e.g. "server.global.nomad"), valid
    for both server and client auth (peers are both, as in the reference's
    mutual-TLS RPC). Returns (cert_path, key_path)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    with open(ca_cert, "rb") as f:
        ca = x509.load_pem_x509_certificate(f.read())
    with open(ca_key, "rb") as f:
        cakey = serialization.load_pem_private_key(f.read(), password=None)

    key = ec.generate_private_key(ec.SECP256R1())
    sans: list[x509.GeneralName] = [x509.DNSName(name),
                                    x509.DNSName("localhost")]
    for san in extra_sans or []:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(san)))
        except ValueError:
            sans.append(x509.DNSName(san))
    sans.append(x509.IPAddress(ipaddress.ip_address("127.0.0.1")))
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(x509.Name(
                [x509.NameAttribute(NameOID.COMMON_NAME, name)]))
            .issuer_name(ca.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(x509.SubjectAlternativeName(sans), critical=False)
            .add_extension(x509.ExtendedKeyUsage(
                [ExtendedKeyUsageOID.SERVER_AUTH,
                 ExtendedKeyUsageOID.CLIENT_AUTH]), critical=False)
            .sign(cakey, hashes.SHA256()))
    os.makedirs(out_dir, exist_ok=True)
    slug = name.split(".")[0]
    cert_path = _write(os.path.join(out_dir, f"{slug}.pem"),
                       cert.public_bytes(serialization.Encoding.PEM))
    key_path = _write(
        os.path.join(out_dir, f"{slug}-key.pem"),
        key.private_bytes(serialization.Encoding.PEM,
                          serialization.PrivateFormat.PKCS8,
                          serialization.NoEncryption()))
    return cert_path, key_path


# --------------------------------------------------------------- TLS config

@dataclass
class TLSConfig:
    """The `tls { }` agent stanza (ref nomad/structs/config/tls.go)."""
    enable_rpc: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    # require the remote to present a cert signed by ca_file AND named
    # for its role+region (ref VerifyServerHostname)
    verify_server_hostname: bool = False
    region: str = "global"

    def server_context(self) -> ssl.SSLContext:
        """Context for the RPC listener: mutual TLS — clients must present
        a CA-signed cert (ref tlsutil IncomingTLSConfig w/
        VerifyIncomingRPC)."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        ctx.load_cert_chain(self.cert_file, self.key_file)
        ctx.load_verify_locations(self.ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def client_context(self) -> ssl.SSLContext:
        """Context for outbound RPC connections (ref OutgoingTLSConfig)."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        ctx.load_verify_locations(self.ca_file)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        if not self.verify_server_hostname:
            ctx.check_hostname = False
        return ctx

    @property
    def server_name(self) -> str:
        """The name dialers verify when verify_server_hostname is set."""
        return f"server.{self.region or 'global'}.nomad"
