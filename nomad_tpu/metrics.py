"""In-process telemetry: counters + latency samples + labeled fixed-bucket
histograms on the scheduler hot path (ref nomad/worker.go:461,553
`nomad.worker.invoke_scheduler_*`, nomad/plan_apply.go:185,204
`nomad.plan.evaluate`/`nomad.plan.submit`, armon/go-metrics used
throughout the reference).

A single process-global registry; the agent surfaces it at /v1/metrics and
bench.py reads it for the per-phase breakdown. Lock-free fast path: CPython
dict/float ops are atomic enough for monitoring data, and the hot loop
(50k-alloc plans) must not take a lock per sample.

Every sample keeps (a) a bounded RING of raw values for in-process
percentiles — newest-N, so a long-running stream reports steady state,
not startup (ISSUE 7 satellite) — and (b) cumulative fixed-bucket counts
so the Prometheus exposition carries real quantiles (histogram type with
`_bucket{le=...}` lines, not a `_count`/`_sum`-only summary). Labeled
histograms (`observe(name, v, labels=...)`) serve the few metrics where a
bounded dimension (tier, scheduler type, disposition) is worth a real
label instead of a metric-name suffix — nomadlint OBS001 polices the
unbounded-name-interpolation anti-pattern.
"""
from __future__ import annotations

import bisect
import time
from contextlib import contextmanager

RAW_VALUES_CAP = 4096       # per-sample raw-value ring for percentiles

# fixed bucket bounds (seconds-oriented; counts/sizes reuse them as plain
# magnitudes). FIXED per process lifetime: cumulative bucket counts are
# only mergeable/exposable if the bounds never move under them.
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0)


class _Sample:
    __slots__ = ("count", "sum", "min", "max", "last", "values", "total",
                 "buckets")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        self.last = 0.0
        # bounded raw-value RING so readers can compute percentiles over
        # the newest RAW_VALUES_CAP values (p50 stream batch size, p50
        # submit latency); list append/setitem is atomic under the GIL,
        # matching the lock-free writer contract. `total` counts every
        # value ever recorded — the ring write position AND the `skip`
        # checkpoint unit for windowed bench percentiles.
        self.values: list = []
        self.total = 0
        # cumulative fixed-bucket counts (len(DEFAULT_BUCKETS)+1, last is
        # +Inf) for the Prometheus histogram exposition
        self.buckets = [0] * (len(DEFAULT_BUCKETS) + 1)

    def add(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self.last = v
        if len(self.values) < RAW_VALUES_CAP:
            self.values.append(v)
        else:
            self.values[self.total % RAW_VALUES_CAP] = v
        self.total += 1
        self.buckets[bisect.bisect_left(DEFAULT_BUCKETS, v)] += 1

    def raw_window(self, skip: int = 0) -> list:
        """Values recorded after the `skip` checkpoint, oldest-first,
        bounded by what the ring still holds (the newest
        RAW_VALUES_CAP). A checkpoint older than the ring returns the
        whole ring — every surviving value IS inside the window."""
        n = len(self.values)
        if n == 0 or skip >= self.total:
            return []
        if self.total <= RAW_VALUES_CAP:
            return self.values[skip:]
        head = self.total % RAW_VALUES_CAP
        ordered = self.values[head:] + self.values[:head]
        want = min(self.total - skip, RAW_VALUES_CAP)
        return ordered[-want:]

    def as_dict(self) -> dict:
        mean = self.sum / self.count if self.count else 0.0
        bounds = list(DEFAULT_BUCKETS) + ["+Inf"]
        return {"count": self.count, "sum": round(self.sum, 6),
                "min": round(self.min, 6) if self.count else 0.0,
                "max": round(self.max, 6), "mean": round(mean, 6),
                "last": round(self.last, 6),
                # non-cumulative nonzero buckets: what the UI's metrics
                # page renders as a distribution (ISSUE 7 satellite)
                "buckets": [[bounds[i], c]
                            for i, c in enumerate(self.buckets) if c]}


class _Hist:
    """One labeled histogram series: cumulative fixed buckets + sum/count
    (the Prometheus histogram data model, per label set)."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float, bounds) -> None:
        self.counts[bisect.bisect_left(bounds, v)] += 1
        self.sum += v
        self.count += 1


class _HistFamily:
    __slots__ = ("bounds", "series", "help")

    def __init__(self, bounds=DEFAULT_BUCKETS, help_text: str = ""):
        self.bounds = tuple(bounds)
        self.series: dict[tuple, _Hist] = {}   # sorted label items -> series
        self.help = help_text


class Registry:
    def __init__(self):
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.samples: dict[str, _Sample] = {}
        self.hists: dict[str, _HistFamily] = {}
        self.help: dict[str, str] = {}         # metric name -> # HELP text

    # ------------------------------------------------------------- writers

    def incr(self, name: str, v: float = 1.0) -> None:
        self.counters[name] = self.counters.get(name, 0.0) + v

    def set_gauge(self, name: str, v: float) -> None:
        self.gauges[name] = v

    def add_sample(self, name: str, seconds: float) -> None:
        s = self.samples.get(name)
        if s is None:
            s = self.samples[name] = _Sample()
        s.add(seconds)

    def observe(self, name: str, v: float, labels: dict = None,
                buckets=None) -> None:
        """Labeled fixed-bucket histogram observation. Labels must be a
        BOUNDED dimension (tier, scheduler type, disposition); ids and
        node names belong in trace attributes, not metric labels
        (OBS001). `buckets` applies only on first touch of `name`."""
        fam = self.hists.get(name)
        if fam is None:
            fam = self.hists[name] = _HistFamily(buckets or DEFAULT_BUCKETS)
        key = tuple(sorted(labels.items())) if labels else ()
        h = fam.series.get(key)
        if h is None:
            h = fam.series[key] = _Hist(len(fam.bounds))
        h.observe(v, fam.bounds)

    def describe(self, name: str, help_text: str) -> None:
        """Attach Prometheus `# HELP` text to a metric name (counters,
        gauges, samples, and histograms all honor it)."""
        self.help[name] = help_text

    @contextmanager
    def measure(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_sample(name, time.perf_counter() - t0)

    # ------------------------------------------------------------- readers

    def timer_sum(self, name: str) -> float:
        s = self.samples.get(name)
        return s.sum if s else 0.0

    def percentile(self, name: str, q: float, skip: int = 0) -> float:
        """q in [0, 1] over the sample's raw-value ring. The ring keeps
        the NEWEST RAW_VALUES_CAP values (a long-running stream reports
        steady state, not the first 4096 startup samples). `skip` drops
        values recorded before a checkpoint taken with sample_count(),
        so a caller can window the percentile to samples recorded after
        it (the bench's timed-stream windows)."""
        s = self.samples.get(name)
        if s is None:
            return 0.0
        vals = sorted(s.raw_window(skip))
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, max(0, int(q * len(vals))))
        return vals[idx]

    def sample_count(self, name: str) -> int:
        """How many raw values the sample has EVER recorded — the `skip`
        checkpoint for a later windowed percentile()."""
        s = self.samples.get(name)
        return s.total if s else 0

    def ratio(self, num: str, den: str) -> float:
        """timer_sum(num) / timer_sum(den), 0.0 when the denominator is
        empty — e.g. phase_overlap_fraction = time the host spent working
        while device/applier work was in flight, over all host time."""
        d = self.timer_sum(den)
        return self.timer_sum(num) / d if d else 0.0

    def counter(self, name: str) -> float:
        return self.counters.get(name, 0.0)

    def snapshot(self) -> dict:
        # lock-free writers can insert a first-seen key mid-iteration;
        # retry the copy rather than taking a lock on the hot path
        for _ in range(16):
            try:
                counters = dict(self.counters)
                gauges = dict(self.gauges)
                samples = dict(self.samples)
                # the per-family series dicts grow lock-free too (first
                # observe() of a new label set) — copy them INSIDE the
                # retry, or a concurrent insert crashes the scrape
                hists = {k: (fam.bounds, dict(fam.series))
                         for k, fam in dict(self.hists).items()}
                break
            except RuntimeError:
                continue
        return {
            "counters": {k: counters[k] for k in sorted(counters)},
            "gauges": {k: gauges[k] for k in sorted(gauges)},
            "samples": {k: samples[k].as_dict() for k in sorted(samples)},
            "histograms": {
                k: {
                    "buckets": list(hists[k][0]),
                    "series": {
                        "" if not key else ",".join(
                            f"{lk}={lv}" for lk, lv in key): {
                            "counts": list(h.counts),
                            "sum": round(h.sum, 6), "count": h.count}
                        for key, h in sorted(hists[k][1].items())},
                } for k in sorted(hists)},
        }

    # --------------------------------------------------------- prometheus

    def _sanitizer(self):
        """Collision-safe name sanitization: two distinct metric names
        must never sanitize to the same exposition name (ISSUE 7
        satellite — `a.b-c` and `a.b_c` used to collide silently). The
        first claimant keeps the clean form; later colliders get a
        short stable hash suffix."""
        import hashlib
        taken: dict[str, str] = {}

        def san(name: str) -> str:
            base = "".join(c if c.isalnum() or c == "_" else "_"
                           for c in name)
            owner = taken.get(base)
            if owner is None:
                taken[base] = name
                return base
            if owner == name:
                return base
            suffix = hashlib.sha1(name.encode()).hexdigest()[:6]
            out = f"{base}_{suffix}"
            taken[out] = name
            return out
        return san

    def prometheus(self, extra_gauges: dict = None) -> str:
        """Prometheus text exposition of the registry (ref
        telemetry.prometheus_metrics + armon/go-metrics' prometheus
        sink): counters as counters, gauges as gauges, samples and
        labeled histograms as real histograms (`_bucket{le=...}` +
        `_sum` + `_count`) with `_min`/`_max`/`_mean` companion gauges
        and `# HELP` lines."""
        san = self._sanitizer()
        lines = []
        # copy only what this exposition reads (snapshot() would also
        # serialize every sample/histogram into dicts we'd discard);
        # same lock-free-writer retry as snapshot()
        for _ in range(16):
            try:
                counters = {k: self.counters[k]
                            for k in sorted(self.counters)}
                gauges = dict(self.gauges)
                break
            except RuntimeError:
                continue

        def emit_head(n: str, orig: str, mtype: str) -> None:
            lines.append(f"# HELP {n} {self.help.get(orig, orig)}")
            lines.append(f"# TYPE {n} {mtype}")

        for k, v in counters.items():
            n = san(k)
            emit_head(n, k, "counter")
            lines.append(f"{n} {v}")
        gauges.update(extra_gauges or {})
        for k, v in sorted(gauges.items()):
            n = san(k)
            emit_head(n, k, "gauge")
            lines.append(f"{n} {v}")
        for _ in range(16):     # lock-free writers, like snapshot()
            try:
                samples = dict(self.samples)
                break
            except RuntimeError:
                continue
        for k in sorted(samples):
            s = samples[k]
            n = san(k)
            emit_head(n, k, "histogram")
            acc = 0
            for bound, c in zip(DEFAULT_BUCKETS, s.buckets):
                acc += c
                lines.append(f'{n}_bucket{{le="{bound}"}} {acc}')
            lines.append(f'{n}_bucket{{le="+Inf"}} {s.count}')
            lines.append(f"{n}_sum {round(s.sum, 6)}")
            lines.append(f"{n}_count {s.count}")
            d = s.as_dict()
            for stat in ("min", "max", "mean"):
                sn = san(f"{k}.{stat}")
                lines.append(f"# TYPE {sn} gauge")
                lines.append(f"{sn} {d[stat]}")
        for _ in range(16):
            try:
                hists = {k: (fam.bounds, dict(fam.series))
                         for k, fam in dict(self.hists).items()}
                break
            except RuntimeError:
                continue
        for k in sorted(hists):
            bounds, series = hists[k]
            n = san(k)
            emit_head(n, k, "histogram")
            for key, h in sorted(series.items()):
                lbl = ",".join(f'{lk}="{lv}"' for lk, lv in key)
                pre = f"{lbl}," if lbl else ""
                acc = 0
                for bound, c in zip(bounds, h.counts):
                    acc += c
                    lines.append(f'{n}_bucket{{{pre}le="{bound}"}} {acc}')
                lines.append(f'{n}_bucket{{{pre}le="+Inf"}} {h.count}')
                tail = f"{{{lbl}}}" if lbl else ""
                lines.append(f"{n}_sum{tail} {round(h.sum, 6)}")
                lines.append(f"{n}_count{tail} {h.count}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        self.counters.clear()
        self.gauges.clear()
        self.samples.clear()
        self.hists.clear()


metrics = Registry()

# read-path scale-out telemetry (ISSUE 16): pre-registered HELP text so
# the Prometheus page documents the staleness/backpressure counters even
# before they first move (reset() keeps help, so tests see these too)
metrics.describe("nomad.read.leader_served",
                 "list/get reads served from the leader's store")
metrics.describe("nomad.read.follower_served",
                 "list/get reads served from a follower's replicated "
                 "store (stale reads)")
metrics.describe("nomad.event.subscriber_dropped",
                 "event subscribers closed for falling behind after "
                 "coalescing could not shrink their queue (last rung)")
metrics.describe("nomad.event.coalesced_batches",
                 "per-subscriber queue folds (backpressure rung 1)")
metrics.describe("nomad.event.coalesced_events",
                 "events superseded latest-wins-per-key by coalescing")
metrics.describe("nomad.event.waiters_parked",
                 "blocking queries parked on the event broker instead "
                 "of poll-looping the state store")


def record_swallowed_error(site: str, err: BaseException,
                           logger=None) -> None:
    """EXC001 discipline: daemon paths that deliberately survive an
    exception must still surface it — a total `nomad.swallowed_errors`
    counter (plus a per-site breakdown) moves on the /v1/metrics page,
    and the owning component's logger gets one line. `logger=None` keeps
    the counter for components without one (e.g. the state store's event
    sinks)."""
    metrics.incr("nomad.swallowed_errors")
    # sites are short literals at the call sites, never interpolated ids
    # nomadlint: disable=OBS001 — bounded per-site breakdown
    metrics.incr(f"nomad.swallowed_errors.{site}")
    if logger is not None:
        try:
            logger(f"{site}: swallowed {err!r}")
        except Exception:       # noqa: BLE001 — telemetry must not throw
            pass
