"""In-process telemetry: counters + latency samples on the scheduler hot
path (ref nomad/worker.go:461,553 `nomad.worker.invoke_scheduler_*`,
nomad/plan_apply.go:185,204 `nomad.plan.evaluate`/`nomad.plan.submit`,
armon/go-metrics used throughout the reference).

A single process-global registry; the agent surfaces it at /v1/metrics and
bench.py reads it for the per-phase breakdown. Lock-free fast path: CPython
dict/float ops are atomic enough for monitoring data, and the hot loop
(50k-alloc plans) must not take a lock per sample.
"""
from __future__ import annotations

import time
from contextlib import contextmanager


RAW_VALUES_CAP = 4096       # per-sample raw-value window for percentiles


class _Sample:
    __slots__ = ("count", "sum", "min", "max", "last", "values")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        self.last = 0.0
        # bounded raw-value window so readers can compute percentiles
        # (p50 stream batch size, p50 submit latency); list append is
        # atomic under the GIL, matching the lock-free writer contract
        self.values: list = []

    def add(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self.last = v
        if len(self.values) < RAW_VALUES_CAP:
            self.values.append(v)

    def as_dict(self) -> dict:
        mean = self.sum / self.count if self.count else 0.0
        return {"count": self.count, "sum": round(self.sum, 6),
                "min": round(self.min, 6) if self.count else 0.0,
                "max": round(self.max, 6), "mean": round(mean, 6),
                "last": round(self.last, 6)}


class Registry:
    def __init__(self):
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.samples: dict[str, _Sample] = {}

    # ------------------------------------------------------------- writers

    def incr(self, name: str, v: float = 1.0) -> None:
        self.counters[name] = self.counters.get(name, 0.0) + v

    def set_gauge(self, name: str, v: float) -> None:
        self.gauges[name] = v

    def add_sample(self, name: str, seconds: float) -> None:
        s = self.samples.get(name)
        if s is None:
            s = self.samples[name] = _Sample()
        s.add(seconds)

    @contextmanager
    def measure(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_sample(name, time.perf_counter() - t0)

    # ------------------------------------------------------------- readers

    def timer_sum(self, name: str) -> float:
        s = self.samples.get(name)
        return s.sum if s else 0.0

    def percentile(self, name: str, q: float, skip: int = 0) -> float:
        """q in [0, 1] over the sample's bounded raw-value window
        (RAW_VALUES_CAP newest-first is NOT kept — the window holds the
        first N values, which for bench-length runs is all of them).
        `skip` drops the first N recorded values, so a caller can window
        the percentile to samples recorded after a checkpoint (see
        sample_count)."""
        s = self.samples.get(name)
        if s is None or len(s.values) <= skip:
            return 0.0
        vals = sorted(s.values[skip:])
        idx = min(len(vals) - 1, max(0, int(q * len(vals))))
        return vals[idx]

    def sample_count(self, name: str) -> int:
        """How many raw values the sample's window holds — the `skip`
        checkpoint for a later windowed percentile()."""
        s = self.samples.get(name)
        return len(s.values) if s else 0

    def ratio(self, num: str, den: str) -> float:
        """timer_sum(num) / timer_sum(den), 0.0 when the denominator is
        empty — e.g. phase_overlap_fraction = time the host spent working
        while device/applier work was in flight, over all host time."""
        d = self.timer_sum(den)
        return self.timer_sum(num) / d if d else 0.0

    def counter(self, name: str) -> float:
        return self.counters.get(name, 0.0)

    def snapshot(self) -> dict:
        # lock-free writers can insert a first-seen key mid-iteration;
        # retry the copy rather than taking a lock on the hot path
        for _ in range(16):
            try:
                counters = dict(self.counters)
                gauges = dict(self.gauges)
                samples = dict(self.samples)
                break
            except RuntimeError:
                continue
        return {
            "counters": {k: counters[k] for k in sorted(counters)},
            "gauges": {k: gauges[k] for k in sorted(gauges)},
            "samples": {k: samples[k].as_dict() for k in sorted(samples)},
        }

    def prometheus(self, extra_gauges: dict = None) -> str:
        """Prometheus text exposition of the registry (ref
        telemetry.prometheus_metrics + armon/go-metrics' prometheus
        sink): counters as counters, gauges as gauges, samples as
        _count/_sum summaries — names sanitized to the metric charset."""
        def san(name: str) -> str:
            return "".join(c if c.isalnum() or c == "_" else "_"
                           for c in name)

        snap = self.snapshot()
        lines = []
        for k, v in snap["counters"].items():
            n = san(k)
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {v}")
        gauges = dict(snap["gauges"])
        gauges.update(extra_gauges or {})
        for k, v in sorted(gauges.items()):
            n = san(k)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {v}")
        for k, s in snap["samples"].items():
            n = san(k)
            lines.append(f"# TYPE {n} summary")
            lines.append(f"{n}_count {s['count']}")
            lines.append(f"{n}_sum {s['sum']}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        self.counters.clear()
        self.gauges.clear()
        self.samples.clear()


metrics = Registry()


def record_swallowed_error(site: str, err: BaseException,
                           logger=None) -> None:
    """EXC001 discipline: daemon paths that deliberately survive an
    exception must still surface it — a total `nomad.swallowed_errors`
    counter (plus a per-site breakdown) moves on the /v1/metrics page,
    and the owning component's logger gets one line. `logger=None` keeps
    the counter for components without one (e.g. the state store's event
    sinks)."""
    metrics.incr("nomad.swallowed_errors")
    metrics.incr(f"nomad.swallowed_errors.{site}")
    if logger is not None:
        try:
            logger(f"{site}: swallowed {err!r}")
        except Exception:       # noqa: BLE001 — telemetry must not throw
            pass
