"""Allocation model (ref nomad/structs/structs.go:9230 Allocation,
AllocatedResources, TaskState, RescheduleTracker, DesiredTransition).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional

from .resources import ComparableResources, NetworkResource
from .job import Job, ReschedulePolicy

# Desired statuses (ref structs.go AllocDesiredStatus*)
ALLOC_DESIRED_RUN = "run"
ALLOC_DESIRED_STOP = "stop"
ALLOC_DESIRED_EVICT = "evict"

# Client statuses (ref structs.go AllocClientStatus*)
ALLOC_CLIENT_PENDING = "pending"
ALLOC_CLIENT_RUNNING = "running"
ALLOC_CLIENT_COMPLETE = "complete"
ALLOC_CLIENT_FAILED = "failed"
ALLOC_CLIENT_LOST = "lost"
ALLOC_CLIENT_UNKNOWN = "unknown"

# Desired descriptions used by the reconciler/scheduler
DESC_RESCHEDULED = "alloc was rescheduled because it failed"
DESC_NOT_NEEDED = "alloc not needed due to job update"
DESC_MIGRATING = "alloc is being migrated"
DESC_CANARY = "alloc is a canary"
DESC_NODE_TAINTED = "alloc was lost since its node is down"
DESC_PREEMPTED = "alloc preempted by a higher-priority allocation"

TASK_STATE_PENDING = "pending"
TASK_STATE_RUNNING = "running"
TASK_STATE_DEAD = "dead"


@dataclass(slots=True)
class AllocatedTaskResources:
    cpu_shares: int = 0
    reserved_cores: tuple[int, ...] = ()
    memory_mb: int = 0
    memory_max_mb: int = 0
    networks: list[NetworkResource] = field(default_factory=list)
    devices: list["AllocatedDeviceResource"] = field(default_factory=list)

    def comparable(self) -> ComparableResources:
        return ComparableResources(
            cpu_shares=self.cpu_shares,
            reserved_cores=tuple(self.reserved_cores),
            memory_mb=self.memory_mb,
            memory_max_mb=self.memory_max_mb,
            networks=list(self.networks),
        )


@dataclass(slots=True)
class AllocatedDeviceResource:
    vendor: str = ""
    type: str = ""
    name: str = ""
    device_ids: list[str] = field(default_factory=list)


@dataclass(slots=True)
class AllocatedSharedResources:
    disk_mb: int = 0
    networks: list[NetworkResource] = field(default_factory=list)
    ports: list[dict] = field(default_factory=list)   # AllocatedPortMapping


@dataclass(slots=True)
class AllocatedResources:
    """Per-task + shared resources actually granted (ref structs.go
    AllocatedResources)."""
    tasks: dict[str, AllocatedTaskResources] = field(default_factory=dict)
    shared: AllocatedSharedResources = field(default_factory=AllocatedSharedResources)
    # usage-index caches (state/usage_index.py): allocs stamped from one
    # task group share this object, so the XR row computes once per TG
    _xr_usage: Optional[tuple] = field(default=None, init=False,
                                       repr=False, compare=False)
    _xr_seq: Optional[bool] = field(default=None, init=False,
                                    repr=False, compare=False)

    def comparable(self) -> ComparableResources:
        c = ComparableResources(disk_mb=self.shared.disk_mb,
                                networks=list(self.shared.networks))
        for tr in self.tasks.values():
            c.add(tr.comparable())
        return c


@dataclass(slots=True)
class TaskEvent:
    type: str = ""
    time_unix: float = 0.0
    message: str = ""
    details: dict[str, str] = field(default_factory=dict)


@dataclass(slots=True)
class TaskState:
    state: str = TASK_STATE_PENDING
    failed: bool = False
    restarts: int = 0
    last_restart_unix: float = 0.0
    started_at: float = 0.0
    finished_at: float = 0.0
    events: list[TaskEvent] = field(default_factory=list)

    def successful(self) -> bool:
        return self.state == TASK_STATE_DEAD and not self.failed


@dataclass(slots=True)
class RescheduleEvent:
    reschedule_time_unix: float = 0.0
    prev_alloc_id: str = ""
    prev_node_id: str = ""
    delay_sec: float = 0.0


@dataclass(slots=True)
class RescheduleTracker:
    events: list[RescheduleEvent] = field(default_factory=list)


@dataclass(slots=True)
class DesiredTransition:
    """Server-suggested transitions applied by drainer/scheduler (ref
    structs.go DesiredTransition)."""
    migrate: Optional[bool] = None
    reschedule: Optional[bool] = None
    force_reschedule: Optional[bool] = None

    def should_migrate(self) -> bool:
        return bool(self.migrate)

    def should_force_reschedule(self) -> bool:
        return bool(self.force_reschedule)


@dataclass(slots=True)
class AllocDeploymentStatus:
    healthy: Optional[bool] = None
    timestamp_unix: float = 0.0
    canary: bool = False
    modify_index: int = 0

    def is_healthy(self) -> bool:
        return self.healthy is True

    def is_unhealthy(self) -> bool:
        return self.healthy is False


@dataclass(slots=True)
class NetworkStatus:
    interface_name: str = ""
    address: str = ""
    dns: Optional[dict] = None


@dataclass(slots=True)
class Allocation:
    id: str = ""
    namespace: str = "default"
    eval_id: str = ""
    name: str = ""
    node_id: str = ""
    node_name: str = ""
    job_id: str = ""
    job: Optional[Job] = None          # job snapshot at placement time
    task_group: str = ""
    allocated_resources: AllocatedResources = field(default_factory=AllocatedResources)
    metrics: Optional["AllocMetric"] = None

    desired_status: str = ALLOC_DESIRED_RUN
    desired_description: str = ""
    desired_transition: DesiredTransition = field(default_factory=DesiredTransition)

    client_status: str = ALLOC_CLIENT_PENDING
    client_description: str = ""
    task_states: dict[str, TaskState] = field(default_factory=dict)
    network_status: Optional[NetworkStatus] = None

    deployment_id: str = ""
    deployment_status: Optional[AllocDeploymentStatus] = None
    reschedule_tracker: Optional[RescheduleTracker] = None
    follow_up_eval_id: str = ""
    # graceful client disconnection (ref 1.3 structs.Allocation
    # AllocStates / Expired): when the reconciler marks this alloc
    # `unknown` it stamps the disconnect time; expiry is measured
    # against the group's max_client_disconnect window
    disconnected_at: float = 0.0
    preempted_by_allocation: str = ""
    preempted_allocations: list[str] = field(default_factory=list)

    previous_allocation: str = ""
    next_allocation: str = ""

    create_index: int = 0
    modify_index: int = 0
    alloc_modify_index: int = 0
    create_time_unix: float = 0.0
    modify_time_unix: float = 0.0

    def copy(self, deep_job: bool = False) -> "Allocation":
        return dataclasses.replace(
            self,
            job=(self.job.copy() if (self.job and deep_job) else self.job),
            task_states=dict(self.task_states),
            desired_transition=dataclasses.replace(self.desired_transition),
            deployment_status=(dataclasses.replace(self.deployment_status)
                               if self.deployment_status else None),
            reschedule_tracker=(RescheduleTracker(events=list(self.reschedule_tracker.events))
                                if self.reschedule_tracker else None),
            preempted_allocations=list(self.preempted_allocations),
        )

    # ---- status predicates (ref structs.go Allocation.TerminalStatus etc) ----

    def terminal_status(self) -> bool:
        """Terminal from the server's perspective: desired stop/evict or the
        client has reached a terminal state."""
        if self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            return True
        return self.client_terminal_status()

    def server_terminal_status(self) -> bool:
        return self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT)

    def client_terminal_status(self) -> bool:
        return self.client_status in (ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED,
                                      ALLOC_CLIENT_LOST)

    def comparable_resources(self) -> ComparableResources:
        return self.allocated_resources.comparable()

    def job_namespaced_id(self) -> tuple[str, str]:
        return (self.namespace, self.job_id)

    # ---- reschedule logic (ref structs.go Allocation.NextRescheduleTime,
    #      RescheduleEligible, reconcile_util.go updateByReschedulable) ----

    def reschedule_policy(self) -> Optional[ReschedulePolicy]:
        if self.job is None:
            return None
        tg = self.job.lookup_task_group(self.task_group)
        return tg.reschedule_policy if tg else None

    def next_reschedule_time(self, policy: Optional[ReschedulePolicy] = None
                             ) -> tuple[float, bool]:
        """Returns (when, eligible): the next time this failed alloc may be
        rescheduled under its policy's backoff."""
        policy = policy or self.reschedule_policy()
        if policy is None or not policy.should_reschedule():
            return 0.0, False
        if self.client_status != ALLOC_CLIENT_FAILED:
            return 0.0, False
        fail_time = self.last_event_time()
        delay = self.reschedule_delay(policy)
        next_time = fail_time + delay
        if not policy.unlimited:
            attempted, _ = self.reschedule_attempts_in_interval(policy)
            if attempted >= policy.attempts:
                return next_time, False
        return next_time, True

    def reschedule_delay(self, policy: ReschedulePolicy) -> float:
        """Backoff delay for the next reschedule attempt: constant,
        exponential, or fibonacci on the number of prior attempts."""
        n = len(self.reschedule_tracker.events) if self.reschedule_tracker else 0
        base = policy.delay_sec
        if policy.delay_function == "constant" or n == 0:
            delay = base
        elif policy.delay_function == "exponential":
            delay = base * (2 ** n)
        elif policy.delay_function == "fibonacci":
            a, b = base, base
            for _ in range(max(0, n - 1)):
                a, b = b, a + b
            delay = b
        else:
            delay = base
        if policy.max_delay_sec > 0:
            delay = min(delay, policy.max_delay_sec)
        return delay

    def reschedule_attempts_in_interval(self, policy: ReschedulePolicy
                                        ) -> tuple[int, float]:
        if not self.reschedule_tracker:
            return 0, 0.0
        now = self.last_event_time()
        window_start = now - policy.interval_sec
        attempts = [e for e in self.reschedule_tracker.events
                    if e.reschedule_time_unix >= window_start]
        return len(attempts), window_start

    def last_event_time(self) -> float:
        """Latest task finished_at, falling back to modify time."""
        last = 0.0
        for ts in self.task_states.values():
            if ts.finished_at > last:
                last = ts.finished_at
        return last or self.modify_time_unix

    def ran_successfully(self) -> bool:
        if not self.task_states:
            return False
        return all(ts.successful() for ts in self.task_states.values())

    def migrate_disk(self) -> bool:
        if self.job is None:
            return False
        tg = self.job.lookup_task_group(self.task_group)
        return bool(tg and tg.ephemeral_disk.migrate)


@dataclass
class AllocMetric:
    """Scheduler decision metadata attached to each placement
    (ref structs.go AllocMetric)."""
    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_available: dict[str, int] = field(default_factory=dict)   # per DC
    class_filtered: dict[str, int] = field(default_factory=dict)
    constraint_filtered: dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: dict[str, int] = field(default_factory=dict)
    dimension_exhausted: dict[str, int] = field(default_factory=dict)
    quota_exhausted: list[str] = field(default_factory=list)
    scores: dict[str, float] = field(default_factory=dict)
    score_meta: list[dict] = field(default_factory=list)
    allocation_time_ns: int = 0
    coalesced_failures: int = 0

    def filter_node(self, node, reason: str) -> None:
        self.nodes_filtered += 1
        if node is not None and node.node_class:
            self.class_filtered[node.node_class] = \
                self.class_filtered.get(node.node_class, 0) + 1
        if reason:
            self.constraint_filtered[reason] = \
                self.constraint_filtered.get(reason, 0) + 1

    def exhausted_node(self, node, dimension: str) -> None:
        self.nodes_exhausted += 1
        if node is not None and node.node_class:
            self.class_exhausted[node.node_class] = \
                self.class_exhausted.get(node.node_class, 0) + 1
        if dimension:
            self.dimension_exhausted[dimension] = \
                self.dimension_exhausted.get(dimension, 0) + 1

    def score_node(self, node_id: str, name: str, score: float) -> None:
        self.scores[f"{node_id}.{name}"] = score

    def copy(self) -> "AllocMetric":
        return dataclasses.replace(
            self,
            nodes_available=dict(self.nodes_available),
            class_filtered=dict(self.class_filtered),
            constraint_filtered=dict(self.constraint_filtered),
            class_exhausted=dict(self.class_exhausted),
            dimension_exhausted=dict(self.dimension_exhausted),
            quota_exhausted=list(self.quota_exhausted),
            scores=dict(self.scores),
            score_meta=list(self.score_meta),
        )


def filter_terminal_allocs(allocs: list[Allocation]
                           ) -> tuple[list[Allocation], dict[str, Allocation]]:
    """Split into (live, terminal-by-name keeping newest) — ref
    scheduler/util.go filterTerminalAllocs."""
    live: list[Allocation] = []
    terminal: dict[str, Allocation] = {}
    for a in allocs:
        if a.terminal_status():
            prev = terminal.get(a.name)
            if prev is None or prev.create_index < a.create_index:
                terminal[a.name] = a
        else:
            live.append(a)
    return live, terminal
