"""Job summary rollups maintained by the state store
(ref nomad/structs/structs.go JobSummary / TaskGroupSummary and
nomad/state/state_store.go summary maintenance)."""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field


@dataclass
class TaskGroupSummary:
    queued: int = 0
    complete: int = 0
    failed: int = 0
    running: int = 0
    starting: int = 0
    lost: int = 0
    unknown: int = 0


@dataclass
class JobSummary:
    job_id: str = ""
    namespace: str = "default"
    summary: dict[str, TaskGroupSummary] = field(default_factory=dict)
    children_pending: int = 0
    children_running: int = 0
    children_dead: int = 0
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "JobSummary":
        return dataclasses.replace(
            self,
            summary={k: dataclasses.replace(v) for k, v in self.summary.items()})
