"""Plan + Deployment models (ref nomad/structs/structs.go:10643 Plan,
:10887 PlanResult, :8862 Deployment).

A Plan is a scheduler's proposed state mutation: per-node placements, stops,
and preemptions. The serial plan applier verifies each node's slice against
current state (optimistic concurrency) and commits what fits.
"""
from __future__ import annotations

import dataclasses
import uuid
from dataclasses import dataclass, field
from typing import Optional

from .alloc import Allocation, ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT
from .job import Job

DEPLOYMENT_STATUS_RUNNING = "running"
DEPLOYMENT_STATUS_PAUSED = "paused"
DEPLOYMENT_STATUS_FAILED = "failed"
DEPLOYMENT_STATUS_SUCCESSFUL = "successful"
DEPLOYMENT_STATUS_CANCELLED = "cancelled"
DEPLOYMENT_STATUS_PENDING = "pending"
DEPLOYMENT_STATUS_BLOCKED = "blocked"
DEPLOYMENT_STATUS_UNBLOCKING = "unblocking"

DEPLOYMENT_TERMINAL = {DEPLOYMENT_STATUS_FAILED, DEPLOYMENT_STATUS_SUCCESSFUL,
                       DEPLOYMENT_STATUS_CANCELLED}

DESC_DEPLOYMENT_PROMOTED = "promoted canaries"
DESC_NEW_DEPLOYMENT = "created for job update"


@dataclass
class DeploymentState:
    """Per-task-group deployment progress (ref structs.go DeploymentState)."""
    auto_revert: bool = False
    auto_promote: bool = False
    promoted: bool = False
    placed_canaries: list[str] = field(default_factory=list)
    desired_canaries: int = 0
    desired_total: int = 0
    placed_allocs: int = 0
    healthy_allocs: int = 0
    unhealthy_allocs: int = 0
    progress_deadline_sec: float = 0.0
    require_progress_by_unix: float = 0.0

    def copy(self) -> "DeploymentState":
        return dataclasses.replace(self, placed_canaries=list(self.placed_canaries))


@dataclass
class Deployment:
    id: str = field(default_factory=lambda: str(uuid.uuid4()))
    namespace: str = "default"
    job_id: str = ""
    job_version: int = 0
    job_modify_index: int = 0
    job_spec_modify_index: int = 0
    job_create_index: int = 0
    is_multiregion: bool = False
    task_groups: dict[str, DeploymentState] = field(default_factory=dict)
    status: str = DEPLOYMENT_STATUS_RUNNING
    status_description: str = DESC_NEW_DEPLOYMENT
    create_index: int = 0
    modify_index: int = 0
    create_time_unix: float = 0.0
    modify_time_unix: float = 0.0

    def copy(self) -> "Deployment":
        return dataclasses.replace(
            self,
            task_groups={k: v.copy() for k, v in self.task_groups.items()})

    def active(self) -> bool:
        return self.status in (DEPLOYMENT_STATUS_RUNNING, DEPLOYMENT_STATUS_PAUSED,
                               DEPLOYMENT_STATUS_PENDING, DEPLOYMENT_STATUS_BLOCKED,
                               DEPLOYMENT_STATUS_UNBLOCKING)

    def requires_promotion(self) -> bool:
        for st in self.task_groups.values():
            if st.desired_canaries > 0 and not st.promoted:
                return True
        return False

    def has_auto_promote(self) -> bool:
        states = [st for st in self.task_groups.values() if st.desired_canaries > 0]
        return bool(states) and all(st.auto_promote for st in states)


def new_deployment(job: Job, now: float = 0.0) -> Deployment:
    """ref structs.go NewDeployment"""
    return Deployment(
        namespace=job.namespace,
        job_id=job.id,
        job_version=job.version,
        job_modify_index=job.modify_index,
        job_spec_modify_index=job.job_modify_index,
        job_create_index=job.create_index,
        status=DEPLOYMENT_STATUS_RUNNING,
        create_time_unix=now,
        modify_time_unix=now,
    )


@dataclass
class DeploymentStatusUpdate:
    deployment_id: str = ""
    status: str = ""
    status_description: str = ""


@dataclass
class DesiredUpdates:
    """Plan annotations per task group (ref structs.go DesiredUpdates)."""
    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0
    canary: int = 0
    preemptions: int = 0


@dataclass
class PlanAnnotations:
    desired_tg_updates: dict[str, DesiredUpdates] = field(default_factory=dict)
    preempted_allocs: list[str] = field(default_factory=list)


@dataclass
class Plan:
    eval_id: str = ""
    eval_token: str = ""
    priority: int = 50
    all_at_once: bool = False
    job: Optional[Job] = None
    # node_id -> allocs to stop/evict (with updated desired status/description)
    node_update: dict[str, list[Allocation]] = field(default_factory=dict)
    # node_id -> new/updated allocs to place
    node_allocation: dict[str, list[Allocation]] = field(default_factory=dict)
    # node_id -> allocs preempted (desired_status=evict)
    node_preemptions: dict[str, list[Allocation]] = field(default_factory=dict)
    deployment: Optional[Deployment] = None
    deployment_updates: list[DeploymentStatusUpdate] = field(default_factory=list)
    annotations: Optional[PlanAnnotations] = None
    snapshot_index: int = 0
    # the submitting eval's enqueue TTL (ISSUE 8): the applier rejects a
    # past-deadline plan BEFORE the raft round — its caller already gave
    # up, committing would be wasted device+consensus work. 0 = none.
    deadline_unix: float = 0.0
    # fused plan-evaluate verdict (ISSUE 15): {version, uid, epoch,
    # rows: {view_row -> verified-ask f32[R']}} stamped by the solver's
    # fused dispatch — rows the device proved fit post-solve at that
    # usage-journal version. Worker-local advisory state (never crosses
    # raft); the applier consumes it as a monotone fast path and falls
    # back to its own dense compare whenever the stamp doesn't bind.
    solver_verdict: Optional[dict] = None

    # ---- mutators used by the schedulers (ref structs.go Plan.AppendAlloc etc) ----

    def append_alloc(self, alloc: Allocation, job: Optional[Job]) -> None:
        """Add a placement. The alloc's job is normalized to the plan job
        unless a specific (e.g. older) job version is given."""
        alloc.job = job or self.job
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_stopped_alloc(self, alloc: Allocation, desired_desc: str,
                             client_status: str = "",
                             follow_up_eval_id: str = "") -> None:
        a = alloc.copy()
        a.job = None  # the job is carried by existing state
        a.desired_status = ALLOC_DESIRED_STOP
        a.desired_description = desired_desc
        if client_status:
            a.client_status = client_status
        if follow_up_eval_id:
            a.follow_up_eval_id = follow_up_eval_id
        self.node_update.setdefault(a.node_id, []).append(a)

    def append_preempted_alloc(self, alloc: Allocation, preempting_id: str) -> None:
        a = alloc.copy()
        a.job = None
        a.desired_status = ALLOC_DESIRED_EVICT
        a.desired_description = f"Preempted by alloc ID {preempting_id}"
        a.preempted_by_allocation = preempting_id
        self.node_preemptions.setdefault(a.node_id, []).append(a)

    def pop_update(self, alloc: Allocation) -> None:
        """Remove a pending stop for this alloc (used when an updated alloc is
        placed in the same plan)."""
        updates = self.node_update.get(alloc.node_id, [])
        self.node_update[alloc.node_id] = [u for u in updates if u.id != alloc.id]
        if not self.node_update[alloc.node_id]:
            del self.node_update[alloc.node_id]

    def is_no_op(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and self.deployment is None and not self.deployment_updates)


@dataclass
class PlanResult:
    """What the plan applier actually committed (ref structs.go:10887)."""
    node_update: dict[str, list[Allocation]] = field(default_factory=dict)
    node_allocation: dict[str, list[Allocation]] = field(default_factory=dict)
    node_preemptions: dict[str, list[Allocation]] = field(default_factory=dict)
    deployment: Optional[Deployment] = None
    deployment_updates: list[DeploymentStatusUpdate] = field(default_factory=list)
    rejected_nodes: list[str] = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0

    def full_commit(self, plan: Plan) -> tuple[bool, int, int]:
        """(fully committed?, expected placements, actual) — ref
        structs.go PlanResult.FullCommit."""
        expected = sum(len(v) for v in plan.node_allocation.values())
        actual = sum(len(v) for v in self.node_allocation.values())
        return expected == actual, expected, actual

    def is_no_op(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and not self.deployment_updates and self.deployment is None)
