"""Fit & scoring math — the kernel the TPU solver vectorizes
(ref nomad/structs/funcs.go:147 AllocsFit, :236 ScoreFitBinPack,
:263 ScoreFitSpread). The scalar forms here are the behavioral oracle for
nomad_tpu.solver's dense versions.
"""
from __future__ import annotations

import math
from typing import Optional

from .alloc import Allocation
from .node import Node
from .resources import ComparableResources
from .network import NetworkIndex

BINPACK_MAX_FIT_SCORE = 18.0


def allocs_fit(node: Node, allocs: list[Allocation],
               net_idx: Optional[NetworkIndex] = None,
               check_devices: bool = False
               ) -> tuple[bool, str, ComparableResources]:
    """Do these allocations all fit on the node?
    Returns (fit, failing dimension, summed utilization).
    Mirrors funcs.go:147 AllocsFit: terminal allocs are ignored; reserved
    cores must not overlap; node resources minus node reservation must be a
    superset of the sum; port collisions and bandwidth overcommit fail."""
    used = ComparableResources()
    seen_cores: set[int] = set()
    core_overlap = False

    for alloc in allocs:
        if alloc.terminal_status():
            continue
        cr = alloc.comparable_resources()
        used.add(cr)
        for core in cr.reserved_cores:
            if core in seen_cores:
                core_overlap = True
            seen_cores.add(core)

    if core_overlap:
        return False, "cores", used

    available = node.comparable_resources()
    available.subtract(node.comparable_reserved_resources())
    ok, dim = available.superset(used)
    if not ok:
        return False, dim, used

    if net_idx is None:
        net_idx = NetworkIndex()
        if net_idx.set_node(node) or net_idx.add_allocs(allocs):
            return False, "reserved port collision", used

    if net_idx.overcommitted():
        return False, "bandwidth exceeded", used

    if check_devices:
        acct = DeviceAccounter(node)
        if acct.add_allocs(allocs):
            return False, "device oversubscribed", used

    return True, "", used


def _free_percentages(node: Node, util: ComparableResources) -> tuple[float, float]:
    reserved = node.comparable_reserved_resources()
    res = node.comparable_resources()
    node_cpu = float(res.cpu_shares) - float(reserved.cpu_shares)
    node_mem = float(res.memory_mb) - float(reserved.memory_mb)
    free_cpu = 1.0 - (float(util.cpu_shares) / node_cpu) if node_cpu else 0.0
    free_mem = 1.0 - (float(util.memory_mb) / node_mem) if node_mem else 0.0
    return free_cpu, free_mem


def score_fit_binpack(node: Node, util: ComparableResources) -> float:
    """BestFit v3: score in [0,18]; fuller node => higher score
    (funcs.go:236)."""
    free_cpu, free_mem = _free_percentages(node, util)
    total = math.pow(10, free_cpu) + math.pow(10, free_mem)
    return min(18.0, max(0.0, 20.0 - total))


def score_fit_spread(node: Node, util: ComparableResources) -> float:
    """Worst Fit: emptier node => higher score (funcs.go:263)."""
    free_cpu, free_mem = _free_percentages(node, util)
    total = math.pow(10, free_cpu) + math.pow(10, free_mem)
    return min(18.0, max(0.0, total - 2.0))


class DeviceAccounter:
    """Tracks device instance usage on a node
    (ref nomad/structs/devices.go DeviceAccounter)."""

    def __init__(self, node: Node):
        # (vendor, type, name) -> {instance_id: count}
        self.devices: dict[tuple, dict[str, int]] = {}
        self._healthy: dict[tuple, set[str]] = {}
        for dev in node.node_resources.devices:
            key = dev.id_tuple()
            self.devices[key] = {inst.id: 0 for inst in dev.instances}
            self._healthy[key] = {inst.id for inst in dev.instances if inst.healthy}

    def add_allocs(self, allocs: list[Allocation]) -> bool:
        """Returns True if devices are oversubscribed (collision)."""
        collision = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            for tr in alloc.allocated_resources.tasks.values():
                for ad in tr.devices:
                    key = (ad.vendor, ad.type, ad.name)
                    insts = self.devices.get(key)
                    if insts is None:
                        continue
                    for dev_id in ad.device_ids:
                        if dev_id in insts:
                            insts[dev_id] += 1
                            if insts[dev_id] > 1:
                                collision = True
        return collision

    def free_instances(self, key: tuple) -> list[str]:
        insts = self.devices.get(key, {})
        return [i for i, c in insts.items()
                if c == 0 and i in self._healthy.get(key, set())]


def score_normalize(scores: list[float]) -> float:
    """Mean of component scores (ref scheduler/rank.go
    ScoreNormalizationIterator:737)."""
    if not scores:
        return 0.0
    return sum(scores) / len(scores)
