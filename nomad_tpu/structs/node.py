"""Node model (ref nomad/structs/structs.go:1853, node_class.go).

A Node is the fingerprinted description of one agent: attributes map,
total/reserved resources, drain/eligibility state, and a computed node class
used to cache scheduler feasibility per *equivalence class* of nodes.
"""
from __future__ import annotations

import dataclasses
import hashlib
from dataclasses import dataclass, field
from typing import Optional

from .resources import NodeReservedResources, NodeResources, ComparableResources

# Node statuses (ref structs.go NodeStatus*)
NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"
NODE_STATUS_DISCONNECTED = "disconnected"

# Scheduling eligibility (ref structs.go NodeScheduling*)
NODE_SCHED_ELIGIBLE = "eligible"
NODE_SCHED_INELIGIBLE = "ineligible"


@dataclass
class DrainStrategy:
    """Node drain spec (ref structs.go DrainStrategy)."""
    deadline_sec: float = 0.0        # <0: force drain, 0: no deadline
    ignore_system_jobs: bool = False
    force_deadline_unix: float = 0.0  # absolute time the drain deadlines


@dataclass
class NodeEvent:
    message: str = ""
    subsystem: str = ""
    timestamp_unix: float = 0.0
    details: dict[str, str] = field(default_factory=dict)


@dataclass
class HostVolumeInfo:
    path: str = ""
    read_only: bool = False


@dataclass
class Node:
    id: str = ""
    name: str = ""
    datacenter: str = "dc1"
    node_class: str = ""
    status: str = NODE_STATUS_INIT
    status_description: str = ""
    scheduling_eligibility: str = NODE_SCHED_ELIGIBLE
    drain_strategy: Optional[DrainStrategy] = None

    http_addr: str = ""
    attributes: dict[str, str] = field(default_factory=dict)
    node_resources: NodeResources = field(default_factory=NodeResources)
    reserved_resources: NodeReservedResources = field(default_factory=NodeReservedResources)
    links: dict[str, str] = field(default_factory=dict)
    meta: dict[str, str] = field(default_factory=dict)
    host_volumes: dict[str, HostVolumeInfo] = field(default_factory=dict)
    csi_node_plugins: dict[str, dict] = field(default_factory=dict)
    csi_controller_plugins: dict[str, dict] = field(default_factory=dict)
    drivers: dict[str, "DriverInfo"] = field(default_factory=dict)
    events: list[NodeEvent] = field(default_factory=list)

    computed_class: str = ""
    status_updated_at: float = 0.0
    # flap damping (ISSUE 10, docs/NODE_FAILURE.md): while nonzero, the
    # node was held ineligible by the leader's flap damper until this
    # wall-clock deadline. Rides raft (NODE_UPDATE_ELIGIBILITY payload)
    # so a NEW leader re-admits nodes a deposed damper held; operator
    # eligibility writes clear it.
    flap_held_until: float = 0.0
    create_index: int = 0
    modify_index: int = 0

    # ---- lifecycle predicates (ref structs.go Node.Ready / Canonicalize) ----

    def ready(self) -> bool:
        return (self.status == NODE_STATUS_READY
                and self.drain_strategy is None
                and self.scheduling_eligibility == NODE_SCHED_ELIGIBLE)

    @property
    def drain(self) -> bool:
        return self.drain_strategy is not None

    def terminal_status(self) -> bool:
        return self.status == NODE_STATUS_DOWN

    def copy(self) -> "Node":
        return dataclasses.replace(
            self,
            attributes=dict(self.attributes),
            meta=dict(self.meta),
            links=dict(self.links),
            host_volumes=dict(self.host_volumes),
            drivers=dict(self.drivers),
            events=list(self.events),
            node_resources=self.node_resources.copy(),
            reserved_resources=dataclasses.replace(self.reserved_resources),
            drain_strategy=(dataclasses.replace(self.drain_strategy)
                            if self.drain_strategy else None),
        )

    def comparable_resources(self) -> ComparableResources:
        return self.node_resources.comparable()

    def comparable_reserved_resources(self) -> ComparableResources:
        return self.reserved_resources.comparable()

    # ---- computed node class (ref nomad/structs/node_class.go) ----

    def compute_class(self) -> None:
        """Hash of the scheduling-relevant fields. Nodes with equal computed
        class are interchangeable for feasibility, enabling the per-class
        eligibility cache (ref scheduler/context.go:190) and blocked-eval
        unblocking keyed by class (ref nomad/blocked_evals.go)."""
        h = hashlib.sha1()
        h.update(self.datacenter.encode())
        h.update(self.node_class.encode())
        for k in sorted(self.attributes):
            if k.startswith("unique."):
                continue
            h.update(k.encode())
            h.update(str(self.attributes[k]).encode())
        for k in sorted(self.meta):
            if k.startswith("unique."):
                continue
            h.update(k.encode())
            h.update(str(self.meta[k]).encode())
        for d in sorted(self.drivers):
            info = self.drivers[d]
            h.update(d.encode())
            h.update(b"1" if info.detected else b"0")
            h.update(b"1" if info.healthy else b"0")
        cpu = self.node_resources.cpu
        h.update(str(cpu.cpu_shares).encode())
        h.update(str(self.node_resources.memory.memory_mb).encode())
        h.update(str(self.node_resources.disk.disk_mb).encode())
        for dev in self.node_resources.devices:
            h.update("/".join(dev.id_tuple()).encode())
            h.update(str(len(dev.instances)).encode())
        for name in sorted(self.host_volumes):
            h.update(name.encode())
        self.computed_class = "v1:" + h.hexdigest()[:16]


@dataclass
class DriverInfo:
    detected: bool = False
    healthy: bool = False
    health_description: str = ""
    attributes: dict[str, str] = field(default_factory=dict)
    update_time: float = 0.0
