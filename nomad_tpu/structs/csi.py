"""CSI volume + plugin model (ref nomad/structs/csi.go: CSIVolume,
CSIPlugin, CSIVolumeClaim; state tables ref nomad/state/schema.go
csi_volumes / csi_plugins).

Plugins are not stored directly — they are derived: every node that
fingerprints a CSI plugin (node.csi_node_plugins / csi_controller_plugins)
contributes to the plugin's aggregated health counts, exactly like the
reference's CSIPlugin.AddPlugin/DeleteNode bookkeeping.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

# access modes (ref csi.go CSIVolumeAccessMode)
ACCESS_MODE_SINGLE_NODE_READER = "single-node-reader-only"
ACCESS_MODE_SINGLE_NODE_WRITER = "single-node-writer"
ACCESS_MODE_MULTI_NODE_READER = "multi-node-reader-only"
ACCESS_MODE_MULTI_NODE_SINGLE_WRITER = "multi-node-single-writer"
ACCESS_MODE_MULTI_NODE_MULTI_WRITER = "multi-node-multi-writer"

# attachment modes (ref csi.go CSIVolumeAttachmentMode)
ATTACHMENT_MODE_BLOCK = "block-device"
ATTACHMENT_MODE_FS = "file-system"

# claim modes
CLAIM_READ = "read"
CLAIM_WRITE = "write"

# claim states (ref csi.go CSIVolumeClaimState)
CLAIM_STATE_TAKEN = "taken"
CLAIM_STATE_NODE_DETACHED = "node-detached"
CLAIM_STATE_CONTROLLER_DETACHED = "controller-detached"
CLAIM_STATE_READY_TO_FREE = "ready-to-free"


@dataclass
class CSIVolumeClaim:
    """One alloc's claim on a volume (ref csi.go CSIVolumeClaim)."""
    alloc_id: str = ""
    node_id: str = ""
    mode: str = CLAIM_READ
    state: str = CLAIM_STATE_TAKEN

    def copy(self) -> "CSIVolumeClaim":
        return dataclasses.replace(self)


@dataclass
class CSIVolume:
    """ref csi.go CSIVolume"""
    id: str = ""
    namespace: str = "default"
    name: str = ""
    external_id: str = ""
    plugin_id: str = ""
    access_mode: str = ACCESS_MODE_SINGLE_NODE_WRITER
    attachment_mode: str = ATTACHMENT_MODE_FS
    mount_options: dict = field(default_factory=dict)
    secrets: dict = field(default_factory=dict)
    parameters: dict = field(default_factory=dict)
    context: dict = field(default_factory=dict)
    capacity_min_bytes: int = 0
    capacity_max_bytes: int = 0
    # claims: alloc_id -> CSIVolumeClaim
    read_claims: dict[str, CSIVolumeClaim] = field(default_factory=dict)
    write_claims: dict[str, CSIVolumeClaim] = field(default_factory=dict)
    # plugin health rollup, denormalized at read time
    schedulable: bool = True
    controller_required: bool = False
    controllers_healthy: int = 0
    nodes_healthy: int = 0
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "CSIVolume":
        return dataclasses.replace(
            self,
            mount_options=dict(self.mount_options),
            secrets=dict(self.secrets),
            parameters=dict(self.parameters),
            context=dict(self.context),
            read_claims={k: v.copy() for k, v in self.read_claims.items()},
            write_claims={k: v.copy() for k, v in self.write_claims.items()},
        )

    # ------------------------------------------------------------- claims

    def write_free(self) -> bool:
        """ref csi.go WriteFreeClaims"""
        if self.access_mode in (ACCESS_MODE_SINGLE_NODE_WRITER,
                                ACCESS_MODE_MULTI_NODE_SINGLE_WRITER):
            return len(self.write_claims) == 0
        if self.access_mode == ACCESS_MODE_MULTI_NODE_MULTI_WRITER:
            return True
        return False

    def read_allowed(self) -> bool:
        return self.access_mode in (
            ACCESS_MODE_SINGLE_NODE_READER, ACCESS_MODE_MULTI_NODE_READER,
            ACCESS_MODE_MULTI_NODE_SINGLE_WRITER,
            ACCESS_MODE_MULTI_NODE_MULTI_WRITER,
            ACCESS_MODE_SINGLE_NODE_WRITER)

    def claim_ok(self, mode: str) -> bool:
        """ref csi.go CSIVolume.Claim* checks"""
        if mode == CLAIM_WRITE:
            return self.write_free()
        return self.read_allowed()

    def in_use(self) -> bool:
        return bool(self.read_claims or self.write_claims)


@dataclass
class CSIPlugin:
    """Aggregated plugin health across the fleet (ref csi.go CSIPlugin)."""
    id: str = ""
    provider: str = ""
    version: str = ""
    controller_required: bool = False
    # node_id -> healthy
    controllers: dict[str, bool] = field(default_factory=dict)
    nodes: dict[str, bool] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "CSIPlugin":
        return dataclasses.replace(self, controllers=dict(self.controllers),
                                   nodes=dict(self.nodes))

    @property
    def controllers_healthy(self) -> int:
        return sum(1 for h in self.controllers.values() if h)

    @property
    def nodes_healthy(self) -> int:
        return sum(1 for h in self.nodes.values() if h)

    def is_empty(self) -> bool:
        return not self.controllers and not self.nodes


def volume_stub(vol: CSIVolume) -> dict:
    """List-endpoint projection (ref structs.CSIVolListStub)."""
    return {
        "ID": vol.id, "Namespace": vol.namespace, "Name": vol.name,
        "PluginID": vol.plugin_id, "Schedulable": vol.schedulable,
        "AccessMode": vol.access_mode, "AttachmentMode": vol.attachment_mode,
        "CurrentReaders": len(vol.read_claims),
        "CurrentWriters": len(vol.write_claims),
        "ControllerRequired": vol.controller_required,
        "ControllersHealthy": vol.controllers_healthy,
        "NodesHealthy": vol.nodes_healthy,
        "CreateIndex": vol.create_index, "ModifyIndex": vol.modify_index,
    }


def plugin_stub(p: CSIPlugin) -> dict:
    """ref structs.CSIPluginListStub"""
    return {
        "ID": p.id, "Provider": p.provider, "Version": p.version,
        "ControllerRequired": p.controller_required,
        "ControllersHealthy": p.controllers_healthy,
        "ControllersExpected": len(p.controllers),
        "NodesHealthy": p.nodes_healthy, "NodesExpected": len(p.nodes),
        "CreateIndex": p.create_index, "ModifyIndex": p.modify_index,
    }
