"""Scaling policy state model (ref nomad/structs/structs.go ScalingPolicy
and ScalingEvent; state table ref nomad/state/schema.go scaling_policy /
scaling_event tables).

The jobspec-side `scaling` block (structs/job.py ScalingPolicy) is the ask;
these are the server-side records: a policy row per task group target kept in
the state store, and an event trail per (job, group) recording every scale
action (ref nomad/structs/structs.go JobScaleStatus).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

import uuid

from .eval import new_id

SCALING_TARGET_NAMESPACE = "Namespace"
SCALING_TARGET_JOB = "Job"
SCALING_TARGET_GROUP = "Group"

SCALING_POLICY_TYPE_HORIZONTAL = "horizontal"

# cap on retained scaling events per task group
# (ref nomad/structs/structs.go JobTrackedScalingEvents)
JOB_TRACKED_SCALING_EVENTS = 20


@dataclass
class ScalingPolicyState:
    """A stored scaling policy row (ref structs.go ScalingPolicy)."""
    id: str = field(default_factory=new_id)
    type: str = SCALING_POLICY_TYPE_HORIZONTAL
    target: dict[str, str] = field(default_factory=dict)
    min: int = 0
    max: int = 0
    policy: dict = field(default_factory=dict)
    enabled: bool = True
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "ScalingPolicyState":
        return dataclasses.replace(
            self, target=dict(self.target), policy=dict(self.policy))

    def target_key(self) -> tuple[str, str, str]:
        return (self.target.get(SCALING_TARGET_NAMESPACE, ""),
                self.target.get(SCALING_TARGET_JOB, ""),
                self.target.get(SCALING_TARGET_GROUP, ""))


def policy_from_group(job, tg) -> "ScalingPolicyState | None":
    """Lower a task group's jobspec scaling block into a stored policy row
    (ref structs.go TaskGroup.GetScalingPolicies)."""
    if tg.scaling is None:
        return None
    # deterministic id: policy rows are created inside FSM apply, so a
    # random uuid would diverge across raft replicas/replays
    pid = str(uuid.uuid5(uuid.NAMESPACE_OID,
                         f"scaling/{job.namespace}/{job.id}/{tg.name}"))
    return ScalingPolicyState(
        id=pid,
        type=tg.scaling.type or SCALING_POLICY_TYPE_HORIZONTAL,
        target={
            SCALING_TARGET_NAMESPACE: job.namespace,
            SCALING_TARGET_JOB: job.id,
            SCALING_TARGET_GROUP: tg.name,
        },
        min=tg.scaling.min,
        max=tg.scaling.max,
        policy=dict(tg.scaling.policy),
        enabled=tg.scaling.enabled,
    )


@dataclass
class ScalingEvent:
    """One scale action on a task group (ref structs.go ScalingEvent)."""
    time: float = 0.0
    count: int | None = None
    previous_count: int = 0
    message: str = ""
    error: bool = False
    meta: dict = field(default_factory=dict)
    eval_id: str = ""
    create_index: int = 0

    def copy(self) -> "ScalingEvent":
        return dataclasses.replace(self, meta=dict(self.meta))
