"""Operator-mutable scheduler configuration (ref nomad/structs/operator.go:131-180).

This is the extension point where the TPU solver registers as a scheduler
algorithm alongside classic binpack/spread: SURVEY.md north star.
"""
from __future__ import annotations

from dataclasses import dataclass, field

SCHED_ALG_BINPACK = "binpack"
SCHED_ALG_SPREAD = "spread"
SCHED_ALG_TPU = "tpu-batch"   # the new one: batched JAX/XLA solve
SCHED_ALG_CONVEX = "convex"   # ISSUE 19: global projected-gradient solve

VALID_SCHEDULER_ALGORITHMS = (SCHED_ALG_BINPACK, SCHED_ALG_SPREAD,
                              SCHED_ALG_TPU, SCHED_ALG_CONVEX)


@dataclass
class PreemptionConfig:
    """Per-scheduler preemption toggles (ref operator.go PreemptionConfig)."""
    system_scheduler_enabled: bool = True
    sysbatch_scheduler_enabled: bool = False
    batch_scheduler_enabled: bool = False
    service_scheduler_enabled: bool = False


@dataclass
class SchedulerConfiguration:
    """Raft-replicated, runtime-mutable scheduler config
    (ref operator.go:144, set via /v1/operator/scheduler/configuration).

    The tpu-batch knobs ride the same hot-reload path as
    `scheduler_algorithm`: a SCHEDULER_CONFIG log entry replaces the
    stored config, and every eval reads the latest copy through its
    EvalContext — no restart, no cache to bust.

      preemption_config       per-scheduler-kind preemption switches
                              (system/sysbatch/batch/service) the
                              planner consults before evicting victims.
      memory_oversubscription_enabled
                              allow tasks to exceed their memory reserve
                              up to the node max (ref behavior); off =
                              reserve is the hard cap at placement time.
      reject_job_registration drain valve: refuse new job registrations
                              (writes) while the cluster sheds load —
                              reads and in-flight work are untouched.
      pause_eval_broker       stop the broker handing evals to workers
                              (dequeue returns empty); enqueued work
                              parks until unpaused. Operator brownout
                              lever, not a data-path state.
      plan_pipeline_enabled   pipelined plan lifecycle: chunk the solve,
                              dispatch chunk N+1 on the accelerator while
                              the host materializes/evaluates/commits
                              chunk N. False forces the serial path.
      plan_pipeline_chunks    how many chunks a pipelined eval splits
                              into; 1 means stay serial (a one-chunk
                              pipeline commits nothing early).
      plan_pipeline_min_count below this many placements an eval stays
                              serial (chunking overhead beats the overlap).
      eval_batch_enabled      eval-stream micro-batching: small depth
                              solves on a TPU coalesce into one padded
                              batched dispatch instead of the host tier.
      eval_batch_window_ms    how long the first pending solve waits for
                              siblings before dispatching the batch.
      plan_commit_batch_max   how many verified pending plans the serial
                              applier may drain into ONE raft entry / FSM
                              batch apply (cross-eval commit coalescing);
                              1 means the pre-coalescing serial path.
      plan_commit_timeout_s   the raft-apply budget for a WHOLE commit
                              batch (not per message) — on exhaustion
                              every plan of the batch fails with a
                              `nomad.plan.commit_timeout` count instead
                              of serially starving the queue.
      plan_commit_window_ms   how long the applier lingers for more
                              arrivals after a partial drain — engages
                              ONLY while more evals than drained plans
                              are in flight (the micro-batcher's signal),
                              so a lone plan never waits.
      telemetry_trace_enabled span-based eval tracing (nomad_tpu/obs/):
                              False makes every instrumentation site a
                              cheap no-op. NOMAD_TRACE=0/1 env overrides
                              either way (docs/OBSERVABILITY.md).
      telemetry_trace_sample  head-based sampling rate in [0,1] for
                              HEALTHY traces; traces ending non-ok
                              (faulted, failed, leadership lost) are
                              always retained regardless.
      telemetry_trace_capacity  how many completed traces the bounded
                              in-memory store keeps for /v1/traces.
      ingress_write_rate      token-bucket admission rate (requests/s)
                              for write endpoints at the HTTP/RPC front
                              doors; over-rate callers get 429 +
                              Retry-After before any state is touched.
                              0 disables the class (docs/OVERLOAD.md).
      ingress_read_rate       same, for non-blocking reads.
      ingress_blocking_rate   same, for blocking queries (?index=&wait=).
      ingress_burst_s         bucket capacity in seconds of rate: a
                              bucket holds rate*burst_s tokens, so short
                              bursts up to that size are admitted even
                              at the sustained limit.
      broker_depth_cap        eval-broker backlog ceiling (ready +
                              job-pending + delayed). On overflow the
                              LOWEST-priority pending eval is shed into
                              the failed-eval backoff lifecycle (never
                              core/system evals); 0 = unbounded (the
                              pre-overload-layer behavior).
      eval_deadline_s         enqueue TTL stamped on evals entering the
                              broker: workers drop expired evals before
                              the solve, the plan applier rejects past-
                              deadline plans before the raft round
                              (goodput over throughput). 0 = no TTL.
      pressure_saturated_frac fraction of broker_depth_cap at which the
                              pressure state leaves `ok` (brownout:
                              wider micro-batch window, trace sampling
                              downshift, shorter blocking queries).
      heartbeat_invalidate_rate_cap
                              max expired nodes one heartbeat sweep may
                              flip down (one BATCH_NODE_UPDATE_STATUS
                              raft entry per sweep); overflow carries
                              over to the next sweep, so a mass
                              partition drains paced instead of as one
                              raft megaflood. 0 = uncapped
                              (docs/NODE_FAILURE.md).
      flap_damping_threshold  down->up cycles inside the window before a
                              node is held ineligible (flap damping);
                              0 disables damping entirely.
      flap_damping_window_s   sliding window the cycle count lives in.
      flap_damping_backoff_s  first hold duration; doubles per
                              subsequent flap episode.
      flap_damping_backoff_max_s   hold ceiling for chronic flappers.
      placement_explain_enabled   placement explainability (ISSUE 11):
                              the tensor solve keeps its per-stage
                              elimination reductions as a fixed-shape
                              device byproduct and materializes real
                              AllocMetric attribution for failed
                              placements. NOMAD_EXPLAIN=0/1 env
                              overrides either way; placements are
                              bit-identical on or off
                              (docs/OBSERVABILITY.md).
      placement_explain_recent  how many recent explain records the
                              bounded process ring retains for the
                              operator debug bundle.
      solver_fused_enabled    whole-eval device residency (ISSUE 15):
                              dispatch gather+solve+plan-verdict
                              (+explain) as ONE compiled program per
                              solve against the resident state-cache
                              twins — one device round trip per eval.
                              Placements are bit-identical on or off;
                              NOMAD_SOLVER_FUSED=0/1 overrides
                              (docs/BACKEND_TIERS.md).
      raft_fsync              fsync discipline for raft persistence
                              (ISSUE 13, docs/DURABILITY.md): `always`
                              fsyncs every append/meta/commit (the
                              no-acked-entry-lost contract), `interval`
                              paces append fsyncs at
                              raft_fsync_interval_ms while still
                              syncing commit points (manifest/meta/
                              snapshot), `never` trusts the page cache
                              (throughput over durability — a power
                              loss may forget acked entries; a plain
                              process crash still loses nothing).
                              Hot-reloadable; NOMAD_RAFT_FSYNC
                              (`mode` or `mode:interval_ms`) overrides
                              for bench legs.
      raft_fsync_interval_ms  append-fsync pacing for raft_fsync =
                              interval.
      raft_group_commit_max_entries
                              leader write-path group commit (ISSUE 20,
                              docs/DURABILITY.md): max proposals one
                              committer drain stages into a SINGLE WAL
                              append + fsync window. Self-clocking (no
                              timer): an idle leader still commits a
                              lone entry immediately. 1 = serial
                              one-entry-per-sync (the differential-test
                              oracle). Hot-reloadable;
                              NOMAD_RAFT_GROUP_COMMIT overrides for
                              bench legs and the crash fuzzer.
      raft_replicate_batch_max
                              max log entries one AppendEntries RPC
                              ships per follower round; the follower
                              persists the whole batch with ONE fsync
                              before acking (persist-before-ack at
                              batch granularity). Hot-reloadable;
                              NOMAD_RAFT_REPL_BATCH overrides.
      solver_convex_enabled   global convex placement tier (ISSUE 19):
                              with scheduler_algorithm = "convex", solve
                              the whole eval as ONE on-device projected-
                              gradient program (binpack/spread/affinity
                              objective + per-tenant quota budget +
                              namespace-stacking fairness), demoting to
                              the greedy ladder via the tier breaker on
                              any failure. False pins the greedy ladder
                              even under the convex algorithm;
                              NOMAD_SOLVER_CONVEX=0/1 env overrides
                              (docs/BACKEND_TIERS.md).
      solver_convex_max_iters projected-gradient iteration ceiling (the
                              `lax.while_loop` bound; convergence
                              usually stops the loop far earlier).
      solver_convex_tolerance relative objective-decrease threshold that
                              declares convergence.
      solver_convex_fairness_weight
                              weight of the namespace-stacking fairness
                              term in the objective; 0 solves pure
                              fragmentation.
      solver_convex_namespace_quota
                              per-tenant (namespace) running-instance
                              budget the convex solve hard-caps each
                              eval's placement count against; 0 = no
                              quota.
    """
    scheduler_algorithm: str = SCHED_ALG_BINPACK
    preemption_config: PreemptionConfig = field(default_factory=PreemptionConfig)
    memory_oversubscription_enabled: bool = False
    reject_job_registration: bool = False
    pause_eval_broker: bool = False
    plan_pipeline_enabled: bool = True
    plan_pipeline_chunks: int = 4
    plan_pipeline_min_count: int = 8192
    eval_batch_enabled: bool = True
    eval_batch_window_ms: float = 8.0
    plan_commit_batch_max: int = 32
    plan_commit_timeout_s: float = 30.0
    plan_commit_window_ms: float = 5.0
    telemetry_trace_enabled: bool = True
    telemetry_trace_sample: float = 1.0
    telemetry_trace_capacity: int = 2048
    ingress_write_rate: float = 0.0
    ingress_read_rate: float = 0.0
    ingress_blocking_rate: float = 0.0
    ingress_burst_s: float = 2.0
    broker_depth_cap: int = 8192
    eval_deadline_s: float = 0.0
    pressure_saturated_frac: float = 0.5
    heartbeat_invalidate_rate_cap: int = 4096
    flap_damping_threshold: int = 3
    flap_damping_window_s: float = 300.0
    flap_damping_backoff_s: float = 30.0
    flap_damping_backoff_max_s: float = 900.0
    placement_explain_enabled: bool = True
    placement_explain_recent: int = 256
    # whole-eval residency (ISSUE 15): fuse gather+solve+plan-verdict
    # (+explain) into ONE compiled dispatch against the state cache's
    # resident twins. Placements are bit-identical on or off;
    # NOMAD_SOLVER_FUSED=0/1 env force-overrides (bench parity legs).
    solver_fused_enabled: bool = True
    # global convex placement tier (ISSUE 19): cluster-wide allocation
    # as one on-device projected-gradient solve when the operator picks
    # scheduler_algorithm = "convex". All four knobs are runtime scalars
    # of the compiled program — hot-reloading them never recompiles.
    # NOMAD_SOLVER_CONVEX=0/1 env force-overrides (bench parity legs).
    solver_convex_enabled: bool = True
    solver_convex_max_iters: int = 200
    solver_convex_tolerance: float = 1e-4
    solver_convex_fairness_weight: float = 0.05
    solver_convex_namespace_quota: int = 0
    raft_fsync: str = "always"
    raft_fsync_interval_ms: float = 50.0
    raft_group_commit_max_entries: int = 64
    raft_replicate_batch_max: int = 1024
    create_index: int = 0
    modify_index: int = 0

    def effective_scheduler_algorithm(self) -> str:
        """ref operator.go:164 EffectiveSchedulerAlgorithm"""
        return self.scheduler_algorithm or SCHED_ALG_BINPACK

    def validate(self) -> str:
        if self.scheduler_algorithm not in VALID_SCHEDULER_ALGORITHMS:
            return (f"invalid scheduler algorithm {self.scheduler_algorithm!r}; "
                    f"must be one of {VALID_SCHEDULER_ALGORITHMS}")
        if self.plan_pipeline_chunks < 1:
            return "plan_pipeline_chunks must be >= 1"
        if self.plan_pipeline_min_count < 0:
            return "plan_pipeline_min_count must be >= 0"
        if self.eval_batch_window_ms < 0:
            return "eval_batch_window_ms must be >= 0"
        if self.plan_commit_batch_max < 1:
            return "plan_commit_batch_max must be >= 1"
        if self.plan_commit_timeout_s <= 0:
            return "plan_commit_timeout_s must be > 0"
        if self.plan_commit_window_ms < 0:
            return "plan_commit_window_ms must be >= 0"
        if not 0.0 <= self.telemetry_trace_sample <= 1.0:
            return "telemetry_trace_sample must be in [0, 1]"
        if self.telemetry_trace_capacity < 1:
            return "telemetry_trace_capacity must be >= 1"
        for knob in ("ingress_write_rate", "ingress_read_rate",
                     "ingress_blocking_rate"):
            if getattr(self, knob) < 0:
                return f"{knob} must be >= 0 (0 disables)"
        if self.ingress_burst_s <= 0:
            return "ingress_burst_s must be > 0"
        if self.broker_depth_cap < 0:
            return "broker_depth_cap must be >= 0 (0 = unbounded)"
        if self.eval_deadline_s < 0:
            return "eval_deadline_s must be >= 0 (0 = no deadline)"
        if not 0.0 < self.pressure_saturated_frac <= 1.0:
            return "pressure_saturated_frac must be in (0, 1]"
        if self.heartbeat_invalidate_rate_cap < 0:
            return "heartbeat_invalidate_rate_cap must be >= 0 (0 = uncapped)"
        if self.flap_damping_threshold < 0:
            return "flap_damping_threshold must be >= 0 (0 disables)"
        if self.flap_damping_window_s <= 0:
            return "flap_damping_window_s must be > 0"
        if self.flap_damping_backoff_s <= 0:
            return "flap_damping_backoff_s must be > 0"
        if self.flap_damping_backoff_max_s < self.flap_damping_backoff_s:
            return ("flap_damping_backoff_max_s must be >= "
                    "flap_damping_backoff_s")
        if self.placement_explain_recent < 1:
            return "placement_explain_recent must be >= 1"
        if self.solver_convex_max_iters < 1:
            return "solver_convex_max_iters must be >= 1"
        if self.solver_convex_tolerance <= 0:
            return "solver_convex_tolerance must be > 0"
        if self.solver_convex_fairness_weight < 0:
            return "solver_convex_fairness_weight must be >= 0"
        if self.solver_convex_namespace_quota < 0:
            return "solver_convex_namespace_quota must be >= 0 (0 = no quota)"
        if self.raft_fsync not in ("always", "interval", "never"):
            return ("raft_fsync must be one of 'always', 'interval', "
                    "'never'")
        if self.raft_fsync_interval_ms <= 0:
            return "raft_fsync_interval_ms must be > 0"
        if self.raft_group_commit_max_entries < 1:
            return "raft_group_commit_max_entries must be >= 1 (1 = serial)"
        if self.raft_replicate_batch_max < 1:
            return "raft_replicate_batch_max must be >= 1"
        return ""
