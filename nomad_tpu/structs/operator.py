"""Operator-mutable scheduler configuration (ref nomad/structs/operator.go:131-180).

This is the extension point where the TPU solver registers as a scheduler
algorithm alongside classic binpack/spread: SURVEY.md north star.
"""
from __future__ import annotations

from dataclasses import dataclass, field

SCHED_ALG_BINPACK = "binpack"
SCHED_ALG_SPREAD = "spread"
SCHED_ALG_TPU = "tpu-batch"   # the new one: batched JAX/XLA solve

VALID_SCHEDULER_ALGORITHMS = (SCHED_ALG_BINPACK, SCHED_ALG_SPREAD, SCHED_ALG_TPU)


@dataclass
class PreemptionConfig:
    """Per-scheduler preemption toggles (ref operator.go PreemptionConfig)."""
    system_scheduler_enabled: bool = True
    sysbatch_scheduler_enabled: bool = False
    batch_scheduler_enabled: bool = False
    service_scheduler_enabled: bool = False


@dataclass
class SchedulerConfiguration:
    """Raft-replicated, runtime-mutable scheduler config
    (ref operator.go:144, set via /v1/operator/scheduler/configuration)."""
    scheduler_algorithm: str = SCHED_ALG_BINPACK
    preemption_config: PreemptionConfig = field(default_factory=PreemptionConfig)
    memory_oversubscription_enabled: bool = False
    reject_job_registration: bool = False
    pause_eval_broker: bool = False
    create_index: int = 0
    modify_index: int = 0

    def effective_scheduler_algorithm(self) -> str:
        """ref operator.go:164 EffectiveSchedulerAlgorithm"""
        return self.scheduler_algorithm or SCHED_ALG_BINPACK

    def validate(self) -> str:
        if self.scheduler_algorithm not in VALID_SCHEDULER_ALGORITHMS:
            return (f"invalid scheduler algorithm {self.scheduler_algorithm!r}; "
                    f"must be one of {VALID_SCHEDULER_ALGORITHMS}")
        return ""
