"""Pooled copy-on-write AllocatedResources construction (ISSUE 5: the
zero-copy half of alloc materialization).

Every instance of a task group is identical up to its SEQUENTIAL
resources (ports, device instances, cpuset cores) — yet the placement
paths used to rebuild the whole AllocatedResources object tree per
allocation: one AllocatedSharedResources, one AllocatedTaskResources per
task, per alloc, 50k times for a 50k-task job. A `ResourceSkeleton` is
built once per task group and hands out:

  * for fully-simple groups (no networks/devices/cores anywhere): the ONE
    shared `AllocatedResources` — the exact sharing `_prepare_stamp` /
    `stamp_batch` already rely on (`structs/fastbatch.py`), now available
    to the per-alloc paths too;
  * for groups with sequential tasks: a shallow copy-on-write frame —
    fresh `AllocatedResources` + a fresh shared-resources row only when
    the group reserves networks, with the task dict PRE-SEEDED from the
    shared base rows. The caller replaces only the rows of tasks that
    carry per-alloc sequential state; simple tasks keep pointing at the
    shared base objects.

Sharing contract (same as fastbatch's): shared sub-objects are immutable
by convention — the state store's update paths copy before mutating, and
the usage index's `_xr_usage`/`_xr_seq` caches ride along for free (one
XR-row computation per task group instead of one per alloc).
"""
from __future__ import annotations

from .alloc import (
    AllocatedResources, AllocatedSharedResources, AllocatedTaskResources,
)


class ResourceSkeleton:
    """One task group's immutable resource base + CoW materializer."""

    __slots__ = ("tg", "oversub", "task_base", "seq_task_names", "simple",
                 "shared_total")

    def __init__(self, tg, oversub: bool):
        self.tg = tg
        self.oversub = bool(oversub)
        self.task_base: dict[str, AllocatedTaskResources] = {}
        self.seq_task_names: tuple[str, ...] = ()
        seq = []
        for task in tg.tasks:
            r = task.resources
            tr = AllocatedTaskResources(cpu_shares=r.cpu,
                                        memory_mb=r.memory_mb)
            if self.oversub:
                tr.memory_max_mb = r.memory_max_mb
            self.task_base[task.name] = tr
            if r.networks or r.devices or r.cores > 0:
                seq.append(task.name)
        self.seq_task_names = tuple(seq)
        self.simple = not tg.networks and not seq
        self.shared_total = AllocatedResources(
            shared=AllocatedSharedResources(
                disk_mb=tg.ephemeral_disk.size_mb),
            tasks=dict(self.task_base))

    def task_is_sequential(self, name: str) -> bool:
        return name in self.seq_task_names

    def materialize(self) -> AllocatedResources:
        """One alloc's AllocatedResources. Fully-simple groups share THE
        skeleton object (zero construction); anything else gets a CoW
        frame whose simple task rows still point at the shared base —
        the caller overwrites only the sequential rows it assigns."""
        if self.simple:
            return self.shared_total
        if self.tg.networks:
            shared = AllocatedSharedResources(
                disk_mb=self.tg.ephemeral_disk.size_mb)
        else:
            shared = self.shared_total.shared
        return AllocatedResources(shared=shared,
                                  tasks=dict(self.task_base))


def skeleton_for(cache: dict, tg, oversub: bool) -> ResourceSkeleton:
    """Get-or-build from a caller-owned cache (typically per-eval: task
    group objects are stable for an eval's lifetime). Keyed by identity —
    a job update hands the scheduler new TaskGroup objects, so a stale
    hit is impossible within one cache's lifetime."""
    key = (id(tg), bool(oversub))
    sk = cache.get(key)
    if sk is None or sk.tg is not tg:
        sk = cache[key] = ResourceSkeleton(tg, oversub)
    return sk
