"""Job diff for `job plan` (behavioral ref nomad/structs/diff.go — a field-
level diff of two job versions with Added/Deleted/Edited annotations,
grouped by task group and task).

Implemented as a generic recursive diff over the API (PascalCase dict)
representation rather than hand-written per-struct methods: the dataclass
model is uniform enough that one walker covers the whole tree.
"""
from __future__ import annotations

from typing import Any, Optional

from ..api_codec import to_api

DIFF_NONE = "None"
DIFF_ADDED = "Added"
DIFF_DELETED = "Deleted"
DIFF_EDITED = "Edited"

# fields excluded from diffs (server-maintained bookkeeping)
_IGNORED = {
    "Id", "ID", "Status", "StatusDescription", "Version", "SubmitTime",
    "CreateIndex", "ModifyIndex", "JobModifyIndex", "Stable", "Stop",
    "Dispatched", "ParentId", "ParentID", "NomadTokenId", "NomadTokenID",
    "VaultToken", "ConsulToken", "Payload",
}


def _scalar(v) -> bool:
    return not isinstance(v, (dict, list))


def _fmt(v) -> str:
    from ..jobspec.hcl import _to_string
    return _to_string(v)


def _field_diff(name: str, old, new, contextual: bool = False
                ) -> Optional[dict]:
    if old == new or (old in (None, "", 0, False, [], {})
                      and new in (None, "", 0, False, [], {})):
        if not contextual:
            return None
        # contextual mode (ref diff.go fieldDiff w/ contextual=true):
        # unchanged fields appear with Type None so `plan -verbose` can
        # show the full object, not just the delta.
        return {"Type": DIFF_NONE, "Name": name,
                "Old": _fmt(old), "New": _fmt(new)}
    typ = DIFF_EDITED
    if old in (None, "", [], {}):
        typ = DIFF_ADDED
    elif new in (None, "", [], {}):
        typ = DIFF_DELETED
    return {"Type": typ, "Name": name, "Old": _fmt(old), "New": _fmt(new)}


def _object_diff(name: str, old: Optional[dict], new: Optional[dict],
                 contextual: bool = False) -> Optional[dict]:
    """Diff two API dicts into {Type, Name, Fields, Objects}."""
    old = old or {}
    new = new or {}
    fields, objects = [], []
    changed = False
    for key in sorted(set(old) | set(new)):
        if key in _IGNORED:
            continue
        ov, nv = old.get(key), new.get(key)
        if _scalar(ov) and _scalar(nv):
            fd = _field_diff(key, ov, nv, contextual)
            if fd:
                fields.append(fd)
                changed = changed or fd["Type"] != DIFF_NONE
        elif isinstance(ov, dict) or isinstance(nv, dict):
            od = _object_diff(key, ov if isinstance(ov, dict) else None,
                              nv if isinstance(nv, dict) else None,
                              contextual)
            if od:
                objects.append(od)
                changed = changed or od["Type"] != DIFF_NONE
        else:   # lists
            ods = _list_diff(key, ov or [], nv or [], contextual)
            if ods:
                objects.extend(ods)
                changed = changed or any(
                    o["Type"] != DIFF_NONE for o in ods)
    if not changed:
        if not (contextual and (fields or objects)):
            return None
        return {"Type": DIFF_NONE, "Name": name, "Fields": fields,
                "Objects": objects}
    typ = DIFF_EDITED
    if not old:
        typ = DIFF_ADDED
    elif not new:
        typ = DIFF_DELETED
    return {"Type": typ, "Name": name, "Fields": fields, "Objects": objects}


def _list_key(item) -> str:
    if isinstance(item, dict):
        for k in ("Name", "Label", "Value", "LTarget", "Attribute",
                  "GetterSource", "DestPath", "Volume"):
            if item.get(k):
                return str(item[k])
        return str(sorted(item.items()))
    return str(item)


def _list_diff(name: str, old: list, new: list,
               contextual: bool = False) -> list[dict]:
    """Diff element lists keyed by a natural identity field."""
    out = []
    if all(_scalar(x) for x in old + new):
        olds, news = set(map(str, old)), set(map(str, new))
        for v in sorted(olds - news):
            out.append({"Type": DIFF_DELETED, "Name": name,
                        "Fields": [{"Type": DIFF_DELETED, "Name": name,
                                    "Old": v, "New": ""}], "Objects": []})
        for v in sorted(news - olds):
            out.append({"Type": DIFF_ADDED, "Name": name,
                        "Fields": [{"Type": DIFF_ADDED, "Name": name,
                                    "Old": "", "New": v}], "Objects": []})
        if contextual:
            for v in sorted(olds & news):
                out.append({"Type": DIFF_NONE, "Name": name,
                            "Fields": [{"Type": DIFF_NONE, "Name": name,
                                        "Old": v, "New": v}],
                            "Objects": []})
        return out
    om = {_list_key(x): x for x in old}
    nm = {_list_key(x): x for x in new}
    for key in sorted(set(om) | set(nm)):
        od = _object_diff(name, om.get(key), nm.get(key), contextual)
        if od:
            out.append(od)
    return out


def task_diff(old: Optional[dict], new: Optional[dict],
              contextual: bool = False) -> Optional[dict]:
    name = (new or old or {}).get("Name", "")
    d = _object_diff("Task", old, new, contextual)
    if d is None:
        return None
    d["Name"] = name
    d["Annotations"] = []
    return d


def task_group_diff(old: Optional[dict], new: Optional[dict],
                    contextual: bool = False) -> Optional[dict]:
    name = (new or old or {}).get("Name", "")
    old, new = dict(old or {}), dict(new or {})
    old_tasks = {t.get("Name"): t for t in old.pop("Tasks", None) or []}
    new_tasks = {t.get("Name"): t for t in new.pop("Tasks", None) or []}
    d = _object_diff("Group", old or None, new or None, contextual) or \
        {"Type": DIFF_NONE, "Name": "Group", "Fields": [], "Objects": []}
    tasks = []
    for tname in sorted(set(old_tasks) | set(new_tasks)):
        td = task_diff(old_tasks.get(tname), new_tasks.get(tname),
                       contextual)
        if td:
            tasks.append(td)
    if d["Type"] == DIFF_NONE and not contextual and \
            not any(t["Type"] != DIFF_NONE for t in tasks):
        return None
    typ = d["Type"]
    if not old and new:
        typ = DIFF_ADDED
    elif old and not new:
        typ = DIFF_DELETED
    elif typ == DIFF_NONE and any(t["Type"] != DIFF_NONE for t in tasks):
        typ = DIFF_EDITED
    return {"Type": typ, "Name": name, "Fields": d["Fields"],
            "Objects": d["Objects"], "Tasks": tasks, "Updates": {}}


def job_diff(old, new, contextual: bool = False) -> dict:
    """Diff two Job dataclasses (either may be None) into the JobDiff API
    shape consumed by `job plan` (ref structs/diff.go JobDiff).

    With contextual=True (the plan endpoint's mode, ref
    job_endpoint.go Plan → job.Diff(args.Job, true)), unchanged fields
    and objects are included with Type "None" so the CLI can render the
    full context under -verbose."""
    oapi = to_api(old) if old is not None else {}
    napi = to_api(new) if new is not None else {}
    job_id = (napi or oapi).get("Id") or (napi or oapi).get("ID", "")
    old_tgs = {g.get("Name"): g for g in oapi.pop("TaskGroups", None) or []}
    new_tgs = {g.get("Name"): g for g in napi.pop("TaskGroups", None) or []}
    top = _object_diff("Job", oapi or None, napi or None, contextual) or \
        {"Type": DIFF_NONE, "Fields": [], "Objects": []}
    tgs = []
    for name in sorted(set(old_tgs) | set(new_tgs)):
        tgd = task_group_diff(old_tgs.get(name), new_tgs.get(name),
                              contextual)
        if tgd:
            tgs.append(tgd)
    typ = top["Type"]
    if not oapi:
        typ = DIFF_ADDED
    elif not napi:
        typ = DIFF_DELETED
    elif typ == DIFF_NONE and any(t["Type"] != DIFF_NONE for t in tgs):
        typ = DIFF_EDITED
    return {"Type": typ, "ID": job_id, "Fields": top["Fields"],
            "Objects": top["Objects"], "TaskGroups": tgs}
