"""Job diff for `job plan` (behavioral ref nomad/structs/diff.go — a field-
level diff of two job versions with Added/Deleted/Edited annotations,
grouped by task group and task).

Implemented as a generic recursive diff over the API (PascalCase dict)
representation rather than hand-written per-struct methods: the dataclass
model is uniform enough that one walker covers the whole tree.
"""
from __future__ import annotations

from typing import Any, Optional

from ..api_codec import to_api

DIFF_NONE = "None"
DIFF_ADDED = "Added"
DIFF_DELETED = "Deleted"
DIFF_EDITED = "Edited"

# fields excluded from diffs (server-maintained bookkeeping)
_IGNORED = {
    "Id", "ID", "Status", "StatusDescription", "Version", "SubmitTime",
    "CreateIndex", "ModifyIndex", "JobModifyIndex", "Stable", "Stop",
    "Dispatched", "ParentId", "ParentID", "NomadTokenId", "NomadTokenID",
    "VaultToken", "ConsulToken", "Payload",
}


def _scalar(v) -> bool:
    return not isinstance(v, (dict, list))


def _fmt(v) -> str:
    from ..jobspec.hcl import _to_string
    return _to_string(v)


def _field_diff(name: str, old, new, contextual: bool = False
                ) -> Optional[dict]:
    if old == new or (old in (None, "", 0, False, [], {})
                      and new in (None, "", 0, False, [], {})):
        if not contextual:
            return None
        # contextual mode (ref diff.go fieldDiff w/ contextual=true):
        # unchanged fields appear with Type None so `plan -verbose` can
        # show the full object, not just the delta.
        return {"Type": DIFF_NONE, "Name": name,
                "Old": _fmt(old), "New": _fmt(new)}
    typ = DIFF_EDITED
    if old in (None, "", [], {}):
        typ = DIFF_ADDED
    elif new in (None, "", [], {}):
        typ = DIFF_DELETED
    return {"Type": typ, "Name": name, "Old": _fmt(old), "New": _fmt(new)}


def _object_diff(name: str, old: Optional[dict], new: Optional[dict],
                 contextual: bool = False) -> Optional[dict]:
    """Diff two API dicts into {Type, Name, Fields, Objects}."""
    old = old or {}
    new = new or {}
    fields, objects = [], []
    changed = False
    for key in sorted(set(old) | set(new)):
        if key in _IGNORED:
            continue
        ov, nv = old.get(key), new.get(key)
        if _scalar(ov) and _scalar(nv):
            fd = _field_diff(key, ov, nv, contextual)
            if fd:
                fields.append(fd)
                changed = changed or fd["Type"] != DIFF_NONE
        elif isinstance(ov, dict) or isinstance(nv, dict):
            od = _object_diff(key, ov if isinstance(ov, dict) else None,
                              nv if isinstance(nv, dict) else None,
                              contextual)
            if od:
                objects.append(od)
                changed = changed or od["Type"] != DIFF_NONE
        else:   # lists
            ods = _list_diff(key, ov or [], nv or [], contextual)
            if ods:
                objects.extend(ods)
                changed = changed or any(
                    o["Type"] != DIFF_NONE for o in ods)
    if not changed:
        if not (contextual and (fields or objects)):
            return None
        return {"Type": DIFF_NONE, "Name": name, "Fields": fields,
                "Objects": objects}
    typ = DIFF_EDITED
    if not old:
        typ = DIFF_ADDED
    elif not new:
        typ = DIFF_DELETED
    return {"Type": typ, "Name": name, "Fields": fields, "Objects": objects}


_IDENTITY_KEYS = ("Name", "Label", "Value", "LTarget", "Attribute",
                  "GetterSource", "DestPath", "Volume")


def _list_key(item) -> str:
    if isinstance(item, dict):
        for k in _IDENTITY_KEYS:
            if item.get(k):
                return str(item[k])
        return str(sorted(item.items()))
    return str(item)


def _has_identity(item) -> bool:
    return isinstance(item, dict) and any(
        item.get(k) for k in _IDENTITY_KEYS)


def _list_diff(name: str, old: list, new: list,
               contextual: bool = False) -> list[dict]:
    """Diff element lists keyed by a natural identity field."""
    out = []
    if all(_scalar(x) for x in old + new):
        olds, news = set(map(str, old)), set(map(str, new))
        for v in sorted(olds - news):
            out.append({"Type": DIFF_DELETED, "Name": name,
                        "Fields": [{"Type": DIFF_DELETED, "Name": name,
                                    "Old": v, "New": ""}], "Objects": []})
        for v in sorted(news - olds):
            out.append({"Type": DIFF_ADDED, "Name": name,
                        "Fields": [{"Type": DIFF_ADDED, "Name": name,
                                    "Old": "", "New": v}], "Objects": []})
        if contextual:
            for v in sorted(olds & news):
                out.append({"Type": DIFF_NONE, "Name": name,
                            "Fields": [{"Type": DIFF_NONE, "Name": name,
                                        "Old": v, "New": v}],
                            "Objects": []})
        return out
    om = {_list_key(x): x for x in old}
    nm = {_list_key(x): x for x in new}
    both = set(om) & set(nm)
    for key in sorted(both):
        od = _object_diff(name, om[key], nm[key], contextual)
        if od:
            out.append(od)
    # identity-LESS items (networks, unnamed checks) fall back to
    # content keys, where ANY edit changes the key: pair the leftover
    # old/new items by field similarity so an edit renders as ONE
    # Edited object with field-level deltas — the nested granularity
    # `nomad plan` shows. Items that DO carry a natural identity
    # (Name/Label/...) are never similarity-paired: a renamed service
    # is a destroy+create in the reference's keyed diffs (diff.go),
    # and rendering it as an in-place edit would hide that.
    left_old = [om[k] for k in sorted(set(om) - both)]
    left_new = [nm[k] for k in sorted(set(nm) - both)]
    used_new: set[int] = set()
    pairs: list[tuple] = []
    for o in left_old:
        best, best_sim = -1, 0.0
        if not _has_identity(o):
            for j, n in enumerate(left_new):
                if j in used_new or _has_identity(n):
                    continue
                sim = _similarity(o, n)
                if sim > best_sim:
                    best_sim, best = sim, j
        if best >= 0 and best_sim >= 0.5:
            used_new.add(best)
            pairs.append((o, left_new[best]))
        else:
            pairs.append((o, None))
    pairs += [(None, n) for j, n in enumerate(left_new)
              if j not in used_new]
    for o, n in pairs:
        od = _object_diff(name, o, n, contextual)
        if od:
            out.append(od)
    return out


def _similarity(a, b) -> float:
    """Fraction of (deep-)equal fields across the union of keys."""
    if not isinstance(a, dict) or not isinstance(b, dict):
        return 1.0 if a == b else 0.0
    keys = (set(a) | set(b)) - _IGNORED
    if not keys:
        return 1.0
    return sum(1 for k in keys if a.get(k) == b.get(k)) / len(keys)


def task_diff(old: Optional[dict], new: Optional[dict],
              contextual: bool = False) -> Optional[dict]:
    name = (new or old or {}).get("Name", "")
    d = _object_diff("Task", old, new, contextual)
    if d is None:
        return None
    d["Name"] = name
    d["Annotations"] = []
    return d


def task_group_diff(old: Optional[dict], new: Optional[dict],
                    contextual: bool = False) -> Optional[dict]:
    name = (new or old or {}).get("Name", "")
    old, new = dict(old or {}), dict(new or {})
    old_tasks = {t.get("Name"): t for t in old.pop("Tasks", None) or []}
    new_tasks = {t.get("Name"): t for t in new.pop("Tasks", None) or []}
    d = _object_diff("Group", old or None, new or None, contextual) or \
        {"Type": DIFF_NONE, "Name": "Group", "Fields": [], "Objects": []}
    tasks = []
    for tname in sorted(set(old_tasks) | set(new_tasks)):
        td = task_diff(old_tasks.get(tname), new_tasks.get(tname),
                       contextual)
        if td:
            tasks.append(td)
    if d["Type"] == DIFF_NONE and not contextual and \
            not any(t["Type"] != DIFF_NONE for t in tasks):
        return None
    typ = d["Type"]
    if not old and new:
        typ = DIFF_ADDED
    elif old and not new:
        typ = DIFF_DELETED
    elif typ == DIFF_NONE and any(t["Type"] != DIFF_NONE for t in tasks):
        typ = DIFF_EDITED
    return {"Type": typ, "Name": name, "Fields": d["Fields"],
            "Objects": d["Objects"], "Tasks": tasks, "Updates": {}}


def job_diff(old, new, contextual: bool = False) -> dict:
    """Diff two Job dataclasses (either may be None) into the JobDiff API
    shape consumed by `job plan` (ref structs/diff.go JobDiff).

    With contextual=True (the plan endpoint's mode, ref
    job_endpoint.go Plan → job.Diff(args.Job, true)), unchanged fields
    and objects are included with Type "None" so the CLI can render the
    full context under -verbose."""
    oapi = to_api(old) if old is not None else {}
    napi = to_api(new) if new is not None else {}
    job_id = (napi or oapi).get("Id") or (napi or oapi).get("ID", "")
    old_tgs = {g.get("Name"): g for g in oapi.pop("TaskGroups", None) or []}
    new_tgs = {g.get("Name"): g for g in napi.pop("TaskGroups", None) or []}
    top = _object_diff("Job", oapi or None, napi or None, contextual) or \
        {"Type": DIFF_NONE, "Fields": [], "Objects": []}
    tgs = []
    for name in sorted(set(old_tgs) | set(new_tgs)):
        tgd = task_group_diff(old_tgs.get(name), new_tgs.get(name),
                              contextual)
        if tgd:
            tgs.append(tgd)
    typ = top["Type"]
    if not oapi:
        typ = DIFF_ADDED
    elif not napi:
        typ = DIFF_DELETED
    elif typ == DIFF_NONE and any(t["Type"] != DIFF_NONE for t in tgs):
        typ = DIFF_EDITED
    return {"Type": typ, "ID": job_id, "Fields": top["Fields"],
            "Objects": top["Objects"], "TaskGroups": tgs}
