"""Evaluation model (ref nomad/structs/structs.go:10341).

An Evaluation is the unit of scheduler work: "something changed for job J,
re-assess its allocations". Evals flow through the EvalBroker to scheduler
workers and result in Plans.
"""
from __future__ import annotations

import dataclasses
import os
import uuid
from dataclasses import dataclass, field
from typing import Optional

EVAL_STATUS_BLOCKED = "blocked"
EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"
EVAL_STATUS_CANCELLED = "canceled"

TRIGGER_JOB_REGISTER = "job-register"
TRIGGER_JOB_DEREGISTER = "job-deregister"
TRIGGER_PERIODIC_JOB = "periodic-job"
TRIGGER_NODE_DRAIN = "node-drain"
TRIGGER_NODE_UPDATE = "node-update"
TRIGGER_ALLOC_STOP = "alloc-stop"
TRIGGER_SCHEDULED = "scheduled"
TRIGGER_ROLLING_UPDATE = "rolling-update"
TRIGGER_DEPLOYMENT_WATCHER = "deployment-watcher"
TRIGGER_FAILED_FOLLOW_UP = "failed-follow-up"
TRIGGER_MAX_PLANS = "max-plan-attempts"
TRIGGER_RETRY_FAILED_ALLOC = "alloc-failure"
TRIGGER_QUEUED_ALLOCS = "queued-allocs"
TRIGGER_PREEMPTION = "preemption"
TRIGGER_SCALING = "job-scaling"
TRIGGER_MAX_DISCONNECT = "max-disconnect-timeout"
TRIGGER_RECONNECT = "reconnect"

CORE_JOB_EVAL_GC = "eval-gc"
CORE_JOB_NODE_GC = "node-gc"
CORE_JOB_JOB_GC = "job-gc"
CORE_JOB_DEPLOYMENT_GC = "deployment-gc"
CORE_JOB_CSI_VOLUME_CLAIM_GC = "csi-volume-claim-gc"
CORE_JOB_FAILED_EVAL_REAP = "failed-eval-reap"
CORE_JOB_FORCE_GC = "force-gc"


def new_id() -> str:
    """Random UUIDv4-format id. Hand-formatted from urandom: ~7x faster
    than uuid.uuid4()+str, which matters when a 50k-alloc plan mints 50k
    ids inside the placement loop (ref helper/uuid/uuid.go Generate, which
    is likewise a raw-bytes formatter for the same reason)."""
    h = os.urandom(16).hex()
    return (f"{h[:8]}-{h[8:12]}-4{h[13:16]}-"
            f"{'89ab'[int(h[16], 16) & 3]}{h[17:20]}-{h[20:]}")


def new_ids(n: int) -> list[str]:
    """n random ids from ONE urandom read — the mass-placement path mints
    ids in batch to avoid n getrandom syscalls. The native formatter
    (native/allocstamp.c format_uuids) writes each ascii string directly
    (~50ns/id vs ~1.6us for the slicing formatter below)."""
    from .fastbatch import _load_native
    native = _load_native()
    if native:
        return native.format_uuids(os.urandom(16 * n), n)
    h = os.urandom(16 * n).hex()
    vr = "89ab"
    return [f"{s[:8]}-{s[8:12]}-4{s[13:16]}-"
            f"{vr[int(s[16], 16) & 3]}{s[17:20]}-{s[20:]}"
            for s in (h[i:i + 32] for i in range(0, 32 * n, 32))]


@dataclass
class Evaluation:
    id: str = field(default_factory=new_id)
    namespace: str = "default"
    priority: int = 50
    type: str = "service"            # scheduler type = job type
    triggered_by: str = TRIGGER_JOB_REGISTER
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    deployment_id: str = ""
    status: str = EVAL_STATUS_PENDING
    status_description: str = ""

    wait_sec: float = 0.0            # broker initial delay
    wait_until_unix: float = 0.0     # delayed eval absolute time
    # enqueue TTL (ISSUE 8): stamped by the broker from the hot-reloadable
    # eval_deadline_s config unless the creator set one; 0 = no deadline.
    # Workers drop expired evals BEFORE the solve; the plan applier
    # rejects past-deadline plans before they cost a raft round.
    deadline_unix: float = 0.0

    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    related_evals: list[str] = field(default_factory=list)

    # Blocked-eval bookkeeping (ref structs.go Evaluation + blocked_evals.go)
    class_eligibility: dict[str, bool] = field(default_factory=dict)
    quota_limit_reached: str = ""
    escaped_computed_class: bool = False

    failed_tg_allocs: dict[str, object] = field(default_factory=dict)  # tg -> AllocMetric
    queued_allocations: dict[str, int] = field(default_factory=dict)   # tg -> count
    # how many failed-follow-up generations precede this eval — drives
    # the reaper's capped exponential backoff (ISSUE 3 lifecycle)
    failed_follow_ups: int = 0
    annotate_plan: bool = False
    leader_ack: str = ""             # broker token for ack/nack

    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    create_time_unix: float = 0.0
    modify_time_unix: float = 0.0

    def copy(self) -> "Evaluation":
        return dataclasses.replace(
            self,
            related_evals=list(self.related_evals),
            class_eligibility=dict(self.class_eligibility),
            failed_tg_allocs=dict(self.failed_tg_allocs),
            queued_allocations=dict(self.queued_allocations),
        )

    def terminal_status(self) -> bool:
        return self.status in (EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED,
                               EVAL_STATUS_CANCELLED)

    def should_enqueue(self) -> bool:
        return self.status == EVAL_STATUS_PENDING

    def should_block(self) -> bool:
        return self.status == EVAL_STATUS_BLOCKED

    def make_plan(self, job) -> "Plan":
        from .plan import Plan
        return Plan(
            eval_id=self.id,
            priority=(job.priority if job else self.priority),
            job=job,
            all_at_once=(job.all_at_once if job else False),
            deadline_unix=self.deadline_unix,
        )

    def create_blocked_eval(self, classes: dict[str, bool], escaped: bool,
                            quota: str, failed_tg_allocs=None) -> "Evaluation":
        """Blocked-eval follow-up when placements fail
        (ref structs.go CreateBlockedEval)."""
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=TRIGGER_QUEUED_ALLOCS,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_BLOCKED,
            previous_eval=self.id,
            class_eligibility=dict(classes),
            escaped_computed_class=escaped,
            quota_limit_reached=quota,
            failed_tg_allocs=dict(failed_tg_allocs or {}),
        )

    def create_failed_follow_up_eval(self, wait_sec: float) -> "Evaluation":
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=TRIGGER_FAILED_FOLLOW_UP,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait_sec=wait_sec,
            previous_eval=self.id,
            failed_follow_ups=self.failed_follow_ups + 1,
        )
