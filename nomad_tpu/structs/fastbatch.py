"""Batch stamping of slots-dataclass instances (the materialize hot path).

Backed by the native extension (native/allocstamp.c) when built — slot
stores through pre-resolved member descriptors, no interpreter frames in
the loop — with a pure-Python fallback of identical semantics. Minting
50k Allocations drops from ~320ms (dataclass __init__) to ~15ms native
(VERDICT r3 #2; ref nomad/plan_apply.go:204, where Go pays pointer cost).

Sharing contract: fields NOT supplied by the caller are filled with ONE
shared default per class — including default_factory products (one dict,
one list, one DesiredTransition for the whole batch). That matches the
resources/metrics sharing the placer already does and is safe because
every consumer that mutates allocation state copies first (the state
store's copy-on-write update discipline, Allocation.copy()).
"""
from __future__ import annotations

import dataclasses
import glob
import os
from typing import Optional

_NATIVE = None


def _load_native():
    global _NATIVE
    if _NATIVE is not None:
        return _NATIVE
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    hits = glob.glob(os.path.join(root, "native", "nomad_allocstamp*.so"))
    if not hits:
        _NATIVE = False
        return False
    try:
        from importlib.machinery import ExtensionFileLoader
        from importlib.util import module_from_spec, spec_from_loader
        loader = ExtensionFileLoader("nomad_allocstamp", hits[0])
        spec = spec_from_loader("nomad_allocstamp", loader)
        mod = module_from_spec(spec)
        loader.exec_module(mod)
        _NATIVE = mod
    except Exception:
        _NATIVE = False
    return _NATIVE


_defaults_cache: dict = {}


def _class_defaults(cls) -> dict:
    """One shared default value per dataclass field (factories run ONCE —
    the sharing contract above)."""
    cached = _defaults_cache.get(cls)
    if cached is None:
        cached = {}
        for f in dataclasses.fields(cls):
            if f.default is not dataclasses.MISSING:
                cached[f.name] = f.default
            elif f.default_factory is not dataclasses.MISSING:
                cached[f.name] = f.default_factory()
        _defaults_cache[cls] = cached
    return cached


def stamp_batch(cls, n: int, shared: dict, varying: dict) -> list:
    """n instances of `cls`: `shared` fields on every instance, `varying`
    fields from per-index sequences, everything else from the shared
    class defaults. __init__ / __post_init__ are NOT run."""
    full = dict(_class_defaults(cls))
    full.update(shared)
    for name in varying:
        full.pop(name, None)
    native = _load_native()
    if native:
        return native.stamp_batch(cls, n, full, varying)
    # pure-Python fallback: identical semantics, interpreter-speed
    out = []
    new = cls.__new__
    items = list(full.items())
    vitems = list(varying.items())
    sa = object.__setattr__
    for i in range(n):
        obj = new(cls)
        for name, v in items:
            sa(obj, name, v)
        for name, seq in vitems:
            sa(obj, name, seq[i])
        out.append(obj)
    return out
