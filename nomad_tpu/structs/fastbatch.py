"""Batch stamping of slots-dataclass instances (the materialize hot path).

Backed by the native extension (native/allocstamp.c) when built — slot
stores through pre-resolved member descriptors, no interpreter frames in
the loop — with a pure-Python fallback of identical semantics. Minting
50k Allocations drops from ~320ms (dataclass __init__) to ~15ms native
(VERDICT r3 #2; ref nomad/plan_apply.go:204, where Go pays pointer cost).

Sharing contract: fields the CALLER supplies in `shared` are one object
for the whole batch (the resources/metrics sharing the placer does on
purpose — those are immutable by convention and the state store copies
before mutating). Unsupplied defaults are NOT shared when mutable: each
instance gets a fresh factory product for dict/list/set/dataclass
defaults, materialized lazily on first attribute access (ADVICE r4: one
shared task_states dict across 50k stored Allocations means a single
future in-place mutation corrupts cluster state; Go zero values are
per-struct, ref nomad/structs/structs.go). Lazy keeps stamping O(set
fields): eagerly minting 150k empty containers costs ~10x the stamp.
Immutable defaults (None, str, int, bool, float, tuple) stay shared.
"""
from __future__ import annotations

import dataclasses
import glob
import os
from typing import Optional

_NATIVE = None


def _load_native():
    global _NATIVE
    if _NATIVE is not None:
        return _NATIVE
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    hits = glob.glob(os.path.join(root, "native", "nomad_allocstamp*.so"))
    if not hits:
        _NATIVE = False
        return False
    try:
        from importlib.machinery import ExtensionFileLoader
        from importlib.util import module_from_spec, spec_from_loader
        loader = ExtensionFileLoader("nomad_allocstamp", hits[0])
        spec = spec_from_loader("nomad_allocstamp", loader)
        mod = module_from_spec(spec)
        loader.exec_module(mod)
        _NATIVE = mod
    except Exception:
        _NATIVE = False
    return _NATIVE


# cls -> (shared immutable defaults, {name: factory} for mutable factory
# defaults that must be materialized per instance)
_defaults_cache: dict = {}
# first-call initialization per class runs factories (arbitrary Python →
# GIL yields), so two RPC threads can race the __getattr__ install
import threading as _threading

_defaults_build_lock = _threading.Lock()


def _install_lazy_defaults(cls, factories: dict) -> None:
    """Class-level __getattr__ that materializes a FRESH factory product
    on first access of a slot stamp_batch left unset. Slots dataclasses
    raise AttributeError for unset slots, which routes here; normally
    constructed instances have every slot set, so this never fires for
    them. First-access races between threads can each build a product
    (last setattr wins) — both are fresh empties, and every mutating
    consumer holds the store lock, so this is benign."""
    if "__getattr__" in cls.__dict__:        # compose is unsupported; the
        raise TypeError(                      # structs define none today
            f"{cls.__name__} already defines __getattr__")

    def __getattr__(self, name, _f=factories):
        fac = _f.get(name)
        if fac is None:
            raise AttributeError(
                f"{type(self).__name__!r} object has no attribute {name!r}")
        v = fac()
        object.__setattr__(self, name, v)
        return v

    cls.__getattr__ = __getattr__


def _class_defaults(cls) -> tuple:
    cached = _defaults_cache.get(cls)
    if cached is None:
        with _defaults_build_lock:
            cached = _defaults_cache.get(cls)      # lost the build race?
            if cached is not None:
                return cached
            shared: dict = {}
            fresh: dict = {}
            for f in dataclasses.fields(cls):
                if f.default is not dataclasses.MISSING:
                    shared[f.name] = f.default
                elif f.default_factory is not dataclasses.MISSING:
                    probe = f.default_factory()
                    if (isinstance(probe, (dict, list, set))
                            or dataclasses.is_dataclass(probe)):
                        fresh[f.name] = f.default_factory
                    else:
                        shared[f.name] = probe
            if fresh:
                _install_lazy_defaults(cls, fresh)
            cached = (shared, fresh)
            _defaults_cache[cls] = cached
    return cached


def stamp_batch(cls, n: int, shared: dict, varying: dict) -> list:
    """n instances of `cls`: `shared` fields on every instance, `varying`
    fields from per-index sequences, everything else from class defaults.
    Mutable factory defaults are left UNSET and materialized fresh per
    instance on first access (lazy __getattr__, see _install_lazy_defaults).
    __init__ / __post_init__ are NOT run."""
    class_shared, _fresh = _class_defaults(cls)
    full = dict(class_shared)
    full.update(shared)
    for name in varying:
        full.pop(name, None)
    native = _load_native()
    if native:
        return native.stamp_batch(cls, n, full, varying)
    # pure-Python fallback: identical semantics, interpreter-speed
    out = []
    new = cls.__new__
    items = list(full.items())
    vitems = list(varying.items())
    sa = object.__setattr__
    for i in range(n):
        obj = new(cls)
        for name, v in items:
            sa(obj, name, v)
        for name, seq in vitems:
            sa(obj, name, seq[i])
        out.append(obj)
    return out
