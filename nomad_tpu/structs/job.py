"""Job specification model: Job → TaskGroup → Task plus scheduling directives
(ref nomad/structs/structs.go:4032 Job, :5997 TaskGroup, :6737 Task,
:8357 Constraint, :8477 Affinity, :8563 Spread).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional

from .resources import Resources, NetworkResource

# Job types (ref structs.go JobType*)
JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"
JOB_TYPE_SYSBATCH = "sysbatch"
JOB_TYPE_CORE = "_core"

# Job statuses
JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_DEAD = "dead"

JOB_MIN_PRIORITY = 1
JOB_DEFAULT_PRIORITY = 50
JOB_MAX_PRIORITY = 100
CORE_JOB_PRIORITY = JOB_MAX_PRIORITY * 2

DEFAULT_NAMESPACE = "default"

# Constraint operands (ref structs.go Constraint*)
OP_EQ = "="
OP_NEQ = "!="
OP_GT = ">"
OP_GTE = ">="
OP_LT = "<"
OP_LTE = "<="
OP_REGEX = "regexp"
OP_VERSION = "version"
OP_SEMVER = "semver"
OP_SET_CONTAINS = "set_contains"
OP_SET_CONTAINS_ALL = "set_contains_all"
OP_SET_CONTAINS_ANY = "set_contains_any"
OP_DISTINCT_HOSTS = "distinct_hosts"
OP_DISTINCT_PROPERTY = "distinct_property"
OP_IS_SET = "is_set"
OP_IS_NOT_SET = "is_not_set"


@dataclass
class Constraint:
    ltarget: str = ""     # attribute interpolation, e.g. "${attr.kernel.name}"
    rtarget: str = ""
    operand: str = OP_EQ

    def copy(self) -> "Constraint":
        return dataclasses.replace(self)

    def __str__(self) -> str:
        return f"{self.ltarget} {self.operand} {self.rtarget}"


@dataclass
class Affinity:
    ltarget: str = ""
    rtarget: str = ""
    operand: str = OP_EQ
    weight: int = 50      # [-100, 100]; negative = anti-affinity

    def copy(self) -> "Affinity":
        return dataclasses.replace(self)


@dataclass
class SpreadTarget:
    value: str = ""
    percent: int = 0


@dataclass
class Spread:
    attribute: str = ""
    weight: int = 50      # (0, 100]
    spread_target: list[SpreadTarget] = field(default_factory=list)

    def copy(self) -> "Spread":
        return dataclasses.replace(
            self, spread_target=[dataclasses.replace(t) for t in self.spread_target])


@dataclass
class RestartPolicy:
    """Client-side restart policy (ref structs.go RestartPolicy)."""
    attempts: int = 2
    interval_sec: float = 1800.0
    delay_sec: float = 15.0
    mode: str = "fail"    # fail | delay


@dataclass
class ReschedulePolicy:
    """Server-side reschedule policy (ref structs.go ReschedulePolicy)."""
    attempts: int = 0
    interval_sec: float = 0.0
    delay_sec: float = 30.0
    delay_function: str = "exponential"   # constant | exponential | fibonacci
    max_delay_sec: float = 3600.0
    unlimited: bool = True

    def should_reschedule(self) -> bool:
        return self.unlimited or (self.attempts > 0 and self.interval_sec > 0)


@dataclass
class UpdateStrategy:
    """Rolling-update / deployment strategy (ref structs.go UpdateStrategy)."""
    stagger_sec: float = 30.0
    max_parallel: int = 1
    health_check: str = "checks"          # checks | task_states | manual
    min_healthy_time_sec: float = 10.0
    healthy_deadline_sec: float = 300.0
    progress_deadline_sec: float = 600.0
    auto_revert: bool = False
    auto_promote: bool = False
    canary: int = 0

    def rolling(self) -> bool:
        return self.max_parallel > 0


@dataclass
class MigrateStrategy:
    """Drain migration strategy (ref structs.go MigrateStrategy)."""
    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_sec: float = 10.0
    healthy_deadline_sec: float = 300.0


@dataclass
class EphemeralDisk:
    sticky: bool = False
    size_mb: int = 300
    migrate: bool = False


@dataclass
class VolumeRequest:
    name: str = ""
    type: str = "host"        # host | csi
    source: str = ""
    read_only: bool = False
    access_mode: str = ""
    attachment_mode: str = ""
    per_alloc: bool = False


@dataclass
class VolumeMount:
    volume: str = ""
    destination: str = ""
    read_only: bool = False


@dataclass
class PeriodicConfig:
    """Cron-style launch config (ref structs.go PeriodicConfig)."""
    enabled: bool = False
    spec: str = ""
    spec_type: str = "cron"
    prohibit_overlap: bool = False
    timezone: str = "UTC"


@dataclass
class ParameterizedJobConfig:
    payload: str = "optional"     # optional | required | forbidden
    meta_required: list[str] = field(default_factory=list)
    meta_optional: list[str] = field(default_factory=list)


@dataclass
class DispatchPayloadConfig:
    file: str = ""


@dataclass
class TaskLifecycle:
    hook: str = ""                # prestart | poststart | poststop
    sidecar: bool = False


@dataclass
class TaskArtifact:
    getter_source: str = ""
    getter_options: dict[str, str] = field(default_factory=dict)
    relative_dest: str = ""


@dataclass
class Template:
    source_path: str = ""
    dest_path: str = ""
    embedded_tmpl: str = ""
    change_mode: str = "restart"   # restart | signal | noop
    change_signal: str = ""
    perms: str = "0644"


@dataclass
class Service:
    name: str = ""
    port_label: str = ""
    tags: list[str] = field(default_factory=list)
    checks: list[dict] = field(default_factory=list)
    connect: Optional[dict] = None
    provider: str = "builtin"      # builtin registry (consul-equivalent)


@dataclass
class LogConfig:
    max_files: int = 10
    max_file_size_mb: int = 10


@dataclass
class ScalingPolicy:
    min: int = 0
    max: int = 0
    enabled: bool = True
    policy: dict = field(default_factory=dict)
    type: str = "horizontal"


@dataclass
class Vault:
    """Task vault stanza (ref structs.go Vault): the policies the derived
    token carries and how the task reacts to token changes."""
    policies: list[str] = field(default_factory=list)
    env: bool = True                 # expose VAULT_TOKEN to the task
    change_mode: str = "restart"     # restart | signal | noop
    change_signal: str = ""
    namespace: str = ""


@dataclass
class Task:
    name: str = ""
    driver: str = ""
    user: str = ""
    config: dict = field(default_factory=dict)
    env: dict[str, str] = field(default_factory=dict)
    services: list[Service] = field(default_factory=list)
    resources: Resources = field(default_factory=Resources)
    meta: dict[str, str] = field(default_factory=dict)
    constraints: list[Constraint] = field(default_factory=list)
    affinities: list[Affinity] = field(default_factory=list)
    kill_timeout_sec: float = 5.0
    log_config: LogConfig = field(default_factory=LogConfig)
    artifacts: list[TaskArtifact] = field(default_factory=list)
    templates: list[Template] = field(default_factory=list)
    dispatch_payload: Optional[DispatchPayloadConfig] = None
    lifecycle: Optional[TaskLifecycle] = None
    volume_mounts: list[VolumeMount] = field(default_factory=list)
    leader: bool = False
    shutdown_delay_sec: float = 0.0
    kill_signal: str = ""
    vault: Optional[Vault] = None

    def copy(self) -> "Task":
        return dataclasses.replace(
            self,
            config=dict(self.config),
            env=dict(self.env),
            meta=dict(self.meta),
            services=list(self.services),
            resources=self.resources.copy(),
            constraints=[c.copy() for c in self.constraints],
            affinities=[a.copy() for a in self.affinities],
            artifacts=list(self.artifacts),
            templates=list(self.templates),
            volume_mounts=list(self.volume_mounts),
        )

    def is_prestart(self) -> bool:
        return self.lifecycle is not None and self.lifecycle.hook == "prestart"

    def is_poststart(self) -> bool:
        return self.lifecycle is not None and self.lifecycle.hook == "poststart"

    def is_poststop(self) -> bool:
        return self.lifecycle is not None and self.lifecycle.hook == "poststop"


@dataclass
class TaskGroup:
    name: str = ""
    count: int = 1
    constraints: list[Constraint] = field(default_factory=list)
    affinities: list[Affinity] = field(default_factory=list)
    spreads: list[Spread] = field(default_factory=list)
    tasks: list[Task] = field(default_factory=list)
    restart_policy: RestartPolicy = field(default_factory=RestartPolicy)
    reschedule_policy: Optional[ReschedulePolicy] = None
    update: Optional[UpdateStrategy] = None
    migrate: Optional[MigrateStrategy] = None
    networks: list[NetworkResource] = field(default_factory=list)
    services: list[Service] = field(default_factory=list)
    volumes: dict[str, VolumeRequest] = field(default_factory=dict)
    ephemeral_disk: EphemeralDisk = field(default_factory=EphemeralDisk)
    scaling: Optional[ScalingPolicy] = None
    stop_after_client_disconnect_sec: Optional[float] = None
    max_client_disconnect_sec: Optional[float] = None
    shutdown_delay_sec: float = 0.0
    meta: dict[str, str] = field(default_factory=dict)

    def copy(self) -> "TaskGroup":
        return dataclasses.replace(
            self,
            constraints=[c.copy() for c in self.constraints],
            affinities=[a.copy() for a in self.affinities],
            spreads=[s.copy() for s in self.spreads],
            tasks=[t.copy() for t in self.tasks],
            restart_policy=dataclasses.replace(self.restart_policy),
            reschedule_policy=(dataclasses.replace(self.reschedule_policy)
                               if self.reschedule_policy else None),
            update=dataclasses.replace(self.update) if self.update else None,
            migrate=dataclasses.replace(self.migrate) if self.migrate else None,
            networks=[n.copy() for n in self.networks],
            services=list(self.services),
            volumes=dict(self.volumes),
            meta=dict(self.meta),
        )

    def lookup_task(self, name: str) -> Optional[Task]:
        for t in self.tasks:
            if t.name == name:
                return t
        return None


@dataclass
class Multiregion:
    strategy: dict = field(default_factory=dict)
    regions: list[dict] = field(default_factory=list)


@dataclass
class Job:
    id: str = ""
    name: str = ""
    namespace: str = DEFAULT_NAMESPACE
    region: str = "global"
    type: str = JOB_TYPE_SERVICE
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    datacenters: list[str] = field(default_factory=lambda: ["dc1"])
    constraints: list[Constraint] = field(default_factory=list)
    affinities: list[Affinity] = field(default_factory=list)
    spreads: list[Spread] = field(default_factory=list)
    task_groups: list[TaskGroup] = field(default_factory=list)
    update: Optional[UpdateStrategy] = None
    multiregion: Optional[Multiregion] = None
    periodic: Optional[PeriodicConfig] = None
    parameterized: Optional[ParameterizedJobConfig] = None
    dispatched: bool = False
    payload: bytes = b""
    meta: dict[str, str] = field(default_factory=dict)
    consul_token: str = ""
    vault_token: str = ""
    vault_namespace: str = ""
    nomad_token_id: str = ""

    stop: bool = False
    parent_id: str = ""
    status: str = JOB_STATUS_PENDING
    status_description: str = ""
    stable: bool = False
    version: int = 0
    submit_time: float = 0.0
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0

    def copy(self) -> "Job":
        return dataclasses.replace(
            self,
            datacenters=list(self.datacenters),
            constraints=[c.copy() for c in self.constraints],
            affinities=[a.copy() for a in self.affinities],
            spreads=[s.copy() for s in self.spreads],
            task_groups=[tg.copy() for tg in self.task_groups],
            update=dataclasses.replace(self.update) if self.update else None,
            periodic=dataclasses.replace(self.periodic) if self.periodic else None,
            parameterized=(dataclasses.replace(self.parameterized)
                           if self.parameterized else None),
            meta=dict(self.meta),
        )

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def stopped(self) -> bool:
        return self.stop

    def is_periodic(self) -> bool:
        return self.periodic is not None and self.periodic.enabled

    def is_parameterized(self) -> bool:
        return self.parameterized is not None and not self.dispatched

    def has_update_strategy(self) -> bool:
        if self.type not in (JOB_TYPE_SERVICE,):
            return False
        for tg in self.task_groups:
            if tg.update is not None and tg.update.rolling():
                return True
        return False

    def ns_id(self) -> tuple[str, str]:
        return (self.namespace, self.id)


def alloc_name(job_id: str, group: str, index: int) -> str:
    """Canonical allocation name (ref structs.go AllocName)."""
    return f"{job_id}.{group}[{index}]"


def alloc_name_index(name: str) -> int:
    """Parse the trailing [index] out of an alloc name."""
    lb = name.rfind("[")
    rb = name.rfind("]")
    if lb == -1 or rb == -1 or rb < lb:
        return -1
    try:
        return int(name[lb + 1:rb])
    except ValueError:
        return -1
