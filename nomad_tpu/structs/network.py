"""NetworkIndex: per-node port-occupancy bitmaps and bandwidth accounting
(ref nomad/structs/network.go:37, AssignPorts:332, AssignNetwork:422).

Ports are the canonical "inherently sequential" resource (SURVEY.md hard part
3): the TPU solver does coarse feasibility (free-port counts, bandwidth as a
dense dimension), and exact assignment happens host-side here for the chosen
node. The bitmap is a numpy uint64 array so it can also be shipped to the
solver as lanes when needed.
"""
from __future__ import annotations

import random
from typing import Optional

import numpy as np

from .resources import NetworkResource, Port

MAX_VALID_PORT = 65536
DEFAULT_MIN_DYNAMIC_PORT = 20000
DEFAULT_MAX_DYNAMIC_PORT = 32000
_WORDS = MAX_VALID_PORT // 64


class Bitmap:
    """Fixed 65536-bit port bitmap over uint64 words."""

    __slots__ = ("words",)

    def __init__(self, words: Optional[np.ndarray] = None):
        self.words = words if words is not None else np.zeros(_WORDS, dtype=np.uint64)

    def set(self, i: int) -> None:
        self.words[i >> 6] |= np.uint64(1 << (i & 63))

    def unset(self, i: int) -> None:
        self.words[i >> 6] &= np.uint64(~(1 << (i & 63)) & 0xFFFFFFFFFFFFFFFF)

    def check(self, i: int) -> bool:
        return bool((int(self.words[i >> 6]) >> (i & 63)) & 1)

    def copy(self) -> "Bitmap":
        return Bitmap(self.words.copy())

    def free_count(self, lo: int, hi: int) -> int:
        """Vectorized popcount over [lo, hi] (solver feasibility path — must
        not be a per-bit Python loop)."""
        span = hi - lo + 1
        w_lo, w_hi = lo >> 6, hi >> 6
        words = self.words[w_lo:w_hi + 1].copy()
        lo_bits = lo & 63
        if lo_bits:
            words[0] &= np.uint64(~((1 << lo_bits) - 1) & 0xFFFFFFFFFFFFFFFF)
        hi_bits = hi & 63
        if hi_bits != 63:
            words[-1] &= np.uint64((1 << (hi_bits + 1)) - 1)
        used = int(np.unpackbits(words.view(np.uint8)).sum())
        return span - used


def parse_port_spec(spec: str) -> list[int]:
    """Parse "80,443,8000-8100" into a port list (ref helper ParsePortRanges)."""
    out: list[int] = []
    if not spec:
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    return out


class NetworkIndex:
    """Tracks port/bandwidth usage for one node across its allocations."""

    def __init__(self):
        self.task_networks: list[NetworkResource] = []
        self.group_networks: list[NetworkResource] = []
        self.host_networks: dict[str, list[str]] = {}   # name -> [device]
        self.used_ports: dict[str, Bitmap] = {}          # ip -> bitmap
        self.available_bandwidth: dict[str, int] = {}    # device -> mbits
        self.used_bandwidth: dict[str, int] = {}
        self.min_dynamic_port = DEFAULT_MIN_DYNAMIC_PORT
        self.max_dynamic_port = DEFAULT_MAX_DYNAMIC_PORT

    # ---- setup ----

    def set_node(self, node) -> bool:
        """Index the node's networks + statically reserved ports. Returns True
        on collision (ref network.go SetNode)."""
        collide = False
        for n in node.node_resources.networks:
            if n.device:
                self.available_bandwidth[n.device] = n.mbits
            if n.ip:
                self.used_ports.setdefault(n.ip, Bitmap())
                self.task_networks.append(n)
        reserved = parse_port_spec(node.reserved_resources.reserved_host_ports)
        for ip in list(self.used_ports):
            for p in reserved:
                if 0 < p < MAX_VALID_PORT:
                    if self.used_ports[ip].check(p):
                        collide = True
                    self.used_ports[ip].set(p)
        return collide

    def add_allocs(self, allocs) -> bool:
        collide = False
        for alloc in allocs:
            if alloc.server_terminal_status():
                continue
            res = alloc.allocated_resources
            # shared.ports is the flattened view OF shared.networks'
            # offer — reserve from one or the other, never both, or a
            # group-network alloc collides with itself (ref
            # structs/network.go AddAllocs: AllocatedPorts preferred,
            # networks as the pre-0.12 fallback)
            if res.shared.ports:
                for port in res.shared.ports:
                    if self._reserve_port(port.get("host_ip", ""),
                                          port.get("value", 0)):
                        collide = True
            else:
                for net in res.shared.networks:
                    if self.add_reserved(net):
                        collide = True
            for tr in res.tasks.values():
                for net in tr.networks:
                    if self.add_reserved(net):
                        collide = True
        return collide

    def add_reserved(self, net: NetworkResource) -> bool:
        collide = False
        for p in list(net.reserved_ports) + list(net.dynamic_ports):
            if self._reserve_port(net.ip, p.value):
                collide = True
        if net.device:
            self.used_bandwidth[net.device] = \
                self.used_bandwidth.get(net.device, 0) + net.mbits
        return collide

    def _reserve_port(self, ip: str, port: int) -> bool:
        if port <= 0 or port >= MAX_VALID_PORT:
            return False
        if ip not in self.used_ports:
            self.used_ports[ip] = Bitmap()
        if self.used_ports[ip].check(port):
            return True
        self.used_ports[ip].set(port)
        return False

    def overcommitted(self) -> bool:
        for dev, used in self.used_bandwidth.items():
            if used > self.available_bandwidth.get(dev, 0) > 0:
                return True
        return False

    # ---- assignment (ref network.go AssignPorts / AssignTaskNetwork) ----

    def assign_network(self, ask: NetworkResource,
                       rng: Optional[random.Random] = None
                       ) -> tuple[Optional[NetworkResource], str]:
        """Pick a host network satisfying the ask; assign static + dynamic
        ports. Returns (offer, error_reason)."""
        rng = rng or random.Random(0)
        if not self.task_networks:
            return None, "no networks available"
        err = "no networks available"
        for net in self.task_networks:
            if ask.mbits and net.device and \
               self.used_bandwidth.get(net.device, 0) + ask.mbits > \
               self.available_bandwidth.get(net.device, 0):
                err = "bandwidth exceeded"
                continue
            bitmap = self.used_ports.setdefault(net.ip, Bitmap())
            # static ports must be free
            ok = True
            for p in ask.reserved_ports:
                if bitmap.check(p.value):
                    ok = False
                    err = f"reserved port collision {p.label}={p.value}"
                    break
            if not ok:
                continue
            dyn_ports = self._pick_dynamic(bitmap,
                                           [p.value for p in ask.reserved_ports],
                                           len(ask.dynamic_ports), rng)
            if dyn_ports is None:
                err = "dynamic port selection failed"
                continue
            offer = NetworkResource(
                mode=ask.mode, device=net.device, ip=net.ip, mbits=ask.mbits,
                reserved_ports=[Port(p.label, p.value, p.to, p.host_network)
                                for p in ask.reserved_ports],
                dynamic_ports=[Port(p.label, dyn_ports[i], p.to, p.host_network)
                               for i, p in enumerate(ask.dynamic_ports)],
            )
            return offer, ""
        return None, err

    def _pick_dynamic(self, bitmap: Bitmap, taken: list[int], n: int,
                      rng: random.Random) -> Optional[list[int]]:
        if n == 0:
            return []
        picked: list[int] = []
        exclude = set(taken)
        # randomized probing, then linear fallback (ref network.go
        # getDynamicPortsStochastic/Precise)
        for _ in range(n * 20):
            if len(picked) == n:
                break
            p = rng.randint(self.min_dynamic_port, self.max_dynamic_port)
            if p in exclude or bitmap.check(p):
                continue
            picked.append(p)
            exclude.add(p)
        if len(picked) < n:
            for p in range(self.min_dynamic_port, self.max_dynamic_port + 1):
                if len(picked) == n:
                    break
                if p in exclude or bitmap.check(p):
                    continue
                picked.append(p)
                exclude.add(p)
        return picked if len(picked) == n else None

    def free_dynamic_port_count(self) -> int:
        """Coarse feasibility signal exported to the TPU solver."""
        if not self.used_ports:
            return self.max_dynamic_port - self.min_dynamic_port + 1
        bm = next(iter(self.used_ports.values()))
        return bm.free_count(self.min_dynamic_port, self.max_dynamic_port)

    def release(self) -> None:
        self.used_ports.clear()
        self.used_bandwidth.clear()
