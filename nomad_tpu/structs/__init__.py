"""Shared data model (ref nomad/structs/): the object twin of the solver's
dense tensor form. Everything above (state store, schedulers, server, client)
speaks these types."""
from .resources import (  # noqa: F401
    ComparableResources, DNSConfig, NetworkResource, NodeCpuResources,
    NodeDevice, NodeDeviceResource, NodeDiskResources, NodeMemoryResources,
    NodeNetworkResource, NodeReservedResources, NodeResources, Port,
    RequestedDevice, Resources, RESOURCE_DIMS, R_CPU, R_MEM, R_DISK,
    NUM_RESOURCE_DIMS, comparable_to_vector,
)
from .node import (  # noqa: F401
    DrainStrategy, DriverInfo, HostVolumeInfo, Node, NodeEvent,
    NODE_STATUS_DOWN, NODE_STATUS_INIT, NODE_STATUS_READY,
    NODE_STATUS_DISCONNECTED, NODE_SCHED_ELIGIBLE, NODE_SCHED_INELIGIBLE,
)
from .job import (  # noqa: F401
    Affinity, Constraint, DispatchPayloadConfig, EphemeralDisk, Job, LogConfig,
    MigrateStrategy, Multiregion, ParameterizedJobConfig, PeriodicConfig,
    ReschedulePolicy, RestartPolicy, ScalingPolicy, Service, Spread,
    SpreadTarget, Task, TaskArtifact, TaskGroup, TaskLifecycle, Template,
    UpdateStrategy, Vault, VolumeMount, VolumeRequest,
    JOB_TYPE_SERVICE, JOB_TYPE_BATCH, JOB_TYPE_SYSTEM, JOB_TYPE_SYSBATCH,
    JOB_TYPE_CORE, JOB_STATUS_PENDING, JOB_STATUS_RUNNING, JOB_STATUS_DEAD,
    JOB_DEFAULT_PRIORITY, JOB_MIN_PRIORITY, JOB_MAX_PRIORITY, CORE_JOB_PRIORITY,
    DEFAULT_NAMESPACE, OP_EQ, OP_NEQ, OP_GT, OP_GTE, OP_LT, OP_LTE, OP_REGEX,
    OP_VERSION, OP_SEMVER, OP_SET_CONTAINS, OP_SET_CONTAINS_ALL,
    OP_SET_CONTAINS_ANY, OP_DISTINCT_HOSTS, OP_DISTINCT_PROPERTY, OP_IS_SET,
    OP_IS_NOT_SET, alloc_name, alloc_name_index,
)
from .alloc import (  # noqa: F401
    AllocDeploymentStatus, AllocMetric, AllocatedDeviceResource,
    AllocatedResources, AllocatedSharedResources, AllocatedTaskResources,
    Allocation, DesiredTransition, NetworkStatus, RescheduleEvent,
    RescheduleTracker, TaskEvent, TaskState,
    ALLOC_DESIRED_RUN, ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT,
    ALLOC_CLIENT_PENDING, ALLOC_CLIENT_RUNNING, ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST, ALLOC_CLIENT_UNKNOWN,
    TASK_STATE_PENDING, TASK_STATE_RUNNING, TASK_STATE_DEAD,
    DESC_RESCHEDULED, DESC_NOT_NEEDED, DESC_MIGRATING, DESC_CANARY,
    DESC_NODE_TAINTED, DESC_PREEMPTED, filter_terminal_allocs,
)
from .eval import (  # noqa: F401
    Evaluation, new_id, new_ids,
    EVAL_STATUS_BLOCKED, EVAL_STATUS_PENDING, EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED, EVAL_STATUS_CANCELLED,
    TRIGGER_JOB_REGISTER, TRIGGER_JOB_DEREGISTER, TRIGGER_PERIODIC_JOB,
    TRIGGER_NODE_DRAIN, TRIGGER_NODE_UPDATE, TRIGGER_ALLOC_STOP,
    TRIGGER_SCHEDULED, TRIGGER_ROLLING_UPDATE, TRIGGER_DEPLOYMENT_WATCHER,
    TRIGGER_FAILED_FOLLOW_UP, TRIGGER_MAX_PLANS, TRIGGER_RETRY_FAILED_ALLOC,
    TRIGGER_QUEUED_ALLOCS, TRIGGER_PREEMPTION, TRIGGER_SCALING,
    TRIGGER_MAX_DISCONNECT, TRIGGER_RECONNECT,
    CORE_JOB_EVAL_GC, CORE_JOB_NODE_GC, CORE_JOB_JOB_GC,
    CORE_JOB_DEPLOYMENT_GC, CORE_JOB_CSI_VOLUME_CLAIM_GC,
    CORE_JOB_FAILED_EVAL_REAP, CORE_JOB_FORCE_GC,
)
from .plan import (  # noqa: F401
    Deployment, DeploymentState, DeploymentStatusUpdate, DesiredUpdates, Plan,
    PlanAnnotations, PlanResult, new_deployment,
    DEPLOYMENT_STATUS_RUNNING, DEPLOYMENT_STATUS_PAUSED,
    DEPLOYMENT_STATUS_FAILED, DEPLOYMENT_STATUS_SUCCESSFUL,
    DEPLOYMENT_STATUS_CANCELLED, DEPLOYMENT_STATUS_PENDING,
    DEPLOYMENT_STATUS_BLOCKED, DEPLOYMENT_STATUS_UNBLOCKING,
    DEPLOYMENT_TERMINAL, DESC_DEPLOYMENT_PROMOTED, DESC_NEW_DEPLOYMENT,
)
from .network import (  # noqa: F401
    Bitmap, NetworkIndex, parse_port_spec, MAX_VALID_PORT,
    DEFAULT_MIN_DYNAMIC_PORT, DEFAULT_MAX_DYNAMIC_PORT,
)
from .respool import (  # noqa: F401
    ResourceSkeleton, skeleton_for,
)
from .funcs import (  # noqa: F401
    DeviceAccounter, allocs_fit, score_fit_binpack, score_fit_spread,
    score_normalize, BINPACK_MAX_FIT_SCORE,
)
from .operator import (  # noqa: F401
    PreemptionConfig, SchedulerConfiguration,
    SCHED_ALG_BINPACK, SCHED_ALG_CONVEX, SCHED_ALG_SPREAD, SCHED_ALG_TPU,
    VALID_SCHEDULER_ALGORITHMS,
)
from .csi import (  # noqa: F401
    CSIPlugin, CSIVolume, CSIVolumeClaim, plugin_stub, volume_stub,
    ACCESS_MODE_MULTI_NODE_MULTI_WRITER, ACCESS_MODE_MULTI_NODE_READER,
    ACCESS_MODE_MULTI_NODE_SINGLE_WRITER, ACCESS_MODE_SINGLE_NODE_READER,
    ACCESS_MODE_SINGLE_NODE_WRITER, ATTACHMENT_MODE_BLOCK,
    ATTACHMENT_MODE_FS, CLAIM_READ, CLAIM_STATE_READY_TO_FREE,
    CLAIM_STATE_TAKEN, CLAIM_WRITE,
)
from .scaling import (  # noqa: F401
    ScalingEvent, ScalingPolicyState, policy_from_group,
    JOB_TRACKED_SCALING_EVENTS, SCALING_POLICY_TYPE_HORIZONTAL,
    SCALING_TARGET_GROUP, SCALING_TARGET_JOB, SCALING_TARGET_NAMESPACE,
)
from .acl_structs import (  # noqa: F401
    ACLPolicy, ACLToken, TOKEN_TYPE_CLIENT, TOKEN_TYPE_MANAGEMENT,
    anonymous_token,
)
