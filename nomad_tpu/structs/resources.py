"""Resource model: asks, node capacities, and the flattened comparable form.

Behavioral reference: nomad/structs/structs.go:2251 (Resources),
:2859 (NodeResources), :3931 (ComparableResources), nomad/structs/devices.go.
Re-designed for a dual representation: the object form here, and a dense
tensor form produced by nomad_tpu.solver.tensorize for the TPU solver.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Port:
    label: str = ""
    value: int = 0          # static port (0 = dynamic)
    to: int = 0             # mapped port inside the task namespace
    host_network: str = "default"


@dataclass
class DNSConfig:
    servers: list[str] = field(default_factory=list)
    searches: list[str] = field(default_factory=list)
    options: list[str] = field(default_factory=list)


@dataclass
class NetworkResource:
    """One requested/allocated network (ref structs.go NetworkResource).

    mbits participates in bandwidth overcommit checks
    (nomad/structs/network.go Overcommitted); ports are allocated against the
    node's NetworkIndex bitmaps.
    """
    mode: str = "host"              # host | bridge | none | cni/*
    device: str = ""
    cidr: str = ""
    ip: str = ""
    mbits: int = 0
    dns: Optional[DNSConfig] = None
    reserved_ports: list[Port] = field(default_factory=list)
    dynamic_ports: list[Port] = field(default_factory=list)

    def copy(self) -> "NetworkResource":
        return dataclasses.replace(
            self,
            dns=dataclasses.replace(self.dns) if self.dns else None,
            reserved_ports=[dataclasses.replace(p) for p in self.reserved_ports],
            dynamic_ports=[dataclasses.replace(p) for p in self.dynamic_ports],
        )

    def port_labels(self) -> dict[str, int]:
        out = {}
        for p in self.reserved_ports:
            out[p.label] = p.value
        for p in self.dynamic_ports:
            out[p.label] = p.value
        return out


@dataclass
class RequestedDevice:
    """Device ask, `vendor/type/name` hierarchy (ref nomad/structs/devices.go,
    structs.go RequestedDevice)."""
    name: str = ""                  # e.g. "gpu", "nvidia/gpu", "nvidia/gpu/1080ti"
    count: int = 1
    constraints: list = field(default_factory=list)   # list[Constraint]
    affinities: list = field(default_factory=list)    # list[Affinity]

    def id_tuple(self) -> tuple[str, str, str]:
        """Split name into (vendor, type, name) with wildcards as ''."""
        parts = self.name.split("/")
        if len(parts) == 1:
            return ("", parts[0], "")
        if len(parts) == 2:
            return (parts[0], parts[1], "")
        return (parts[0], parts[1], "/".join(parts[2:]))


@dataclass
class Resources:
    """A task's resource ask (ref structs.go:2251)."""
    cpu: int = 100                  # MHz
    cores: int = 0                  # reserved whole cores (exclusive cpuset)
    memory_mb: int = 300
    memory_max_mb: int = 0          # oversubscription ceiling
    disk_mb: int = 0
    networks: list[NetworkResource] = field(default_factory=list)
    devices: list[RequestedDevice] = field(default_factory=list)

    def copy(self) -> "Resources":
        return dataclasses.replace(
            self,
            networks=[n.copy() for n in self.networks],
            devices=[dataclasses.replace(d) for d in self.devices],
        )

    def add(self, other: "Resources") -> None:
        self.cpu += other.cpu
        self.cores += other.cores
        self.memory_mb += other.memory_mb
        self.memory_max_mb += other.memory_max_mb
        self.disk_mb += other.disk_mb
        self.networks.extend(n.copy() for n in other.networks)


@dataclass
class NodeCpuResources:
    cpu_shares: int = 0             # total MHz
    total_core_count: int = 0
    reservable_cores: list[int] = field(default_factory=list)


@dataclass
class NodeMemoryResources:
    memory_mb: int = 0


@dataclass
class NodeDiskResources:
    disk_mb: int = 0


@dataclass
class NodeDeviceResource:
    """An installed device group on a node (ref structs.go NodeDeviceResource)."""
    vendor: str = ""
    type: str = ""
    name: str = ""
    instances: list["NodeDevice"] = field(default_factory=list)
    attributes: dict[str, object] = field(default_factory=dict)

    def id_tuple(self) -> tuple[str, str, str]:
        return (self.vendor, self.type, self.name)

    def matches(self, ask: RequestedDevice) -> bool:
        """Hierarchical match: ask may specify type, vendor/type, or
        vendor/type/name (ref nomad/structs/devices.go IDMatches)."""
        v, t, n = ask.id_tuple()
        if t and t != self.type:
            return False
        if v and v != self.vendor:
            return False
        if n and n != self.name:
            return False
        return True


@dataclass
class NodeDevice:
    id: str = ""
    healthy: bool = True
    locality: Optional[str] = None


@dataclass
class NodeNetworkResource:
    mode: str = "host"
    device: str = ""
    mac_address: str = ""
    speed: int = 1000               # mbits
    addresses: list[dict] = field(default_factory=list)


@dataclass
class NodeResources:
    """Total resources on a node (ref structs.go:2859)."""
    cpu: NodeCpuResources = field(default_factory=NodeCpuResources)
    memory: NodeMemoryResources = field(default_factory=NodeMemoryResources)
    disk: NodeDiskResources = field(default_factory=NodeDiskResources)
    networks: list[NetworkResource] = field(default_factory=list)
    node_networks: list[NodeNetworkResource] = field(default_factory=list)
    devices: list[NodeDeviceResource] = field(default_factory=list)

    def copy(self) -> "NodeResources":
        return dataclasses.replace(
            self,
            cpu=dataclasses.replace(self.cpu, reservable_cores=list(self.cpu.reservable_cores)),
            memory=dataclasses.replace(self.memory),
            disk=dataclasses.replace(self.disk),
            networks=[n.copy() for n in self.networks],
            node_networks=list(self.node_networks),
            devices=list(self.devices),
        )

    def comparable(self) -> "ComparableResources":
        return ComparableResources(
            cpu_shares=self.cpu.cpu_shares,
            reserved_cores=tuple(self.cpu.reservable_cores),
            memory_mb=self.memory.memory_mb,
            disk_mb=self.disk.disk_mb,
        )


@dataclass
class NodeReservedResources:
    """Resources the client reserves for the host OS (ref structs.go
    NodeReservedResources)."""
    cpu_shares: int = 0
    cores: list[int] = field(default_factory=list)
    memory_mb: int = 0
    disk_mb: int = 0
    reserved_host_ports: str = ""   # port spec string, e.g. "22,80,8000-8100"

    def comparable(self) -> "ComparableResources":
        return ComparableResources(
            cpu_shares=self.cpu_shares,
            reserved_cores=tuple(self.cores),
            memory_mb=self.memory_mb,
            disk_mb=self.disk_mb,
        )


@dataclass
class ComparableResources:
    """Flattened resource vector used by fit checks and preemption distance
    (ref structs.go:3931). This is the object twin of one row of the solver's
    dense resource matrices."""
    cpu_shares: int = 0
    reserved_cores: tuple[int, ...] = ()
    memory_mb: int = 0
    memory_max_mb: int = 0
    disk_mb: int = 0
    networks: list[NetworkResource] = field(default_factory=list)

    def add(self, other: Optional["ComparableResources"]) -> None:
        if other is None:
            return
        self.cpu_shares += other.cpu_shares
        self.reserved_cores = tuple(self.reserved_cores) + tuple(other.reserved_cores)
        self.memory_mb += other.memory_mb
        # memory_max falls back to memory when unset, so the summed max is the
        # true oversubscription claim (ref structs.go:3824 AllocatedMemoryResources.Add)
        self.memory_max_mb += (other.memory_max_mb
                               if other.memory_max_mb else other.memory_mb)
        self.disk_mb += other.disk_mb
        self.networks.extend(other.networks)

    def subtract(self, other: Optional["ComparableResources"]) -> None:
        if other is None:
            return
        self.cpu_shares -= other.cpu_shares
        self.reserved_cores = tuple(c for c in self.reserved_cores
                                    if c not in set(other.reserved_cores))
        self.memory_mb -= other.memory_mb
        self.memory_max_mb -= (other.memory_max_mb
                               if other.memory_max_mb else other.memory_mb)
        self.disk_mb -= other.disk_mb

    def copy(self) -> "ComparableResources":
        return dataclasses.replace(self, networks=[n.copy() for n in self.networks])

    def superset(self, other: "ComparableResources") -> tuple[bool, str]:
        """Is self a superset of other? Returns (ok, failing dimension)
        (ref structs.go ComparableResources.Superset)."""
        if self.cpu_shares < other.cpu_shares:
            return False, "cpu"
        if other.reserved_cores and \
           not set(other.reserved_cores) <= set(self.reserved_cores):
            return False, "cores"
        # memory_max (if set) is the claim against capacity under
        # oversubscription; otherwise memory.
        mem_claim = other.memory_max_mb if other.memory_max_mb > other.memory_mb else other.memory_mb
        if self.memory_mb < mem_claim:
            return False, "memory"
        if self.disk_mb < other.disk_mb:
            return False, "disk"
        return True, ""


# Vector layout shared with the solver: index of each scalar dimension in the
# dense [*, R] resource matrices. Ports/devices are handled by separate masks.
RESOURCE_DIMS = ("cpu", "memory", "disk")
R_CPU, R_MEM, R_DISK = 0, 1, 2
NUM_RESOURCE_DIMS = len(RESOURCE_DIMS)


def comparable_to_vector(c: ComparableResources) -> list[float]:
    """Flatten to the solver's dense layout. Memory uses the oversubscription
    claim (max(memory, memory_max)) to mirror Superset above."""
    mem = c.memory_max_mb if c.memory_max_mb > c.memory_mb else c.memory_mb
    return [float(c.cpu_shares), float(mem), float(c.disk_mb)]
