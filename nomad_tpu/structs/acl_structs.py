"""ACL policy + token records (ref nomad/structs/structs.go ACLPolicy
:11160-ish and ACLToken; replication/bootstrap semantics in nomad/acl.go,
nomad/leader.go:1288)."""
from __future__ import annotations

import dataclasses
import time
import uuid
from dataclasses import dataclass, field

TOKEN_TYPE_CLIENT = "client"
TOKEN_TYPE_MANAGEMENT = "management"

ANONYMOUS_TOKEN_SECRET = ""


@dataclass
class ACLPolicy:
    name: str = ""
    description: str = ""
    rules: str = ""             # HCL policy source
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "ACLPolicy":
        return dataclasses.replace(self)


@dataclass
class ACLToken:
    accessor_id: str = ""
    secret_id: str = ""
    name: str = ""
    type: str = TOKEN_TYPE_CLIENT          # client | management
    policies: list[str] = field(default_factory=list)
    global_: bool = False
    create_time_unix: float = 0.0
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "ACLToken":
        return dataclasses.replace(self, policies=list(self.policies))

    def is_management(self) -> bool:
        return self.type == TOKEN_TYPE_MANAGEMENT

    @staticmethod
    def new(name: str = "", type: str = TOKEN_TYPE_CLIENT,
            policies: list[str] | None = None,
            global_: bool = False) -> "ACLToken":
        return ACLToken(
            accessor_id=str(uuid.uuid4()), secret_id=str(uuid.uuid4()),
            name=name, type=type, policies=list(policies or []),
            global_=global_, create_time_unix=time.time())


def anonymous_token() -> ACLToken:
    """ref nomad/structs AnonymousACLToken"""
    return ACLToken(accessor_id="anonymous", secret_id="", name="Anonymous",
                    type=TOKEN_TYPE_CLIENT, policies=["anonymous"])
