"""Process-level runtime tuning for long-lived server/agent processes.

CPython's default GC thresholds (700 gen0 allocations) make a 50k-alloc
plan pay hundreds of stop-the-world generational scans across the
scheduler -> plan-apply -> FSM pipeline: measured ~0.3s of the end-to-end
headline, smeared across whichever phase the collector happened to fire
in. The Go reference pays none of this (concurrent GC + arena-friendly
structs; ref nomad/plan_apply.go:204 applyPlan). Raising the thresholds
amortizes cycle detection to a sane cadence for an allocation-heavy
server: reference-counting still frees the (acyclic) bulk — plans,
allocations, tensors — immediately; the cycle collector only needs to run
occasionally for the rare cyclic leftovers.

Called from Server.start() / Agent.start() (and bench.py, which simulates
the server loop), so the benchmark measures exactly what production runs.
"""
from __future__ import annotations

import gc
import os
import subprocess

# gen0: collections per ~200k container allocations instead of 700 —
# a 50k-alloc plan triggers a handful of scans, not ~300.
GC_GEN0 = 200_000
GC_GEN1 = 100
GC_GEN2 = 100

_tuned = False


def tune_gc(freeze_baseline: bool = False) -> None:
    """Apply server GC thresholds (idempotent). With freeze_baseline=True,
    objects alive NOW (module/import graph, restored snapshot) move to the
    permanent generation so future full collections skip them."""
    global _tuned
    if not _tuned:
        gc.set_threshold(GC_GEN0, GC_GEN1, GC_GEN2)
        _tuned = True
    if freeze_baseline:
        gc.freeze()


_cache_enabled = False


def enable_compile_cache(path: str = "") -> str:
    """Point JAX's persistent compilation cache at a durable directory
    (VERDICT r4 #3: a restarted scheduler paid the full ~14s XLA compile
    as live placement blackout; with the cache a warm restart replays
    serialized executables instead of recompiling). Idempotent; returns
    the cache dir. Call before the first jit executes — config changes
    after a compile has populated the in-memory cache won't rewrite it.
    """
    global _cache_enabled
    import jax
    if not path:
        path = os.environ.get(
            "NOMAD_COMPILE_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache", "nomad_tpu",
                         "xla_cache"))
    if _cache_enabled:
        return path
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # the solver's kernels all take >0.1s to compile and are worth
    # caching; the default 1s floor would skip the small eval-stream jits
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    try:
        # also persist XLA's internal caches (autotune results, kernel
        # selections) — on TPU these are a real slice of the warm-restart
        # blackout beyond executable deserialization. Knob is version-
        # dependent; best-effort.
        jax.config.update("jax_persistent_cache_enable_xla_caches", "all")
    except Exception:   # noqa: BLE001 — older jax: executables still cache
        pass
    _cache_enabled = True
    return path


_native_built = False


def ensure_native(timeout: float = 120.0) -> bool:
    """Build the native sidecars (native/Makefile: executor, logmon,
    allocstamp extension) if the toolchain is present — compiled artifacts
    are NOT committed (ADVICE r4: unreviewable + silently stale vs their
    sources); deploy/test/bench entrypoints call this once instead. make
    is a fast no-op when everything is current; a flock serializes
    concurrent builders. Returns False (and stays quiet) when no
    toolchain exists — every native consumer has a pure-Python fallback.
    """
    global _native_built
    if _native_built:
        return True
    native_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native")
    if not os.path.isfile(os.path.join(native_dir, "Makefile")):
        return False
    try:
        import fcntl
        with open(os.path.join(native_dir, ".build.lock"), "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            r = subprocess.run(
                ["make", "-C", native_dir, "all"], timeout=timeout,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        _native_built = r.returncode == 0
    except Exception:
        _native_built = False
    return _native_built
