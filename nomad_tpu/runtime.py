"""Process-level runtime tuning for long-lived server/agent processes.

CPython's default GC thresholds (700 gen0 allocations) make a 50k-alloc
plan pay hundreds of stop-the-world generational scans across the
scheduler -> plan-apply -> FSM pipeline: measured ~0.3s of the end-to-end
headline, smeared across whichever phase the collector happened to fire
in. The Go reference pays none of this (concurrent GC + arena-friendly
structs; ref nomad/plan_apply.go:204 applyPlan). Raising the thresholds
amortizes cycle detection to a sane cadence for an allocation-heavy
server: reference-counting still frees the (acyclic) bulk — plans,
allocations, tensors — immediately; the cycle collector only needs to run
occasionally for the rare cyclic leftovers.

Called from Server.start() / Agent.start() (and bench.py, which simulates
the server loop), so the benchmark measures exactly what production runs.
"""
from __future__ import annotations

import gc

# gen0: collections per ~200k container allocations instead of 700 —
# a 50k-alloc plan triggers a handful of scans, not ~300.
GC_GEN0 = 200_000
GC_GEN1 = 100
GC_GEN2 = 100

_tuned = False


def tune_gc(freeze_baseline: bool = False) -> None:
    """Apply server GC thresholds (idempotent). With freeze_baseline=True,
    objects alive NOW (module/import graph, restored snapshot) move to the
    permanent generation so future full collections skip them."""
    global _tuned
    if not _tuned:
        gc.set_threshold(GC_GEN0, GC_GEN1, GC_GEN2)
        _tuned = True
    if freeze_baseline:
        gc.freeze()
