"""Template rendering for task `template` stanzas (ref
client/allocrunner/taskrunner/template/template.go, which embeds
consul-template — a Go text/template dialect).

A real recursive-descent engine (VERDICT r4 #10 — the previous regex
subset could not nest), covering the consul-template constructs the
reference's docs lean on:

  {{ env "NAME" }} {{ key "p" }} {{ keyOrDefault "p" "dflt" }}
  {{ keyExists "p" }} {{ secret "p" ["field"] }} {{ service "name" }}
  {{ if X }}...{{ else if Y }}...{{ else }}...{{ end }}
  {{ with secret "p" }}{{ .Data.password }}{{ end }}
  {{ range service "db" }}{{ .Address }}:{{ .Port }}{{ end }}
  {{ range $i, $v := service "db" }}...{{ end }}      (nested ok)
  pipelines: {{ key "p" | toUpper }}; variables: {{ $x := ... }};
  whitespace trim markers {{- ... -}}.

Functions beyond the sources: toUpper toLower trimSpace split join
toJSON parseJSON base64Encode base64Decode timestamp.
"""
from __future__ import annotations

import base64
import json
import re
import time
from typing import Callable, Optional


class TemplateError(Exception):
    pass


# ------------------------------------------------------------- tokenizer

# action content: quoted strings are consumed atomically so a '}}'
# INSIDE a string literal cannot terminate the action (Go text/template
# lexes strings before delimiters); a '}' is only a terminator when
# doubled. A lone unbalanced quote never matches — the braces stay
# literal text, surfacing the malformed action verbatim.
_ACTION = re.compile(
    r'\{\{(-?)((?:"(?:[^"\\]|\\.)*"|\}(?!\})|[^}"])*?)(-?)\}\}',
    re.DOTALL)
_WORD = re.compile(r'"(?:[^"\\]|\\.)*"|[^\s|]+|\|')
_ESCAPE = re.compile(r"\\(.)")
_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\"}


def _tokenize(src: str) -> list[tuple]:
    """-> [("text", s) | ("action", content)] with {{- -}} trims applied."""
    out: list[tuple] = []
    pos = 0
    for m in _ACTION.finditer(src):
        text = src[pos:m.start()]
        if m.group(1):                  # {{- : trim preceding whitespace
            text = text.rstrip()
        out.append(("text", text))
        out.append(("action", m.group(2).strip()))
        pos = m.end()
        if m.group(3):                  # -}} : trim following whitespace
            rest = src[pos:]
            trimmed = rest.lstrip()
            pos += len(rest) - len(trimmed)
    out.append(("text", src[pos:]))
    return out


# ---------------------------------------------------------------- parser
# Nodes: ("text", s) | ("out", pipeline) | ("assign", var, pipeline)
#   | ("if", [(pipeline, body)...], else_body)
#   | ("with", pipeline, body, else_body)
#   | ("range", vars, pipeline, body, else_body)
# A pipeline is [command, ...]; a command is [word, ...] where word is
# ("lit", v) | ("dot", ["A","B"]) | ("var", "$x", ["path"]) | ("fn", name)


def _parse_word(w: str):
    if w.startswith('"'):
        # single-pass unescape: sequential .replace chains re-interpret
        # the output of earlier replacements ("\\n" must stay
        # backslash+n, not become a newline)
        return ("lit", _ESCAPE.sub(
            lambda m: _ESCAPES.get(m.group(1), m.group(1)), w[1:-1]))
    if w == ".":
        return ("dot", [])
    if w.startswith("."):
        return ("dot", w[1:].split("."))
    if w.startswith("$"):
        name, _, path = w.partition(".")
        return ("var", name, path.split(".") if path else [])
    try:
        return ("lit", int(w))
    except ValueError:
        pass
    try:
        return ("lit", float(w))
    except ValueError:
        pass
    if w in ("true", "false"):
        return ("lit", w == "true")
    if w == "nil":
        return ("lit", None)
    return ("fn", w)


def _parse_pipeline(words: list[str]) -> list:
    cmds, cur = [], []
    for w in words:
        if w == "|":
            if not cur:
                raise TemplateError("empty pipeline stage")
            cmds.append(cur)
            cur = []
        else:
            cur.append(_parse_word(w))
    if not cur:
        raise TemplateError("empty pipeline stage")
    cmds.append(cur)
    return cmds


def _parse(tokens: list[tuple], i: int = 0, *, top: bool = True
           ) -> tuple[list, int, str]:
    """-> (body_nodes, next_index, terminator) where terminator is
    "end" | "else" | "else if <rest>" | "" (EOF, only legal at top)."""
    body: list = []
    while i < len(tokens):
        kind, val = tokens[i]
        i += 1
        if kind == "text":
            if val:
                body.append(("text", val))
            continue
        words = _WORD.findall(val)
        if not words:
            continue
        head = words[0]
        if head == "end" or head == "else":
            if top:
                raise TemplateError(f"unexpected {{{{{val}}}}}")
            return body, i, val
        if head == "if" or head == "with" or head == "range":
            rest = words[1:]
            if head == "range" and ":=" in rest:
                sep = rest.index(":=")
                rng_vars = [w.rstrip(",") for w in rest[:sep]]
                pipeline = _parse_pipeline(rest[sep + 1:])
            else:
                rng_vars = []
                pipeline = _parse_pipeline(rest)
            arms = [(pipeline, None)]
            else_body: list = []
            while True:
                inner, i, term = _parse(tokens, i, top=False)
                if arms[-1][1] is None:
                    arms[-1] = (arms[-1][0], inner)
                if term == "end":
                    break
                tw = _WORD.findall(term)
                if tw[:2] == ["else", "if"] and head == "if":
                    arms.append((_parse_pipeline(tw[2:]), None))
                    continue
                if tw == ["else"]:
                    else_body, i, term2 = _parse(tokens, i, top=False)
                    if _WORD.findall(term2) != ["end"]:
                        raise TemplateError("expected {{end}} after else")
                    break
                raise TemplateError(f"unexpected {{{{{term}}}}}")
            if head == "if":
                body.append(("if", arms, else_body))
            elif head == "with":
                body.append(("with", arms[0][0], arms[0][1], else_body))
            else:
                body.append(("range", rng_vars, arms[0][0], arms[0][1],
                             else_body))
            continue
        if head.startswith("$") and len(words) >= 2 and words[1] == ":=":
            body.append(("assign", head, _parse_pipeline(words[2:])))
            continue
        body.append(("out", _parse_pipeline(words)))
    if not top:
        raise TemplateError("unclosed block: missing {{end}}")
    return body, i, ""


# ------------------------------------------------------------- evaluator

class _ServiceList(list):
    """consul-template's service() result: iterable of instances that
    PRINTS as the first healthy instance's addr:port (the value form the
    framework's one-liner templates rely on). Like consul-template, an
    empty result is fine to iterate/test ({{range}}/{{if}}/{{with}} hit
    their else arms) but rendering it as a VALUE is a hard dependency
    failure — the task must not start on a half-rendered config."""

    name = ""

    def __str__(self) -> str:
        if not self:
            raise TemplateError(
                f"no healthy instances of {self.name!r}")
        inst = self[0]
        return f"{_lookup(inst, 'Address')}:{_lookup(inst, 'Port')}"


def _snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


def _lookup(obj, name: str):
    """Resolve .Field on dicts (exact, then lower/snake key) or objects
    (snake_case attribute) — Go-exported names against Python data. A
    vault-style ``.Data`` on a plain secret dict resolves to the dict
    itself so the reference's documented vault examples render."""
    if isinstance(obj, dict):
        for k in (name, name.lower(), _snake(name)):
            if k in obj:
                return obj[k]
        if name == "Data":
            return obj
        raise TemplateError(f"no field {name!r}")
    for attr in (_snake(name), name):
        if hasattr(obj, attr):
            return getattr(obj, attr)
    raise TemplateError(f"no field {name!r} on {type(obj).__name__}")


def _truthy(v) -> bool:
    if isinstance(v, _ServiceList):
        return len(v) > 0
    return bool(v)


def _to_str(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    if isinstance(v, dict):
        return json.dumps(v, sort_keys=True)
    return str(v)


def _make_funcs(env: dict, secret_reader, service_lookup) -> dict:
    def need_secrets():
        if secret_reader is None:
            raise TemplateError("no secrets provider configured")

    def f_env(name):
        if name not in env:
            raise TemplateError(f"env var {name!r} not set")
        return env[name]

    def f_key(path):
        need_secrets()
        data = secret_reader(path)
        if data is None:
            raise TemplateError(f"key {path!r} not found")
        if isinstance(data, dict) and len(data) == 1:
            return next(iter(data.values()))
        return data

    def f_key_or_default(path, default=""):
        need_secrets()
        data = secret_reader(path)
        if data is None:
            return default
        if isinstance(data, dict) and len(data) == 1:
            return next(iter(data.values()))
        return data

    def f_key_exists(path):
        need_secrets()
        return secret_reader(path) is not None

    def f_secret(path, field=None):
        need_secrets()
        data = secret_reader(path)
        if data is None:
            raise TemplateError(f"secret {path!r} not found")
        if field is not None:
            if field not in data:
                raise TemplateError(
                    f"secret {path!r} has no field {field!r}")
            return data[field]
        return data

    def f_service(name):
        if service_lookup is None:
            raise TemplateError("no service catalog configured")
        healthy = _ServiceList(
            i for i in service_lookup(name)
            if getattr(i, "status", "passing") == "passing")
        healthy.name = name
        return healthy

    return {
        "env": f_env, "key": f_key, "keyOrDefault": f_key_or_default,
        "keyExists": f_key_exists, "secret": f_secret,
        "service": f_service,
        "toUpper": lambda v: _to_str(v).upper(),
        "toLower": lambda v: _to_str(v).lower(),
        "trimSpace": lambda v: _to_str(v).strip(),
        "split": lambda sep, v: _to_str(v).split(_to_str(sep)),
        "join": lambda sep, v: _to_str(sep).join(_to_str(x) for x in v),
        "toJSON": lambda v: json.dumps(v, sort_keys=True),
        "parseJSON": lambda v: json.loads(_to_str(v)),
        "base64Encode": lambda v: base64.b64encode(
            _to_str(v).encode()).decode(),
        "base64Decode": lambda v: base64.b64decode(
            _to_str(v)).decode(),
        "timestamp": lambda fmt=None: time.strftime(
            "%Y-%m-%dT%H:%M:%SZ" if fmt is None else fmt, time.gmtime()),
    }


def _eval_word(word, dot, varz, funcs):
    kind = word[0]
    if kind == "lit":
        return word[1]
    if kind == "dot":
        v = dot
        for part in word[1]:
            v = _lookup(v, part)
        return v
    if kind == "var":
        name = word[1]
        if name not in varz:
            raise TemplateError(f"undefined variable {name}")
        v = varz[name]
        for part in word[2]:
            v = _lookup(v, part)
        return v
    # function reference (called by _eval_command)
    fn = funcs.get(word[1])
    if fn is None:
        raise TemplateError(f"unknown function {word[1]!r}")
    return fn


def _eval_command(cmd: list, dot, varz, funcs, piped=None):
    if cmd[0][0] == "fn":
        fn = _eval_word(cmd[0], dot, varz, funcs)
        args = [_eval_word(w, dot, varz, funcs) for w in cmd[1:]]
        if piped is not None:
            args.append(piped)
        try:
            return fn(*args)
        except TemplateError:
            raise
        except TypeError as e:
            raise TemplateError(f"{cmd[0][1]}: {e}") from e
    if len(cmd) != 1:
        raise TemplateError("literal command takes no arguments")
    if piped is not None:
        raise TemplateError("cannot pipe into a literal")
    return _eval_word(cmd[0], dot, varz, funcs)


def _eval_pipeline(pipeline: list, dot, varz, funcs):
    v = _eval_command(pipeline[0], dot, varz, funcs)
    for cmd in pipeline[1:]:
        v = _eval_command(cmd, dot, varz, funcs, piped=v)
    return v


def _exec(body: list, dot, varz: dict, funcs: dict, out: list) -> None:
    for node in body:
        kind = node[0]
        if kind == "text":
            out.append(node[1])
        elif kind == "out":
            out.append(_to_str(_eval_pipeline(node[1], dot, varz, funcs)))
        elif kind == "assign":
            varz[node[1]] = _eval_pipeline(node[2], dot, varz, funcs)
        elif kind == "if":
            _, arms, else_body = node
            for pipeline, arm_body in arms:
                if _truthy(_eval_pipeline(pipeline, dot, varz, funcs)):
                    _exec(arm_body, dot, dict(varz), funcs, out)
                    break
            else:
                _exec(else_body, dot, dict(varz), funcs, out)
        elif kind == "with":
            _, pipeline, with_body, else_body = node
            v = _eval_pipeline(pipeline, dot, varz, funcs)
            if _truthy(v):
                _exec(with_body, v, dict(varz), funcs, out)
            else:
                _exec(else_body, dot, dict(varz), funcs, out)
        elif kind == "range":
            _, rng_vars, pipeline, rng_body, else_body = node
            coll = _eval_pipeline(pipeline, dot, varz, funcs)
            items: list = []
            if isinstance(coll, dict):
                items = [(k, coll[k]) for k in sorted(coll)]
            elif coll is not None:
                items = [(idx, v) for idx, v in enumerate(coll)]
            if not items:
                _exec(else_body, dot, dict(varz), funcs, out)
                continue
            for k, v in items:
                inner = dict(varz)
                if len(rng_vars) == 2:
                    inner[rng_vars[0]], inner[rng_vars[1]] = k, v
                elif len(rng_vars) == 1:
                    inner[rng_vars[0]] = v
                _exec(rng_body, v, inner, funcs, out)


def render_template(tmpl: str, env: dict[str, str],
                    secret_reader: Optional[Callable] = None,
                    service_lookup: Optional[Callable] = None) -> str:
    """Render one embedded template. Missing keys raise TemplateError so a
    task fails visibly instead of starting with a half-rendered config
    (ref template.go: blocks until all dependencies resolve)."""
    body, _, _ = _parse(_tokenize(tmpl))
    funcs = _make_funcs(env, secret_reader, service_lookup)
    out: list[str] = []
    _exec(body, None, {}, funcs, out)
    return "".join(out)


class TemplateWatcher:
    """Watch -> re-render -> change_mode, the consul-template runner loop
    (ref client/allocrunner/taskrunner/template/template.go:
    handleTemplateRerenders). Poll-and-compare against the framework-native
    sources: each tick re-renders every template; when the output changes
    the file is rewritten in the task dir and the task receives its
    configured change_mode (signal / restart / noop). A render error mid-
    watch (a dependency vanished) keeps the LAST rendered content — the
    reference blocks rather than clobbering a running task's config."""

    def __init__(self, task_runner, templates, env: dict,
                 secret_reader=None, service_lookup=None,
                 interval: float = 2.0, logger=None):
        import threading
        self.tr = task_runner
        self.templates = list(templates)
        self.env = env
        self.secret_reader = secret_reader
        self.service_lookup = service_lookup
        self.interval = interval
        self.logger = logger or (lambda msg: None)
        self._last: dict[int, str] = {}
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.rerenders = 0          # observability + tests

    def prime(self, rendered: list) -> None:
        """Record the initial render (list of (rel, content, perms)) so
        the first tick doesn't re-fire change_mode."""
        for i, (_, content, _) in enumerate(rendered):
            self._last[i] = content

    def start(self) -> None:
        import threading
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"template-watch-{self.tr.task.name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:      # noqa: BLE001
                self.logger(f"template watch: {e!r}")

    def tick(self) -> int:
        """One re-render pass; returns how many templates changed."""
        changed = 0
        for i, tmpl in enumerate(self.templates):
            try:
                content = render_template(
                    tmpl.embedded_tmpl, self.env,
                    secret_reader=self.secret_reader,
                    service_lookup=self.service_lookup)
            except TemplateError:
                continue                # keep last content; retry next tick
            if content == self._last.get(i):
                continue
            # write + notify BEFORE recording: a transient write failure
            # (ENOSPC et al) must stay retryable on the next tick, not
            # silently strand the task on stale config forever
            self.tr.write_rendered_file(tmpl.dest_path or "local/template",
                                        content, tmpl.perms)
            self._fire_change_mode(tmpl)
            self._last[i] = content
            changed += 1
            self.rerenders += 1
        return changed

    def _fire_change_mode(self, tmpl) -> None:
        mode = tmpl.change_mode or "restart"
        if mode == "noop":
            return
        try:
            if mode == "signal":
                self.tr.signal(tmpl.change_signal or "SIGHUP",
                               reason="template re-rendered")
            else:
                self.tr.restart(reason="template re-rendered")
        except Exception as e:          # noqa: BLE001
            self.logger(f"template change_mode {mode}: {e!r}")
