"""Template rendering for task `template` stanzas (ref
client/allocrunner/taskrunner/template/template.go, which embeds
consul-template).

Supported functions — the consul-template subset the reference's docs lean
on, resolved against framework-native sources:

  {{ env "NAME" }}                  task environment variable
  {{ key "path" }}                  service-catalog KV -> secrets provider
  {{ secret "path" "field" }}       secrets provider read (field optional)
  {{ service "name" }}              -> "addr:port" of first healthy instance
  {{ range service "name" }}        iterate healthy instances; the body may
      {{ .Address }} {{ .Port }} {{ .Name }}
  {{ end }}
"""
from __future__ import annotations

import json
import re
from typing import Callable, Optional

_FUNC = re.compile(
    r"\{\{\s*(env|key|secret|service)\s+\"([^\"]+)\"(?:\s+\"([^\"]+)\")?"
    r"\s*\}\}")
_RANGE = re.compile(
    r"\{\{\s*range\s+service\s+\"([^\"]+)\"\s*\}\}(.*?)\{\{\s*end\s*\}\}",
    re.DOTALL)
_FIELD = re.compile(r"\{\{\s*\.(Address|Port|Name)\s*\}\}")


class TemplateError(Exception):
    pass


def render_template(tmpl: str, env: dict[str, str],
                    secret_reader: Optional[Callable] = None,
                    service_lookup: Optional[Callable] = None) -> str:
    """Render one embedded template. Missing keys raise TemplateError so a
    task fails visibly instead of starting with a half-rendered config
    (ref template.go: blocks until all dependencies resolve)."""

    def sub(m: re.Match) -> str:
        fn, arg, field = m.group(1), m.group(2), m.group(3)
        if fn == "env":
            if arg not in env:
                raise TemplateError(f"env var {arg!r} not set")
            return env[arg]
        if fn in ("key", "secret"):
            if secret_reader is None:
                raise TemplateError("no secrets provider configured")
            data = secret_reader(arg)
            if data is None:
                raise TemplateError(f"secret {arg!r} not found")
            if fn == "secret" and field:
                if field not in data:
                    raise TemplateError(
                        f"secret {arg!r} has no field {field!r}")
                return str(data[field])
            if len(data) == 1:
                return str(next(iter(data.values())))
            return json.dumps(data, sort_keys=True)
        if fn == "service":
            if service_lookup is None:
                raise TemplateError("no service catalog configured")
            instances = service_lookup(arg)
            healthy = [i for i in instances
                       if getattr(i, "status", "passing") == "passing"]
            if not healthy:
                raise TemplateError(f"no healthy instances of {arg!r}")
            inst = healthy[0]
            return f"{inst.address}:{inst.port}"
        raise TemplateError(f"unknown function {fn!r}")

    def sub_range(m: re.Match) -> str:
        name, body = m.group(1), m.group(2)
        if service_lookup is None:
            raise TemplateError("no service catalog configured")
        healthy = [i for i in service_lookup(name)
                   if getattr(i, "status", "passing") == "passing"]
        out = []
        for inst in healthy:
            out.append(_FIELD.sub(
                lambda fm, inst=inst: str({
                    "Address": inst.address, "Port": inst.port,
                    "Name": getattr(inst, "name", name),
                }[fm.group(1)]), body))
        return "".join(out)

    return _FUNC.sub(sub, _RANGE.sub(sub_range, tmpl))


class TemplateWatcher:
    """Watch -> re-render -> change_mode, the consul-template runner loop
    (ref client/allocrunner/taskrunner/template/template.go:
    handleTemplateRerenders). Poll-and-compare against the framework-native
    sources: each tick re-renders every template; when the output changes
    the file is rewritten in the task dir and the task receives its
    configured change_mode (signal / restart / noop). A render error mid-
    watch (a dependency vanished) keeps the LAST rendered content — the
    reference blocks rather than clobbering a running task's config."""

    def __init__(self, task_runner, templates, env: dict,
                 secret_reader=None, service_lookup=None,
                 interval: float = 2.0, logger=None):
        import threading
        self.tr = task_runner
        self.templates = list(templates)
        self.env = env
        self.secret_reader = secret_reader
        self.service_lookup = service_lookup
        self.interval = interval
        self.logger = logger or (lambda msg: None)
        self._last: dict[int, str] = {}
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.rerenders = 0          # observability + tests

    def prime(self, rendered: list) -> None:
        """Record the initial render (list of (rel, content, perms)) so
        the first tick doesn't re-fire change_mode."""
        for i, (_, content, _) in enumerate(rendered):
            self._last[i] = content

    def start(self) -> None:
        import threading
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"template-watch-{self.tr.task.name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:      # noqa: BLE001
                self.logger(f"template watch: {e!r}")

    def tick(self) -> int:
        """One re-render pass; returns how many templates changed."""
        changed = 0
        for i, tmpl in enumerate(self.templates):
            try:
                content = render_template(
                    tmpl.embedded_tmpl, self.env,
                    secret_reader=self.secret_reader,
                    service_lookup=self.service_lookup)
            except TemplateError:
                continue                # keep last content; retry next tick
            if content == self._last.get(i):
                continue
            # write + notify BEFORE recording: a transient write failure
            # (ENOSPC et al) must stay retryable on the next tick, not
            # silently strand the task on stale config forever
            self.tr.write_rendered_file(tmpl.dest_path or "local/template",
                                        content, tmpl.perms)
            self._fire_change_mode(tmpl)
            self._last[i] = content
            changed += 1
            self.rerenders += 1
        return changed

    def _fire_change_mode(self, tmpl) -> None:
        mode = tmpl.change_mode or "restart"
        if mode == "noop":
            return
        try:
            if mode == "signal":
                self.tr.signal(tmpl.change_signal or "SIGHUP",
                               reason="template re-rendered")
            else:
                self.tr.restart(reason="template re-rendered")
        except Exception as e:          # noqa: BLE001
            self.logger(f"template change_mode {mode}: {e!r}")
