"""Template rendering for task `template` stanzas (ref
client/allocrunner/taskrunner/template/template.go, which embeds
consul-template).

Supported functions — the consul-template subset the reference's docs lean
on, resolved against framework-native sources:

  {{ env "NAME" }}                  task environment variable
  {{ key "path" }}                  service-catalog KV -> secrets provider
  {{ secret "path" "field" }}       secrets provider read (field optional)
  {{ service "name" }}              -> "addr:port" of first healthy instance
  {{ range service "name" }}...{{ end }} is NOT supported (static subset)
"""
from __future__ import annotations

import json
import re
from typing import Callable, Optional

_FUNC = re.compile(
    r"\{\{\s*(env|key|secret|service)\s+\"([^\"]+)\"(?:\s+\"([^\"]+)\")?"
    r"\s*\}\}")


class TemplateError(Exception):
    pass


def render_template(tmpl: str, env: dict[str, str],
                    secret_reader: Optional[Callable] = None,
                    service_lookup: Optional[Callable] = None) -> str:
    """Render one embedded template. Missing keys raise TemplateError so a
    task fails visibly instead of starting with a half-rendered config
    (ref template.go: blocks until all dependencies resolve)."""

    def sub(m: re.Match) -> str:
        fn, arg, field = m.group(1), m.group(2), m.group(3)
        if fn == "env":
            if arg not in env:
                raise TemplateError(f"env var {arg!r} not set")
            return env[arg]
        if fn in ("key", "secret"):
            if secret_reader is None:
                raise TemplateError("no secrets provider configured")
            data = secret_reader(arg)
            if data is None:
                raise TemplateError(f"secret {arg!r} not found")
            if fn == "secret" and field:
                if field not in data:
                    raise TemplateError(
                        f"secret {arg!r} has no field {field!r}")
                return str(data[field])
            if len(data) == 1:
                return str(next(iter(data.values())))
            return json.dumps(data, sort_keys=True)
        if fn == "service":
            if service_lookup is None:
                raise TemplateError("no service catalog configured")
            instances = service_lookup(arg)
            healthy = [i for i in instances
                       if getattr(i, "status", "passing") == "passing"]
            if not healthy:
                raise TemplateError(f"no healthy instances of {arg!r}")
            inst = healthy[0]
            return f"{inst.address}:{inst.port}"
        raise TemplateError(f"unknown function {fn!r}")

    return _FUNC.sub(sub, tmpl)
