"""External-system integrations re-designed as built-in subsystems.

The reference delegates service discovery to Consul (nomad/consul.go,
command/agent/consul/) and secrets to Vault (nomad/vault.go,
client/vaultclient/). Here both are first-class framework services behind
pluggable interfaces: a state-store-backed service catalog (the native
service discovery the reference later grew in 1.3, designed in from the
start) and a token-issuing secrets provider. Real Consul/Vault backends can
implement the same interfaces; nothing else changes.
"""
from .secrets import (  # noqa: F401
    InMemorySecretsProvider, SecretsProvider, VaultToken,
)
from .services import (  # noqa: F401
    CheckRunner, ServiceInstance, check_service,
)
from .template import render_template  # noqa: F401

__all__ = [
    "CheckRunner", "InMemorySecretsProvider", "SecretsProvider",
    "ServiceInstance", "VaultToken", "check_service", "render_template",
]
