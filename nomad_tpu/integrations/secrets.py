"""Secrets provider: the Vault integration redesigned as an interface
(ref nomad/vault.go vaultClient — token derivation/renewal/revocation —
and client/vaultclient/vaultclient.go).

The server owns one provider; clients derive per-task tokens through the
`Vault.DeriveToken` RPC exactly like the reference's Node.DeriveVaultToken
path (nomad/node_endpoint.go DeriveVaultToken). `InMemorySecretsProvider`
is the dev/test backend (static KV + local token issuance with TTLs); a
real Vault backend implements the same four methods over HTTP.
"""
from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Optional


@dataclasses.dataclass
class VaultToken:
    token: str = ""
    accessor: str = ""
    policies: tuple = ()
    ttl_sec: float = 3600.0
    expires_at: float = 0.0
    renewable: bool = True


class SecretsProvider:
    """ref nomad/vault.go VaultClient interface (subset that matters)."""

    def derive_token(self, alloc_id: str, task: str,
                     policies: list[str]) -> VaultToken:
        raise NotImplementedError

    def renew_token(self, token: str) -> VaultToken:
        raise NotImplementedError

    def revoke_token(self, token: str) -> None:
        raise NotImplementedError

    def read(self, path: str) -> Optional[dict]:
        """KV read for template rendering ({{secret "path"}})."""
        raise NotImplementedError


class InMemorySecretsProvider(SecretsProvider):
    """Dev-mode backend: static KV store + locally-issued TTL tokens.

    Cluster note: this backend is process-local, so all Vault RPCs are
    leader-routed (server.py RPC_ENDPOINTS); a leader failover loses issued
    tokens (clients re-derive via their renewal loop's failure path). A
    real Vault backend is an external shared service and has neither
    limitation."""

    def __init__(self, kv: Optional[dict[str, dict]] = None,
                 default_ttl: float = 3600.0):
        self.kv = dict(kv or {})
        self.default_ttl = default_ttl
        self._lock = threading.Lock()
        self._tokens: dict[str, VaultToken] = {}

    def put(self, path: str, data: dict) -> None:
        with self._lock:
            self.kv[path] = dict(data)

    def derive_token(self, alloc_id, task, policies):
        tok = VaultToken(
            token=str(uuid.uuid4()), accessor=str(uuid.uuid4()),
            policies=tuple(policies), ttl_sec=self.default_ttl,
            expires_at=time.time() + self.default_ttl)
        with self._lock:
            self._tokens[tok.token] = tok
        return tok

    def renew_token(self, token):
        with self._lock:
            tok = self._tokens.get(token)
            if tok is None:
                raise ValueError("unknown or revoked token")
            if not tok.renewable:
                raise ValueError("token is not renewable")
            tok = dataclasses.replace(
                tok, expires_at=time.time() + tok.ttl_sec)
            self._tokens[token] = tok
            return tok

    def revoke_token(self, token):
        with self._lock:
            self._tokens.pop(token, None)

    def token_valid(self, token: str) -> bool:
        with self._lock:
            tok = self._tokens.get(token)
            return tok is not None and tok.expires_at > time.time()

    def read(self, path):
        with self._lock:
            data = self.kv.get(path)
            return dict(data) if data is not None else None


class FileSecretsProvider(InMemorySecretsProvider):
    """Durable backend (VERDICT r3 weak #8: 'no file/external backend, so
    templates+vault paths can't be exercised against anything
    persistent'): KV entries and issued tokens survive a server restart
    via an atomically-replaced JSON file. The same sharing story as the
    reference running against a real Vault — secrets live OUTSIDE the
    raft state and are re-read on start.

    Operators seed/rotate KV either through `put()` (e.g. a sidecar
    process importing this module) or by editing the JSON file and
    letting the mtime-based reload pick it up on the next read —
    consul-template-style out-of-band rotation that the template
    watcher's re-render loop then delivers to tasks."""

    def __init__(self, path: str, default_ttl: float = 3600.0):
        super().__init__(default_ttl=default_ttl)
        import json
        import os
        self.path = path
        self._json = json
        self._os = os
        self._mtime = 0.0
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                blob = self._json.load(f)
        except (OSError, ValueError):
            return
        with self._lock:
            self.kv = {k: dict(v) for k, v in
                       (blob.get("kv") or {}).items()}
            self._tokens = {
                t: VaultToken(**rec) for t, rec in
                (blob.get("tokens") or {}).items()
                if rec.get("expires_at", 0) > time.time()}
            for tok in self._tokens.values():
                tok.policies = tuple(tok.policies)
        try:
            self._mtime = self._os.stat(self.path).st_mtime
        except OSError:
            pass

    def _flush_locked(self) -> None:
        import tempfile
        d = self._os.path.dirname(self.path) or "."
        self._os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            prefix=self._os.path.basename(self.path) + ".", dir=d)
        blob = {"kv": self.kv,
                "tokens": {t: dataclasses.asdict(tok)
                           for t, tok in self._tokens.items()}}
        try:
            with self._os.fdopen(fd, "w") as f:
                self._json.dump(blob, f)
            self._os.replace(tmp, self.path)
            self._mtime = self._os.stat(self.path).st_mtime
        except BaseException:       # incl. TypeError from non-JSON values
            try:
                self._os.unlink(tmp)
            except OSError:
                pass
            raise

    def _mutate(self, fn):
        """Read-modify-write under an inter-process flock: reload the
        CURRENT file state, apply the mutation, flush. Without the
        reload, a sidecar process's stale in-memory snapshot would
        clobber tokens the server derived since it started."""
        import fcntl
        d = self._os.path.dirname(self.path) or "."
        self._os.makedirs(d, exist_ok=True)
        lock_fd = self._os.open(self.path + ".lock",
                                self._os.O_CREAT | self._os.O_RDWR, 0o600)
        try:
            fcntl.flock(lock_fd, fcntl.LOCK_EX)
            self._load()
            with self._lock:
                out = fn()
                self._flush_locked()
            return out
        finally:
            self._os.close(lock_fd)

    def _maybe_reload(self) -> None:
        """Out-of-band edits (operator rotated a secret in the file) are
        picked up on the next read."""
        try:
            m = self._os.stat(self.path).st_mtime
        except OSError:
            return
        if m != self._mtime:
            self._load()

    def put(self, path, data):
        def apply():
            self.kv[path] = dict(data)
        self._mutate(apply)

    def read(self, path):
        self._maybe_reload()
        return super().read(path)

    def token_valid(self, token):
        self._maybe_reload()
        return super().token_valid(token)

    def derive_token(self, alloc_id, task, policies):
        def apply():
            tok = VaultToken(
                token=str(uuid.uuid4()), accessor=str(uuid.uuid4()),
                policies=tuple(policies), ttl_sec=self.default_ttl,
                expires_at=time.time() + self.default_ttl)
            self._tokens[tok.token] = tok
            return tok
        return self._mutate(apply)

    def renew_token(self, token):
        def apply():
            tok = self._tokens.get(token)
            if tok is None:
                raise ValueError("unknown or revoked token")
            if not tok.renewable:
                raise ValueError("token is not renewable")
            tok = dataclasses.replace(
                tok, expires_at=time.time() + tok.ttl_sec)
            self._tokens[token] = tok
            return tok
        return self._mutate(apply)

    def revoke_token(self, token):
        def apply():
            self._tokens.pop(token, None)
        self._mutate(apply)
