"""Secrets provider: the Vault integration redesigned as an interface
(ref nomad/vault.go vaultClient — token derivation/renewal/revocation —
and client/vaultclient/vaultclient.go).

The server owns one provider; clients derive per-task tokens through the
`Vault.DeriveToken` RPC exactly like the reference's Node.DeriveVaultToken
path (nomad/node_endpoint.go DeriveVaultToken). `InMemorySecretsProvider`
is the dev/test backend (static KV + local token issuance with TTLs); a
real Vault backend implements the same four methods over HTTP.
"""
from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Optional


@dataclasses.dataclass
class VaultToken:
    token: str = ""
    accessor: str = ""
    policies: tuple = ()
    ttl_sec: float = 3600.0
    expires_at: float = 0.0
    renewable: bool = True


class SecretsProvider:
    """ref nomad/vault.go VaultClient interface (subset that matters)."""

    def derive_token(self, alloc_id: str, task: str,
                     policies: list[str]) -> VaultToken:
        raise NotImplementedError

    def renew_token(self, token: str) -> VaultToken:
        raise NotImplementedError

    def revoke_token(self, token: str) -> None:
        raise NotImplementedError

    def read(self, path: str) -> Optional[dict]:
        """KV read for template rendering ({{secret "path"}})."""
        raise NotImplementedError


class InMemorySecretsProvider(SecretsProvider):
    """Dev-mode backend: static KV store + locally-issued TTL tokens.

    Cluster note: this backend is process-local, so all Vault RPCs are
    leader-routed (server.py RPC_ENDPOINTS); a leader failover loses issued
    tokens (clients re-derive via their renewal loop's failure path). A
    real Vault backend is an external shared service and has neither
    limitation."""

    def __init__(self, kv: Optional[dict[str, dict]] = None,
                 default_ttl: float = 3600.0):
        self.kv = dict(kv or {})
        self.default_ttl = default_ttl
        self._lock = threading.Lock()
        self._tokens: dict[str, VaultToken] = {}

    def put(self, path: str, data: dict) -> None:
        with self._lock:
            self.kv[path] = dict(data)

    def derive_token(self, alloc_id, task, policies):
        tok = VaultToken(
            token=str(uuid.uuid4()), accessor=str(uuid.uuid4()),
            policies=tuple(policies), ttl_sec=self.default_ttl,
            expires_at=time.time() + self.default_ttl)
        with self._lock:
            self._tokens[tok.token] = tok
        return tok

    def renew_token(self, token):
        with self._lock:
            tok = self._tokens.get(token)
            if tok is None:
                raise ValueError("unknown or revoked token")
            if not tok.renewable:
                raise ValueError("token is not renewable")
            tok = dataclasses.replace(
                tok, expires_at=time.time() + tok.ttl_sec)
            self._tokens[token] = tok
            return tok

    def revoke_token(self, token):
        with self._lock:
            self._tokens.pop(token, None)

    def token_valid(self, token: str) -> bool:
        with self._lock:
            tok = self._tokens.get(token)
            return tok is not None and tok.expires_at > time.time()

    def read(self, path):
        with self._lock:
            data = self.kv.get(path)
            return dict(data) if data is not None else None
