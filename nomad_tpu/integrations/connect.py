"""Service-mesh analog: Connect sidecar injection + the proxy itself
(ref nomad/job_endpoint_hooks.go jobConnectHook — admission-time sidecar
task/port injection — and client/allocrunner/taskrunner/
envoy_bootstrap_hook.go; the envoy data plane is replaced by an in-process
TCP proxy driver, the framework-native equivalent).

Mesh wiring:
  * every `connect.sidecar_service` service gets a dynamic ingress port
    and a `connect-proxy-<service>` prestart-sidecar task; the service is
    REGISTERED at the proxy's ingress port, so mesh traffic always enters
    through the sidecar (ingress -> 127.0.0.1:<service port>);
  * each declared upstream gets a local listener in the downstream's
    sidecar (127.0.0.1:<local_bind_port> -> a healthy catalog instance of
    the destination, which is itself that instance's sidecar ingress);
    tasks find it via NOMAD_UPSTREAM_ADDR_<dest> env, like the reference.
"""
from __future__ import annotations

import socket
import threading
import time

from ..structs import (
    NetworkResource, Port, Resources, Task, TaskLifecycle,
)

PROXY_PREFIX = "connect-proxy-"


def _sanitize(name: str) -> str:
    return name.replace("-", "_").upper()


def _expose_admission(svc, net) -> list[dict]:
    """Expose-check mutator (ref nomad/job_endpoint_hook_expose_check.go:21
    jobExposeCheckHook): an http/grpc check with ``expose = true`` on a
    connect service gets its own dynamic listener port on the sidecar —
    the proxy serves ONLY that check's path there — and the check is
    rewritten to probe through the proxy listener instead of the (mesh-
    private) service port. Returns the proxy task's expose listener
    config. Idempotent: an already-rewritten check is left alone."""
    out: list[dict] = []
    local_label = svc.port_label        # the service's REAL port, pre-
    for i, chk in enumerate(svc.checks):    # ingress rewrite
        if not (chk.get("expose") or chk.get("Expose")):
            continue
        ctype = (chk.get("type") or chk.get("Type") or "").lower()
        if ctype not in ("http", "grpc"):
            continue                    # ref: only http/grpc are exposable
        existing_label = chk.get("port_label") or chk.get("PortLabel") \
            or ""
        if existing_label.startswith("svc_expose_check_"):
            label = existing_label      # re-registration of expanded job
        else:
            label = f"svc_expose_check_{svc.name}_{i}"
            # both shapes: HCL-parsed checks are PascalCase, API/test
            # dicts snake_case
            chk["port_label"] = chk["PortLabel"] = label
        if not any(p.label == label for p in net.dynamic_ports):
            net.dynamic_ports.append(Port(label=label))
        out.append({"path": chk.get("path") or chk.get("Path") or "/",
                    "listener_port_label": label,
                    "local_path_port_label": local_label})
    return out


def connect_admission(job) -> None:
    """Admission mutator (ref job_endpoint_hooks.go:1): expand
    sidecar_service stanzas into proxy tasks + ports + upstream env.
    Idempotent — re-registering an already-expanded job injects nothing."""
    for tg in job.task_groups:
        sidecars = [s for s in tg.services
                    if s.connect and s.connect.get("SidecarService")
                    is not None]
        if not sidecars:
            continue
        existing = {t.name for t in tg.tasks}
        if tg.networks:
            net = tg.networks[0]
        else:
            net = NetworkResource()
            tg.networks.append(net)
        upstream_env: dict[str, str] = {}
        for svc in sidecars:
            proxy_task = PROXY_PREFIX + svc.name
            port_label = proxy_task
            sc = svc.connect["SidecarService"]
            upstreams = (sc.get("Proxy") or {}).get("Upstreams") or []
            for up in upstreams:
                upstream_env[
                    f"NOMAD_UPSTREAM_ADDR_{_sanitize(up['DestinationName'])}"
                ] = f"127.0.0.1:{up['LocalBindPort']}"
            if proxy_task in existing:
                continue            # already expanded (job re-register)
            expose = _expose_admission(svc, net)
            if not any(p.label == port_label for p in net.dynamic_ports):
                net.dynamic_ports.append(Port(label=port_label))
            tg.tasks.append(Task(
                name=proxy_task,
                driver="connect_proxy",
                lifecycle=TaskLifecycle(hook="prestart", sidecar=True),
                config={
                    "service": svc.name,
                    "namespace": job.namespace,
                    "ingress_port_label": port_label,
                    "local_service_port_label": svc.port_label,
                    "upstreams": [
                        {"destination": up["DestinationName"],
                         "local_bind_port": int(up["LocalBindPort"])}
                        for up in upstreams],
                    "expose": expose,
                },
                resources=Resources(cpu=50, memory_mb=32),
            ))
            # the mesh entry point IS the proxy: register the service at
            # the ingress port (ref job_endpoint_hooks: sidecar service
            # port rewrite)
            svc.port_label = port_label
        if upstream_env:
            for task in tg.tasks:
                if task.name.startswith(PROXY_PREFIX):
                    continue
                for k, v in upstream_env.items():
                    task.env.setdefault(k, v)


class _Forwarder(threading.Thread):
    """One listener: accept -> resolve target -> bidirectional splice."""

    def __init__(self, bind: tuple, resolve, logger, name: str):
        super().__init__(daemon=True, name=name)
        self.bind = bind
        self.resolve = resolve              # () -> (host, port) or None
        self.logger = logger
        self._stop = threading.Event()
        self.sock: socket.socket | None = None
        self.connections = 0

    def run(self) -> None:
        # bind with retry: a dying alloc's proxy (or any process on a
        # recycled dynamic port) may hold the address for a moment at
        # start — giving up permanently would leave the sidecar deaf for
        # the alloc's whole life
        srv = None
        warned = False
        while not self._stop.is_set():
            try:
                srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                srv.bind(self.bind)
                srv.listen(16)
                srv.settimeout(0.5)
                self.sock = srv
                break
            except OSError as e:
                if srv is not None:     # socket() itself may have raised
                    try:
                        srv.close()
                    except OSError:
                        pass
                srv = None
                if not warned:
                    self.logger(f"connect-proxy: bind {self.bind} failed "
                                f"({e!r}); retrying")
                    warned = True
                if self._stop.wait(1.0):
                    return
        if srv is None:
            return
        while not self._stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            target = self.resolve()
            if target is None:
                conn.close()
                continue
            self.connections += 1
            threading.Thread(target=self._splice, args=(conn, target),
                             daemon=True).start()
        try:
            srv.close()
        except OSError:
            pass

    def _splice(self, conn: socket.socket, target: tuple,
                preamble: bytes = b"") -> None:
        try:
            out = socket.create_connection(target, timeout=5.0)
            # the connect timeout must not become a 5s idle-read timeout
            # on the spliced stream
            out.settimeout(None)
            if preamble:
                out.sendall(preamble)   # bytes a screening subclass read
        except OSError as e:
            self.logger(f"connect-proxy: dial {target} failed: {e!r}")
            conn.close()
            return

        def pump(a, b):
            try:
                while True:
                    data = a.recv(65536)
                    if not data:
                        break
                    b.sendall(data)
            except OSError:
                pass
            finally:
                # asymmetric half-close: EOF from `a` ends only OUR write
                # direction on `b` — the reverse pump may still be
                # streaming a response (nc -q0 style half-close clients)
                try:
                    b.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
        t = threading.Thread(target=pump, args=(out, conn), daemon=True)
        t.start()
        pump(conn, out)
        # close only after BOTH directions finished: the reverse pump may
        # stream a long response after the client's half-close, and each
        # pump terminates on EOF/error by itself (no read timeouts)
        t.join()
        for s in (conn, out):
            try:
                s.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()


class ExposeForwarder(_Forwarder):
    """Expose-path listener (ref envoy's exposed path listeners, driven
    by job_endpoint_hook_expose_check.go): serves ONLY the configured
    HTTP path (exact, subpath, or query) and answers 403 to anything
    else — external health checkers get the check endpoint through the
    sidecar without the rest of the service leaking around the mesh."""

    def __init__(self, bind: tuple, resolve, logger, name: str,
                 path: str):
        super().__init__(bind, resolve, logger, name)
        self.path = path or "/"

    def _path_allowed(self, req_path: str) -> bool:
        base = self.path.rstrip("/") or "/"
        return (req_path == self.path or req_path == base
                or req_path.startswith(base + "/")
                or req_path.startswith(base + "?"))

    def _splice(self, conn: socket.socket, target: tuple,
                preamble: bytes = b"") -> None:
        # One screened request per connection: the FULL first request
        # (headers + declared body) is read, stamped `connection: close`,
        # and forwarded alone; the client half is never spliced raw, so
        # keep-alive or pipelined follow-ups can never ride a screened
        # connection past the path filter.
        try:
            conn.settimeout(3.0)
            buf = b""
            while b"\r\n\r\n" not in buf and len(buf) < 65536:
                chunk = conn.recv(8192)
                if not chunk:
                    break
                buf += chunk
            head, _, rest = buf.partition(b"\r\n\r\n")
            line = head.split(b"\r\n", 1)[0].decode("latin-1", "replace")
            parts = line.split()
            req_path = parts[1] if len(parts) >= 2 else ""
            if not self._path_allowed(req_path):
                conn.sendall(b"HTTP/1.1 403 Forbidden\r\n"
                             b"content-length: 0\r\n"
                             b"connection: close\r\n\r\n")
                conn.close()
                return
            clen = 0
            keep: list[bytes] = []
            for h in head.split(b"\r\n")[1:]:
                name = h.split(b":", 1)[0].strip().lower()
                if name == b"content-length":
                    try:
                        clen = int(h.split(b":", 1)[1])
                    except ValueError:
                        clen = 0
                if name != b"connection":
                    keep.append(h)
            body = rest[:clen]
            while len(body) < clen:
                chunk = conn.recv(min(65536, clen - len(body)))
                if not chunk:
                    break
                body += chunk
            request = (head.split(b"\r\n", 1)[0] + b"\r\n"
                       + b"\r\n".join(keep)
                       + (b"\r\n" if keep else b"")
                       + b"connection: close\r\n\r\n" + body)
        except OSError:
            try:
                conn.close()
            except OSError:
                pass
            return
        try:
            out = socket.create_connection(target, timeout=5.0)
            out.settimeout(None)
            out.sendall(request)
            out.shutdown(socket.SHUT_WR)
        except OSError as e:
            self.logger(f"connect-expose: dial {target} failed: {e!r}")
            conn.close()
            return
        try:
            while True:                 # response only: backend -> client
                data = out.recv(65536)
                if not data:
                    break
                conn.sendall(data)
        except OSError:
            pass
        for s in (conn, out):
            try:
                s.close()
            except OSError:
                pass
