"""Native service catalog + health checking: the Consul integration
redesigned as a built-in subsystem (ref nomad/consul.go +
command/agent/consul/service_client.go registration lifecycle and check
watching; the catalog itself is state-store-backed like the native service
discovery the reference line later added).

Registrations are raft-replicated rows keyed (namespace, service, alloc);
clients register/deregister through Service RPCs and run their checks
locally, pushing status transitions the same way Consul agents do.
"""
from __future__ import annotations

import dataclasses
import http.client
import socket
import threading
import urllib.parse
from typing import Callable, Optional

CHECK_PASSING = "passing"
CHECK_CRITICAL = "critical"

INTENTION_ALLOW = "allow"
INTENTION_DENY = "deny"


@dataclasses.dataclass
class ServiceIntention:
    """Mesh authorization rule (ref Consul intentions, consumed by the
    connect admission in the reference): may `source` open connections to
    `destination` through the sidecar data plane? "*" wildcards match any
    service; exact entries outrank wildcards (Consul's precedence)."""
    source: str = "*"
    destination: str = "*"
    action: str = INTENTION_ALLOW        # allow | deny
    namespace: str = "default"
    description: str = ""
    create_index: int = 0
    modify_index: int = 0

    def key(self) -> tuple[str, str, str]:
        return (self.namespace, self.source, self.destination)

    def copy(self) -> "ServiceIntention":
        return dataclasses.replace(self)


def intention_allowed(intentions, namespace: str, source: str,
                      destination: str) -> bool:
    """Most-specific-match decision (Consul precedence: exact/exact >
    exact/* > */exact > */*), default ALLOW with no matching rule."""
    best = None
    best_rank = -1
    for it in intentions:
        if it.namespace != namespace:
            continue
        if it.source not in ("*", source) or \
                it.destination not in ("*", destination):
            continue
        rank = (2 if it.source != "*" else 0) + \
               (1 if it.destination != "*" else 0)
        if rank > best_rank:
            best, best_rank = it, rank
    return best is None or best.action == INTENTION_ALLOW


@dataclasses.dataclass
class ServiceInstance:
    """One registered service instance (ref structs ServiceRegistration)."""
    service_name: str = ""
    namespace: str = "default"
    job_id: str = ""
    alloc_id: str = ""
    node_id: str = ""
    task: str = ""
    address: str = "127.0.0.1"
    port: int = 0
    tags: tuple = ()
    status: str = CHECK_PASSING
    create_index: int = 0
    modify_index: int = 0

    def key(self) -> tuple[str, str, str, str]:
        # task in the key: one alloc may expose the same service name from
        # several tasks (different ports) without rows clobbering each other
        return (self.namespace, self.service_name, self.alloc_id, self.task)

    def copy(self) -> "ServiceInstance":
        return dataclasses.replace(self)


def _ck(check: dict, key: str, default=""):
    """Check dicts arrive in snake_case (API/tests) or PascalCase (the
    HCL parser emits the reference's wire shape); read both."""
    v = check.get(key)
    if v is None:
        v = check.get(key[:1].upper() + key[1:])
    return default if v in (None, "") else v


def check_service(check: dict, address: str, port: int,
                  timeout: float = 3.0) -> bool:
    """Execute one health check definition (ref command/agent/consul
    check types: http/tcp). A check carrying its own resolved ``port``
    (expose listeners) probes that instead of the instance port."""
    port = int(_ck(check, "port", 0) or port)
    ctype = str(_ck(check, "type", "tcp")).lower()
    if ctype == "tcp":
        try:
            with socket.create_connection((address, port), timeout=timeout):
                return True
        except OSError:
            return False
    if ctype == "http":
        path = _ck(check, "path", "/")
        try:
            conn = http.client.HTTPConnection(address, port, timeout=timeout)
            conn.request(_ck(check, "method", "GET"), path)
            resp = conn.getresponse()
            resp.read()
            conn.close()
            return 200 <= resp.status < 400
        except (OSError, http.client.HTTPException):
            return False
    if ctype == "script":
        import shlex
        import subprocess
        try:
            return subprocess.run(
                shlex.split(_ck(check, "command", "/bin/true")),
                timeout=timeout, capture_output=True).returncode == 0
        except (OSError, ValueError, subprocess.TimeoutExpired):
            return False
    return True  # unknown check types pass (like a TTL check never set)


class CheckRunner:
    """Periodic check execution for one service instance; pushes status
    transitions through the provided callback (ref consul check_watcher)."""

    def __init__(self, instance: ServiceInstance, checks: list[dict],
                 on_status: Callable[[ServiceInstance, str], None],
                 interval: float = 5.0):
        self.instance = instance
        self.checks = checks
        self.on_status = on_status
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.status = CHECK_PASSING

    def start(self) -> None:
        if not self.checks:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"check-{self.instance.service_name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def run_once(self) -> str:
        ok = all(check_service(c, self.instance.address,
                               self.instance.port) for c in self.checks)
        status = CHECK_PASSING if ok else CHECK_CRITICAL
        if status != self.status:
            self.status = status
            self.on_status(self.instance, status)
        return status

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.run_once()
            except Exception:   # noqa: BLE001 — checks must never die
                pass
