"""Periodic job dispatch (ref nomad/periodic.go:22 PeriodicDispatch): a
leader-only cron launcher that materializes child jobs `<id>/periodic-<ts>`
and tracks launches in the periodic_launch table.
"""
from __future__ import annotations

import threading
import time
from datetime import datetime, timedelta, timezone
from typing import Optional

from ..structs import Evaluation, Job, TRIGGER_PERIODIC_JOB
from .fsm import JOB_REGISTER, PERIODIC_LAUNCH


def parse_cron_field(field: str, lo: int, hi: int) -> set[int]:
    out: set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            rng = range(lo, hi + 1)
        elif "-" in part:
            a, b = part.split("-", 1)
            rng = range(int(a), int(b) + 1)
        else:
            rng = range(int(part), int(part) + 1)
        out.update(v for v in rng if (v - lo) % step == 0)
    return out


def cron_next(spec: str, after: float, tz: str = "UTC") -> Optional[float]:
    """Next fire time strictly after `after` for a 5-field cron spec, or
    '@every <seconds>s' shorthand. The cron fields are interpreted in
    `tz` (ref structs.PeriodicConfig.TimeZone + GetLocation:
    "3 am every day" means 3 am IN THAT ZONE, across DST shifts)."""
    spec = spec.strip()
    if spec.startswith("@every"):
        arg = spec.split(None, 1)[1].strip()
        if arg.endswith("ms"):
            period = float(arg[:-2]) / 1000.0
        elif arg.endswith("s"):
            period = float(arg[:-1])
        elif arg.endswith("m"):
            period = float(arg[:-1]) * 60
        elif arg.endswith("h"):
            period = float(arg[:-1]) * 3600
        else:
            period = float(arg)
        return after + period
    fields = spec.split()
    if len(fields) != 5:
        return None
    mins = parse_cron_field(fields[0], 0, 59)
    hours = parse_cron_field(fields[1], 0, 23)
    doms = parse_cron_field(fields[2], 1, 31)
    months = parse_cron_field(fields[3], 1, 12)
    # cron DOW: Sun=0 (and 7 as the common Sunday alias)
    dows = {v % 7 for v in parse_cron_field(fields[4], 0, 7)}
    zone = timezone.utc
    if tz and tz.upper() != "UTC":
        try:
            from zoneinfo import ZoneInfo
            zone = ZoneInfo(tz)
        # unknown zone name: UTC fallback below is the documented
        # behavior, not a silent drop
        except Exception:  # nomadlint: disable=EXC001 — UTC fallback
            pass
    t = datetime.fromtimestamp(after, tz=zone).replace(
        second=0, microsecond=0) + timedelta(minutes=1)
    for _ in range(366 * 24 * 60):   # bounded search: one year of minutes
        cron_dow = (t.weekday() + 1) % 7   # Python Mon=0 -> cron Sun=0
        if (t.minute in mins and t.hour in hours and t.day in doms and
                t.month in months and cron_dow in dows):
            return t.timestamp()
        t += timedelta(minutes=1)
    return None


class PeriodicDispatch:
    """ref periodic.go:22"""

    def __init__(self, server):
        self.server = server
        self._lock = threading.Lock()
        self._tracked: dict[tuple[str, str], Job] = {}
        self._enabled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if enabled and self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(target=self._run, daemon=True,
                                                name="periodic-dispatch")
                self._thread.start()
            if not enabled:
                self._tracked.clear()

    def add(self, job: Job) -> None:
        """Track (or update) a periodic job (ref periodic.go Add)."""
        with self._lock:
            if not self._enabled:
                return
            if not job.is_periodic() or job.stopped():
                self._tracked.pop((job.namespace, job.id), None)
                return
            self._tracked[(job.namespace, job.id)] = job

    def remove(self, namespace: str, job_id: str) -> None:
        with self._lock:
            self._tracked.pop((namespace, job_id), None)

    def tracked(self) -> list[Job]:
        with self._lock:
            return list(self._tracked.values())

    def _run(self) -> None:
        """ref periodic.go:335 run"""
        while not self._stop.wait(1.0):
            with self._lock:
                if not self._enabled:
                    return
                jobs = list(self._tracked.values())
            now = time.time()
            for job in jobs:
                try:
                    self._maybe_launch(job, now)
                except Exception as e:   # noqa: BLE001
                    self.server.logger(f"periodic: {job.id}: {e!r}")

    def _maybe_launch(self, job: Job, now: float) -> None:
        state = self.server.state
        launch = state.periodic_launch_by_id(job.namespace, job.id)
        last = launch["launch"] if launch else 0.0
        tz = job.periodic.timezone or "UTC"
        nxt = cron_next(job.periodic.spec, last or now - 1.0, tz)
        if nxt is None or nxt > now:
            return
        # fast-forward past windows missed while down: launch at most once,
        # at the latest elapsed boundary (ref periodic.go nextLaunch)
        while True:
            after = cron_next(job.periodic.spec, nxt, tz)
            if after is None or after > now:
                break
            nxt = after
        if job.periodic.prohibit_overlap:
            for child in state.iter_jobs(job.namespace):
                # any non-terminal child (pending/blocked included) blocks
                if child.parent_id == job.id and child.status != "dead":
                    return
        self.force_launch(job, nxt)

    def force_launch(self, job: Job, launch_time: Optional[float] = None
                     ) -> Job:
        """Materialize + register the child job (ref periodic.go:413
        createEval / derivedJob)."""
        launch_time = launch_time or time.time()
        child = job.copy()
        child.id = f"{job.id}/periodic-{int(launch_time)}"
        child.parent_id = job.id
        child.periodic = None
        ev = Evaluation(
            namespace=child.namespace, priority=child.priority,
            type=child.type, triggered_by=TRIGGER_PERIODIC_JOB,
            job_id=child.id, status="pending")
        self.server.raft.apply(JOB_REGISTER, {"job": child, "evals": [ev]})
        self.server.raft.apply(PERIODIC_LAUNCH, {
            "namespace": job.namespace, "job_id": job.id,
            "launch": launch_time})
        return child
