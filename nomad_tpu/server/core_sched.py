"""Core (GC) scheduler (ref nomad/core_sched.go:27): internal `_core` evals
garbage-collect terminal evals/allocs, dead jobs, down nodes and finished
deployments past a GC threshold.
"""
from __future__ import annotations

import time

from ..structs import (
    Evaluation, CORE_JOB_EVAL_GC, CORE_JOB_JOB_GC, CORE_JOB_NODE_GC,
    CORE_JOB_DEPLOYMENT_GC, CORE_JOB_FORCE_GC, DEPLOYMENT_TERMINAL,
    JOB_STATUS_DEAD, EVAL_STATUS_COMPLETE,
)
from .fsm import (DEPLOYMENT_DELETE, EVAL_DELETE, JOB_DEREGISTER,
                  NODE_DEREGISTER)


class CoreScheduler:
    """Processes `_core` evaluations (job_id encodes the GC kind)."""

    def __init__(self, server, eval_gc_threshold: float = 3600.0,
                 job_gc_threshold: float = 4 * 3600.0,
                 node_gc_threshold: float = 24 * 3600.0,
                 deployment_gc_threshold: float = 3600.0):
        self.server = server
        self.eval_gc_threshold = eval_gc_threshold
        self.job_gc_threshold = job_gc_threshold
        self.node_gc_threshold = node_gc_threshold
        self.deployment_gc_threshold = deployment_gc_threshold

    def process(self, ev: Evaluation) -> None:
        """ref core_sched.go Process"""
        kind = ev.job_id
        force = kind == CORE_JOB_FORCE_GC
        if kind in (CORE_JOB_EVAL_GC,) or force:
            self.eval_gc(force)
        if kind in (CORE_JOB_JOB_GC,) or force:
            self.job_gc(force)
        if kind in (CORE_JOB_NODE_GC,) or force:
            self.node_gc(force)
        if kind in (CORE_JOB_DEPLOYMENT_GC,) or force:
            self.deployment_gc(force)

    def _cutoff(self, threshold: float, force: bool) -> float:
        return time.time() if force else time.time() - threshold

    def eval_gc(self, force: bool = False) -> int:
        """ref core_sched.go:231 evalGC: terminal evals whose allocs are all
        terminal."""
        state = self.server.state
        cutoff = self._cutoff(self.eval_gc_threshold, force)
        gc_evals, gc_allocs = [], []
        for ev in state.iter_evals():
            if not ev.terminal_status():
                continue
            if ev.modify_time_unix and ev.modify_time_unix > cutoff:
                continue
            allocs = state.allocs_by_eval(ev.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            # batch-job evals are kept while the job lives (rerun protection)
            job = state.job_by_id(ev.namespace, ev.job_id)
            if job is not None and job.type == "batch" and \
               job.status != JOB_STATUS_DEAD and not force:
                continue
            gc_evals.append(ev.id)
            gc_allocs.extend(a.id for a in allocs)
        if gc_evals:
            self.server.raft.apply(EVAL_DELETE, {
                "eval_ids": gc_evals, "alloc_ids": gc_allocs})
        return len(gc_evals)

    def job_gc(self, force: bool = False) -> int:
        """ref core_sched.go:94 jobGC: dead jobs with no live evals/allocs,
        older than the GC threshold (unless forced)."""
        state = self.server.state
        cutoff = self._cutoff(self.job_gc_threshold, force)
        gc = []
        for job in state.iter_jobs():
            if job.status != JOB_STATUS_DEAD:
                continue
            if job.is_periodic() or job.is_parameterized():
                continue
            evals = state.evals_by_job(job.namespace, job.id)
            if any(not e.terminal_status() for e in evals):
                continue
            allocs = state.allocs_by_job(job.namespace, job.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            last_activity = max(
                [job.submit_time] +
                [e.modify_time_unix for e in evals] +
                [a.modify_time_unix for a in allocs])
            if last_activity > cutoff:
                continue
            gc.append(job)
        for job in gc:
            eval_ids = [e.id for e in state.evals_by_job(job.namespace, job.id)]
            alloc_ids = [a.id for a in state.allocs_by_job(job.namespace, job.id)]
            if eval_ids or alloc_ids:
                self.server.raft.apply(EVAL_DELETE, {
                    "eval_ids": eval_ids, "alloc_ids": alloc_ids})
            self.server.raft.apply(JOB_DEREGISTER, {
                "namespace": job.namespace, "job_id": job.id, "purge": True})
        return len(gc)

    def node_gc(self, force: bool = False) -> int:
        """ref core_sched.go:434 nodeGC: down nodes without allocs."""
        state = self.server.state
        cutoff = self._cutoff(self.node_gc_threshold, force)
        gc = []
        for node in state.iter_nodes():
            if not node.terminal_status():
                continue
            if node.status_updated_at > cutoff:
                continue
            if any(not a.terminal_status()
                   for a in state.allocs_by_node(node.id)):
                continue
            gc.append(node.id)
        if gc:
            self.server.raft.apply(NODE_DEREGISTER, {"node_ids": gc})
        return len(gc)

    def deployment_gc(self, force: bool = False) -> int:
        """ref core_sched.go deploymentGC"""
        state = self.server.state
        cutoff = self._cutoff(self.deployment_gc_threshold, force)
        gc = []
        for d in state.iter_deployments():
            if d.status not in DEPLOYMENT_TERMINAL:
                continue
            if d.modify_time_unix and d.modify_time_unix > cutoff:
                continue
            gc.append(d.id)
        if gc:
            self.server.raft.apply(DEPLOYMENT_DELETE, {"deployment_ids": gc})
        return len(gc)
