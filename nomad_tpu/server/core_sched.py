"""Core (GC) scheduler (ref nomad/core_sched.go:27): internal `_core` evals
garbage-collect terminal evals/allocs, dead jobs, down nodes and finished
deployments past a GC threshold.

Also owns the dead-letter half of the failed-eval lifecycle (ISSUE 3):
evals that exhaust their broker delivery limit are terminated as failed
and re-tried via a delayed `failed-follow-up` eval whose wait grows with
capped exponential backoff per generation — a permanently-broken eval
backs off to FAILED_EVAL_BACKOFF_CAP_S instead of hot-looping workers,
while a transiently-broken one (device loss, raft hiccup) retries
quickly. Operators can take an eval out of the loop entirely with the
agent's /v1/operator/broker/drain-failed.
"""
from __future__ import annotations

import time

from ..metrics import metrics
from ..structs import (
    Evaluation, CORE_JOB_EVAL_GC, CORE_JOB_JOB_GC, CORE_JOB_NODE_GC,
    CORE_JOB_DEPLOYMENT_GC, CORE_JOB_FAILED_EVAL_REAP, CORE_JOB_FORCE_GC,
    DEPLOYMENT_TERMINAL, JOB_STATUS_DEAD, EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
)
from .eval_broker import FAILED_QUEUE
from .fsm import (DEPLOYMENT_DELETE, EVAL_DELETE, EVAL_UPDATE,
                  JOB_DEREGISTER, NODE_DEREGISTER)

# failed-follow-up backoff: base * 2^generation, capped (ref
# nomad/leader.go:782 reapFailedEvaluations, which uses a fixed 1m wait;
# the cap keeps a permanently-failing eval to ~4 retries/hour)
FAILED_EVAL_BACKOFF_BASE_S = 60.0
FAILED_EVAL_BACKOFF_CAP_S = 900.0


def failed_follow_up_wait(ev: Evaluation) -> float:
    """Deterministic capped exponential backoff keyed on the eval's
    follow-up generation (no jitter: determinism is a correctness
    property here, DET001)."""
    gen = min(max(int(ev.failed_follow_ups), 0), 16)
    return min(FAILED_EVAL_BACKOFF_CAP_S,
               FAILED_EVAL_BACKOFF_BASE_S * (2 ** gen))


class CoreScheduler:
    """Processes `_core` evaluations (job_id encodes the GC kind)."""

    def __init__(self, server, eval_gc_threshold: float = 3600.0,
                 job_gc_threshold: float = 4 * 3600.0,
                 node_gc_threshold: float = 24 * 3600.0,
                 deployment_gc_threshold: float = 3600.0):
        self.server = server
        self.eval_gc_threshold = eval_gc_threshold
        self.job_gc_threshold = job_gc_threshold
        self.node_gc_threshold = node_gc_threshold
        self.deployment_gc_threshold = deployment_gc_threshold

    def process(self, ev: Evaluation) -> None:
        """ref core_sched.go Process"""
        kind = ev.job_id
        force = kind == CORE_JOB_FORCE_GC
        if kind in (CORE_JOB_EVAL_GC,) or force:
            self.eval_gc(force)
        if kind in (CORE_JOB_JOB_GC,) or force:
            self.job_gc(force)
        if kind in (CORE_JOB_NODE_GC,) or force:
            self.node_gc(force)
        if kind in (CORE_JOB_DEPLOYMENT_GC,) or force:
            self.deployment_gc(force)
        if kind in (CORE_JOB_FAILED_EVAL_REAP,) or force:
            self.reap_failed_evals()

    def _cutoff(self, threshold: float, force: bool) -> float:
        return time.time() if force else time.time() - threshold

    def reap_failed_evals(self) -> int:
        """Dead-letter consumer (ref leader.go:782 reapFailedEvaluations):
        terminate each dead-lettered eval as failed and emit the delayed
        failed-follow-up with capped exponential backoff. Called every
        leader-loop tick and by `_core`/force-gc evals."""
        broker = self.server.eval_broker
        n = 0
        while True:
            ev, token = broker.dequeue([FAILED_QUEUE], timeout=0.0)
            if ev is None:
                return n
            failed = ev.copy()
            failed.status = EVAL_STATUS_FAILED
            failed.status_description = "evaluation reached delivery limit"
            wait = failed_follow_up_wait(ev)
            follow_up = ev.create_failed_follow_up_eval(wait_sec=wait)
            self.server.raft.apply(EVAL_UPDATE,
                                   {"evals": [failed, follow_up]})
            # count AFTER the commit: a failed apply redelivers the
            # eval and re-reaps it later — counting up front would
            # overstate reaps in the bench robustness block
            metrics.incr("nomad.broker.dead_letter_reaped")
            metrics.add_sample("nomad.broker.dead_letter_backoff", wait)
            try:
                broker.ack(ev.id, token)
            except ValueError:
                pass
            n += 1

    def eval_gc(self, force: bool = False) -> int:
        """ref core_sched.go:231 evalGC: terminal evals whose allocs are all
        terminal."""
        state = self.server.state
        cutoff = self._cutoff(self.eval_gc_threshold, force)
        gc_evals, gc_allocs = [], []
        for ev in state.iter_evals():
            if not ev.terminal_status():
                continue
            if ev.modify_time_unix and ev.modify_time_unix > cutoff:
                continue
            allocs = state.allocs_by_eval(ev.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            # batch-job evals are kept while the job lives (rerun protection)
            job = state.job_by_id(ev.namespace, ev.job_id)
            if job is not None and job.type == "batch" and \
               job.status != JOB_STATUS_DEAD and not force:
                continue
            gc_evals.append(ev.id)
            gc_allocs.extend(a.id for a in allocs)
        if gc_evals:
            self.server.raft.apply(EVAL_DELETE, {
                "eval_ids": gc_evals, "alloc_ids": gc_allocs})
        return len(gc_evals)

    def job_gc(self, force: bool = False) -> int:
        """ref core_sched.go:94 jobGC: dead jobs with no live evals/allocs,
        older than the GC threshold (unless forced)."""
        state = self.server.state
        cutoff = self._cutoff(self.job_gc_threshold, force)
        gc = []
        for job in state.iter_jobs():
            if job.status != JOB_STATUS_DEAD:
                continue
            if job.is_periodic() or job.is_parameterized():
                continue
            evals = state.evals_by_job(job.namespace, job.id)
            if any(not e.terminal_status() for e in evals):
                continue
            allocs = state.allocs_by_job(job.namespace, job.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            last_activity = max(
                [job.submit_time] +
                [e.modify_time_unix for e in evals] +
                [a.modify_time_unix for a in allocs])
            if last_activity > cutoff:
                continue
            gc.append(job)
        for job in gc:
            eval_ids = [e.id for e in state.evals_by_job(job.namespace, job.id)]
            alloc_ids = [a.id for a in state.allocs_by_job(job.namespace, job.id)]
            if eval_ids or alloc_ids:
                self.server.raft.apply(EVAL_DELETE, {
                    "eval_ids": eval_ids, "alloc_ids": alloc_ids})
            self.server.raft.apply(JOB_DEREGISTER, {
                "namespace": job.namespace, "job_id": job.id, "purge": True})
        return len(gc)

    def node_gc(self, force: bool = False) -> int:
        """ref core_sched.go:434 nodeGC: down nodes without allocs."""
        state = self.server.state
        cutoff = self._cutoff(self.node_gc_threshold, force)
        gc = []
        for node in state.iter_nodes():
            if not node.terminal_status():
                continue
            if node.status_updated_at > cutoff:
                continue
            if any(not a.terminal_status()
                   for a in state.allocs_by_node(node.id)):
                continue
            gc.append(node.id)
        if gc:
            self.server.raft.apply(NODE_DEREGISTER, {"node_ids": gc})
        return len(gc)

    def deployment_gc(self, force: bool = False) -> int:
        """ref core_sched.go deploymentGC"""
        state = self.server.state
        cutoff = self._cutoff(self.deployment_gc_threshold, force)
        gc = []
        for d in state.iter_deployments():
            if d.status not in DEPLOYMENT_TERMINAL:
                continue
            if d.modify_time_unix and d.modify_time_unix > cutoff:
                continue
            gc.append(d.id)
        if gc:
            self.server.raft.apply(DEPLOYMENT_DELETE, {"deployment_ids": gc})
        return len(gc)
