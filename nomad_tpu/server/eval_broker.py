"""Eval broker: leader-only priority queue of evaluations with ack/nack
semantics (ref nomad/eval_broker.go:47).

Per-scheduler-type priority heaps; at most one eval per job outstanding —
later evals for the same job wait in a pending map (dedup, ref
eval_broker.go:182 Enqueue); nacked evals requeue with escalating delay;
wait_until evals sit in a delay heap served by a timer thread
(ref :758 runDelayedEvalsWatcher).

The broker is also the eval-stream micro-batcher's concurrency oracle:
every dequeue/ack/nack pushes the outstanding-eval count to
solver/microbatch.py, so a worker's small solve knows whether sibling
evals are in flight (worth waiting the coalescing window for) before the
siblings have even reached their own solve call.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Optional

from ..metrics import metrics
from ..obs import trace
from ..structs import Evaluation, new_id

DEFAULT_NACK_TIMEOUT = 60.0
DEFAULT_INITIAL_NACK_DELAY = 1.0
DEFAULT_SUBSEQUENT_NACK_DELAY = 20.0

FAILED_QUEUE = "_failed"


class EvalBroker:
    def __init__(self, nack_timeout: float = DEFAULT_NACK_TIMEOUT,
                 initial_nack_delay: float = DEFAULT_INITIAL_NACK_DELAY,
                 subsequent_nack_delay: float = DEFAULT_SUBSEQUENT_NACK_DELAY,
                 delivery_limit: int = 3):
        self.nack_timeout = nack_timeout
        self.initial_nack_delay = initial_nack_delay
        self.subsequent_nack_delay = subsequent_nack_delay
        self.delivery_limit = delivery_limit

        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._enabled = False
        self._seq = itertools.count()

        # scheduler type -> heap of (-priority, seq, eval_id)
        self._ready: dict[str, list] = {}
        self._evals: dict[str, Evaluation] = {}        # eval_id -> eval
        self._dequeue_count: dict[str, int] = {}       # eval_id -> deliveries
        # (namespace, job_id) -> blocked evals waiting on the outstanding one
        self._pending: dict[tuple[str, str], list[Evaluation]] = {}
        self._outstanding_jobs: dict[tuple[str, str], str] = {}  # -> eval_id
        self._ready_jobs: dict[tuple[str, str], str] = {}        # -> eval_id
        self._unack: dict[str, dict] = {}              # eval_id -> {token, deadline}

        # delayed evals: (wait_until, seq, eval)
        self._delay_heap: list = []
        self._timer: Optional[threading.Thread] = None
        self._shutdown = False

        self.stats = {"total_ready": 0, "total_unacked": 0,
                      "total_pending": 0, "total_waiting": 0,
                      "total_failed": 0}

    def _notify_inflight(self) -> None:
        """Push the outstanding-eval count to the solver micro-batcher
        (its coalescing oracle). Lazy import: the broker must not drag
        jax in; a stripped build without the solver is a no-op."""
        try:
            from ..solver import microbatch
        except ImportError:
            return
        microbatch.broker_in_flight(self.stats["total_unacked"])

    # ------------------------------------------------------------- control

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            was = self._enabled
            self._enabled = enabled
            if not enabled:
                self._flush_locked()
            elif not was:
                self._shutdown = False
                self._timer = threading.Thread(
                    target=self._run_delayed_watcher, daemon=True)
                self._timer.start()
            self._cond.notify_all()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def _flush_locked(self) -> None:
        """Caller holds self._lock (the *_locked convention LOCK001
        checks; ref eval_broker.go flush, called under b.l)."""
        # every live trace this broker started ends here with the flush
        # disposition — the worker processing an outstanding eval may
        # still be mid-span on its own thread, so truncate (no span-leak
        # accounting) rather than demand a clean close (ISSUE 7)
        flushed = set(self._evals) | set(self._unack)
        for pend in self._pending.values():
            flushed.update(ev.id for ev in pend)
        flushed.update(item[2].id for item in self._delay_heap)
        for eval_id in flushed:
            trace.end_eval(eval_id, "flushed", truncate=True,
                           owner=id(self))
        self._ready.clear()
        self._ready_jobs.clear()
        self._evals.clear()
        self._pending.clear()
        self._outstanding_jobs.clear()
        self._unack.clear()
        self._dequeue_count.clear()
        self._delay_heap = []
        self._shutdown = True
        # every stat is maintained incrementally (+=/-=) against the
        # queues just cleared — zero them ALL or the stats endpoint
        # reports a phantom backlog for the life of the process
        self.stats["total_ready"] = 0
        self.stats["total_unacked"] = 0
        self.stats["total_pending"] = 0
        self.stats["total_waiting"] = 0
        self.stats["total_failed"] = 0
        metrics.set_gauge("nomad.broker.failed_queue_depth", 0)
        self._notify_inflight()

    # ------------------------------------------------------------- enqueue

    def enqueue(self, eval: Evaluation) -> None:
        with self._lock:
            self._enqueue_locked(eval)

    def enqueue_all(self, evals: list[tuple[Evaluation, str]]) -> None:
        """Enqueue evals with optional ack tokens: an eval being re-enqueued
        while outstanding is requeued once its current delivery acks/nacks
        (ref eval_broker.go EnqueueAll)."""
        with self._lock:
            for ev, token in evals:
                if token and ev.id in self._unack:
                    # mark for requeue on ack
                    self._unack[ev.id]["requeue"] = ev
                else:
                    self._enqueue_locked(ev)

    def _enqueue_locked(self, ev: Evaluation) -> None:
        if not self._enabled:
            return
        if ev.id in self._evals:
            return
        # the eval's trace begins at broker ENQUEUE: queue/delay/pending
        # wait is attributed as `broker.wait` when it dequeues. Idempotent
        # for live traces (delayed/pending re-enqueues keep theirs); a
        # fresh trace starts after a completed one ended (requeue-on-ack).
        trace.begin_eval(ev.id, "eval", owner=id(self), job=ev.job_id,
                         type=ev.type, trigger=ev.triggered_by,
                         priority=ev.priority)
        now = time.time()
        if ev.wait_until_unix and ev.wait_until_unix > now:
            heapq.heappush(self._delay_heap,
                           (ev.wait_until_unix, next(self._seq), ev))
            self.stats["total_waiting"] += 1
            self._cond.notify_all()
            return
        if ev.wait_sec:
            heapq.heappush(self._delay_heap,
                           (now + ev.wait_sec, next(self._seq), ev))
            self.stats["total_waiting"] += 1
            self._cond.notify_all()
            return
        job_key = (ev.namespace, ev.job_id)
        if ev.job_id and (job_key in self._outstanding_jobs or
                          job_key in self._ready_jobs):
            # dedup: at most one eval per job ready-or-outstanding; later
            # ones wait in pending until it acks (ref eval_broker.go:182)
            self._pending.setdefault(job_key, []).append(ev)
            self.stats["total_pending"] += 1
            return
        self._evals[ev.id] = ev
        if ev.job_id:
            self._ready_jobs[job_key] = ev.id
        heapq.heappush(self._ready.setdefault(ev.type, []),
                       (-ev.priority, next(self._seq), ev.id))
        self.stats["total_ready"] += 1
        self._cond.notify_all()

    # ------------------------------------------------------------- dequeue

    def dequeue(self, schedulers: list[str], timeout: Optional[float] = None
                ) -> tuple[Optional[Evaluation], str]:
        """Blocking dequeue; returns (eval, ack_token) (ref :335)."""
        deadline = time.time() + timeout if timeout is not None else None
        with self._lock:
            while True:
                if not self._enabled:
                    return None, ""
                best = self._pick_locked(schedulers)
                if best is not None:
                    self._notify_inflight()
                    trace.mark_dequeued(
                        best[0].id,
                        deliveries=self._dequeue_count.get(best[0].id, 1))
                    return best
                if deadline is not None:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        return None, ""
                    self._cond.wait(remaining)
                else:
                    self._cond.wait(1.0)

    def _pick_locked(self, schedulers: list[str]
                     ) -> Optional[tuple[Evaluation, str]]:
        best_key = None
        best_queue = None
        for sched in schedulers:
            heap = self._ready.get(sched)
            while heap and heap[0][2] not in self._evals:
                heapq.heappop(heap)  # stale entry
            if not heap:
                continue
            if best_key is None or heap[0] < best_key:
                best_key = heap[0]
                best_queue = sched
        if best_queue is None:
            return None
        _, _, eval_id = heapq.heappop(self._ready[best_queue])
        ev = self._evals.pop(eval_id)
        if best_queue == FAILED_QUEUE:
            self.stats["total_failed"] -= 1
            metrics.set_gauge("nomad.broker.failed_queue_depth",
                              self.stats["total_failed"])
        if ev.job_id and self._ready_jobs.get((ev.namespace, ev.job_id)) == eval_id:
            del self._ready_jobs[(ev.namespace, ev.job_id)]
        self.stats["total_ready"] -= 1
        token = new_id()
        self._unack[eval_id] = {
            "token": token,
            "eval": ev,
            "deadline": time.time() + self.nack_timeout,
        }
        self.stats["total_unacked"] += 1
        self._dequeue_count[eval_id] = self._dequeue_count.get(eval_id, 0) + 1
        if ev.job_id:
            self._outstanding_jobs[(ev.namespace, ev.job_id)] = eval_id
        return ev, token

    def outstanding(self, eval_id: str) -> Optional[str]:
        with self._lock:
            rec = self._unack.get(eval_id)
            return rec["token"] if rec else None

    def outstanding_reset(self, eval_id: str, token: str) -> str:
        """Reset the nack timer (heartbeat from a busy worker)."""
        with self._lock:
            rec = self._unack.get(eval_id)
            if rec is None:
                return "not outstanding"
            if rec["token"] != token:
                return "token mismatch"
            rec["deadline"] = time.time() + self.nack_timeout
            return ""

    # ------------------------------------------------------------ ack/nack

    def ack(self, eval_id: str, token: str) -> None:
        """ref :537"""
        with self._lock:
            rec = self._unack.get(eval_id)
            if rec is None or rec["token"] != token:
                raise ValueError(f"token mismatch for eval {eval_id}")
            del self._unack[eval_id]
            self.stats["total_unacked"] -= 1
            self._dequeue_count.pop(eval_id, None)
            ev = rec["eval"]
            job_key = (ev.namespace, ev.job_id)
            if self._outstanding_jobs.get(job_key) == eval_id:
                del self._outstanding_jobs[job_key]
            # release one pending eval for this job
            pending = self._pending.get(job_key)
            if pending:
                nxt = pending.pop(0)
                if not pending:
                    del self._pending[job_key]
                self.stats["total_pending"] -= 1
                self._enqueue_locked(nxt)
            requeue = rec.get("requeue")
            if requeue is not None:
                self._enqueue_locked(requeue)
            self._notify_inflight()
            self._cond.notify_all()

    def nack(self, eval_id: str, token: str) -> None:
        """Failed delivery: requeue with delay or move to failed queue
        (ref :601)."""
        with self._lock:
            rec = self._unack.get(eval_id)
            if rec is None or rec["token"] != token:
                raise ValueError(f"token mismatch for eval {eval_id}")
            del self._unack[eval_id]
            self.stats["total_unacked"] -= 1
            ev = rec["eval"]
            job_key = (ev.namespace, ev.job_id)
            if self._outstanding_jobs.get(job_key) == eval_id:
                del self._outstanding_jobs[job_key]
            count = self._dequeue_count.get(eval_id, 1)
            if count >= self.delivery_limit:
                # dead-letter: deliver once more via the failed queue
                # (the leader's reaper terminates it and emits the
                # backed-off failed-follow-up, ref leader.go:782)
                self._evals[ev.id] = ev
                if ev.job_id:
                    self._ready_jobs[job_key] = ev.id
                heapq.heappush(self._ready.setdefault(FAILED_QUEUE, []),
                               (-ev.priority, next(self._seq), ev.id))
                self.stats["total_ready"] += 1
                self.stats["total_failed"] += 1
                metrics.incr("nomad.broker.dead_letter")
                metrics.set_gauge("nomad.broker.failed_queue_depth",
                                  self.stats["total_failed"])
            else:
                delay = (self.initial_nack_delay if count == 1
                         else self.subsequent_nack_delay)
                heapq.heappush(self._delay_heap,
                               (time.time() + delay, next(self._seq), ev))
                self.stats["total_waiting"] += 1
            self._notify_inflight()
            self._cond.notify_all()

    # ------------------------------------------------------ dead letters

    def failed_evals(self) -> list[Evaluation]:
        """The evals currently parked on the dead-letter queue (operator
        visibility via /v1/operator/broker/failed)."""
        with self._lock:
            heap = self._ready.get(FAILED_QUEUE, [])
            return [self._evals[eid] for _, _, eid in heap
                    if eid in self._evals]

    def drain_failed(self) -> tuple[list[Evaluation], list[Evaluation]]:
        """Operator drain: atomically remove every dead-lettered eval
        AND every not-yet-dispatched failed-follow-up (delay heap or
        ready, not outstanding) from the queue. One lock acquisition
        covers both, so the leader reaper — which converts dead letters
        into delayed follow-ups every tick — cannot interleave: whatever
        form the broken eval currently takes, the drain catches it. The
        caller terminates them in state and RESTORES them via
        enqueue/restore_failed if that commit fails. Pending evals
        blocked behind a drained eval's job are released, like an ack
        would. Returns (dead_letters, follow_ups)."""
        from ..structs import TRIGGER_FAILED_FOLLOW_UP
        with self._lock:
            heap = self._ready.get(FAILED_QUEUE, [])
            drained = [self._evals.pop(eid) for _, _, eid in heap
                       if eid in self._evals]
            self._ready.pop(FAILED_QUEUE, None)
            self.stats["total_ready"] -= len(drained)
            self.stats["total_failed"] -= len(drained)
            # waiting follow-ups in the delay heap
            follows = []
            keep = []
            for item in self._delay_heap:
                if item[2].triggered_by == TRIGGER_FAILED_FOLLOW_UP:
                    follows.append(item[2])
                    self.stats["total_waiting"] -= 1
                else:
                    keep.append(item)
            if follows:
                heapq.heapify(keep)
                self._delay_heap = keep
            # ready (undelivered) follow-ups; outstanding ones are left
            # to finish — their result commits through the normal path
            for qname, qheap in self._ready.items():
                for _, _, eid in list(qheap):
                    ev = self._evals.get(eid)
                    if ev is not None and \
                            ev.triggered_by == TRIGGER_FAILED_FOLLOW_UP:
                        follows.append(self._evals.pop(eid))
                        self.stats["total_ready"] -= 1
            removed = drained + follows
            for ev in removed:
                self._dequeue_count.pop(ev.id, None)
                job_key = (ev.namespace, ev.job_id)
                if self._ready_jobs.get(job_key) == ev.id:
                    del self._ready_jobs[job_key]
                pending = self._pending.get(job_key)
                if pending:
                    nxt = pending.pop(0)
                    if not pending:
                        del self._pending[job_key]
                    self.stats["total_pending"] -= 1
                    self._enqueue_locked(nxt)
            if drained:
                metrics.incr("nomad.broker.dead_letter_drained",
                             len(drained))
            metrics.set_gauge("nomad.broker.failed_queue_depth",
                              self.stats["total_failed"])
            self._cond.notify_all()
            return drained, follows

    def restore_failed(self, evals: list[Evaluation]) -> None:
        """Put drained evals back (the drain's raft commit failed): they
        re-enter the normal queues; their preserved dequeue counts send
        repeat offenders straight back to the dead-letter path."""
        with self._lock:
            for ev in evals:
                self._enqueue_locked(ev)

    # -------------------------------------------------------- delay watcher

    def _run_delayed_watcher(self) -> None:
        """ref :758 runDelayedEvalsWatcher"""
        while True:
            with self._lock:
                if self._shutdown or not self._enabled:
                    return
                now = time.time()
                while self._delay_heap and self._delay_heap[0][0] <= now:
                    _, _, ev = heapq.heappop(self._delay_heap)
                    self.stats["total_waiting"] -= 1
                    ev = ev.copy()
                    ev.wait_sec = 0.0
                    ev.wait_until_unix = 0.0
                    self._enqueue_locked(ev)
                wait = 0.2
                if self._delay_heap:
                    wait = min(wait, max(0.01, self._delay_heap[0][0] - now))
                self._cond.wait(wait)

    def check_nack_timeouts(self) -> list[str]:
        """Requeue unacked evals past their deadline; returns timed-out ids.
        Called by the leader loop tick."""
        out = []
        with self._lock:
            now = time.time()
            for eval_id, rec in list(self._unack.items()):
                if rec["deadline"] <= now:
                    out.append(eval_id)
                    try:
                        self.nack(eval_id, rec["token"])
                    except ValueError:
                        pass
        return out
