"""Eval broker: leader-only priority queue of evaluations with ack/nack
semantics (ref nomad/eval_broker.go:47).

Per-scheduler-type priority heaps; at most one eval per job outstanding —
later evals for the same job wait in a pending map (dedup, ref
eval_broker.go:182 Enqueue); nacked evals requeue with escalating delay;
wait_until evals sit in a delay heap served by a timer thread
(ref :758 runDelayedEvalsWatcher).

The broker is also the eval-stream micro-batcher's concurrency oracle:
every dequeue/ack/nack pushes the outstanding-eval count to
solver/microbatch.py, so a worker's small solve knows whether sibling
evals are in flight (worth waiting the coalescing window for) before the
siblings have even reached their own solve call.

The broker is also the first line of overload protection (ISSUE 8):
its backlog is bounded by the hot-reloadable `broker_depth_cap`, and on
overflow the LOWEST-priority queued eval — deterministically by
(priority, seq): lowest priority first, newest arrival within a
priority — is shed into the existing dead-letter lifecycle, where the
leader reaper terminates it and emits a backed-off failed-follow-up.
Shed work retries with backoff instead of vanishing; core/system evals
are never shed. Evals are stamped with an enqueue TTL
(`eval_deadline_s`) so downstream stages can drop work whose caller
already gave up (worker.py, plan_apply.py; docs/OVERLOAD.md).
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from typing import Callable, Optional

from .. import faults
from ..metrics import metrics, record_swallowed_error
from ..obs import trace
from ..structs import (
    Evaluation, TRIGGER_FAILED_FOLLOW_UP, TRIGGER_NODE_UPDATE, new_id,
)

DEFAULT_NACK_TIMEOUT = 60.0
DEFAULT_INITIAL_NACK_DELAY = 1.0
DEFAULT_SUBSEQUENT_NACK_DELAY = 20.0

FAILED_QUEUE = "_failed"

# scheduler types exempt from overload shedding: internal housekeeping
# (`_core`) and system jobs keep the cluster itself alive — shedding them
# to make room for user load would trade availability for goodput
SHED_EXEMPT_TYPES = frozenset({"_core", "system"})

# triggers that are never shed victims AND bypass the depth cap:
# failed-follow-ups are the shed/dead-letter lifecycle's own retry
# channel (capping them re-sheds what shedding just parked), and
# node-update evals are the replacement path for work LOST to a node
# failure — dead-lettering those behind user churn would leave dead
# allocs unreplaced exactly when the cluster is busiest (ISSUE 10)
SHED_EXEMPT_TRIGGERS = frozenset({TRIGGER_FAILED_FOLLOW_UP,
                                  TRIGGER_NODE_UPDATE})
# node-update evals also skip the enqueue TTL: replacement of lost
# allocs must complete eventually, not expire behind a burst
DEADLINE_EXEMPT_TRIGGERS = frozenset({TRIGGER_NODE_UPDATE})


class EvalBroker:
    def __init__(self, nack_timeout: float = DEFAULT_NACK_TIMEOUT,
                 initial_nack_delay: float = DEFAULT_INITIAL_NACK_DELAY,
                 subsequent_nack_delay: float = DEFAULT_SUBSEQUENT_NACK_DELAY,
                 delivery_limit: int = 3,
                 config_fn: Optional[Callable] = None):
        self.nack_timeout = nack_timeout
        self.initial_nack_delay = initial_nack_delay
        self.subsequent_nack_delay = subsequent_nack_delay
        self.delivery_limit = delivery_limit
        # overload knobs (ISSUE 8): `config_fn` returns the live
        # SchedulerConfiguration (hot-reloadable; the server wires
        # state.get_scheduler_config); without one the explicit
        # attributes apply (0 = unbounded / no TTL — standalone brokers
        # in unit tests keep the pre-overload behavior)
        self.config_fn = config_fn
        self.depth_cap = 0
        self.eval_deadline_s = 0.0
        # poked whenever the cap trips (shed or exempt-overflow) so the
        # pressure state reacts to a sub-second burst instead of waiting
        # for the next 1s leader tick; the server wires overload.tick
        self.on_overflow: Optional[Callable] = None
        # (priority, seq, eval_id) of recent sheds — the hammer test's
        # determinism witness; bounded so a shed storm cannot leak
        self.shed_log: deque = deque(maxlen=4096)
        # heap entries invalidated by a shed: the eval moved to the
        # FAILED_QUEUE heap but stays in self._evals, so the stale-entry
        # skip in _pick_locked can't key on eval id alone
        self._shed_entries: set = set()
        # delayed failed-follow-ups (the shed/dead-letter RETRY channel)
        # parked in the delay heap: excluded from the depth the cap
        # bounds — they are backoff-parked retries, not offered load,
        # and counting them would let one burst's follow-ups re-trigger
        # shedding forever (shed -> follow-up -> depth -> shed ...)
        self._waiting_follow_ups = 0
        # ids of node-update evals superseded by an already-queued
        # node-update eval for the same job (storm coalescing, ISSUE
        # 10): parked for the leader loop to cancel in state — the
        # broker runs inside the FSM's eval callback, so it can never
        # raft-apply the cancellation itself. Ids only (the cancel path
        # re-reads state by id), drained via take_coalesced().
        self._coalesced: list[str] = []

        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._enabled = False
        self._seq = itertools.count()

        # scheduler type -> heap of (-priority, seq, eval_id)
        self._ready: dict[str, list] = {}
        self._evals: dict[str, Evaluation] = {}        # eval_id -> eval
        self._dequeue_count: dict[str, int] = {}       # eval_id -> deliveries
        # (namespace, job_id) -> blocked evals waiting on the outstanding one
        self._pending: dict[tuple[str, str], list[Evaluation]] = {}
        self._outstanding_jobs: dict[tuple[str, str], str] = {}  # -> eval_id
        self._ready_jobs: dict[tuple[str, str], str] = {}        # -> eval_id
        self._unack: dict[str, dict] = {}              # eval_id -> {token, deadline}

        # delayed evals: (wait_until, seq, eval)
        self._delay_heap: list = []
        self._timer: Optional[threading.Thread] = None
        self._shutdown = False

        self.stats = {"total_ready": 0, "total_unacked": 0,
                      "total_pending": 0, "total_waiting": 0,
                      "total_failed": 0, "total_shed": 0}

    def _notify_inflight(self) -> None:
        """Push the outstanding-eval count to the solver micro-batcher
        (its coalescing oracle). Lazy import: the broker must not drag
        jax in; a stripped build without the solver is a no-op."""
        try:
            from ..solver import microbatch
        except ImportError:
            return
        microbatch.broker_in_flight(self.stats["total_unacked"])

    # ------------------------------------------------------------- control

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            was = self._enabled
            self._enabled = enabled
            if not enabled:
                self._flush_locked()
            elif not was:
                self._shutdown = False
                self._timer = threading.Thread(
                    target=self._run_delayed_watcher, daemon=True)
                self._timer.start()
            self._cond.notify_all()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def _flush_locked(self) -> None:
        """Caller holds self._lock (the *_locked convention LOCK001
        checks; ref eval_broker.go flush, called under b.l)."""
        # every live trace this broker started ends here with the flush
        # disposition — the worker processing an outstanding eval may
        # still be mid-span on its own thread, so truncate (no span-leak
        # accounting) rather than demand a clean close (ISSUE 7)
        flushed = set(self._evals) | set(self._unack)
        for pend in self._pending.values():
            flushed.update(ev.id for ev in pend)
        flushed.update(item[2].id for item in self._delay_heap)
        for eval_id in flushed:
            trace.end_eval(eval_id, "flushed", truncate=True,
                           owner=id(self))
        self._ready.clear()
        self._ready_jobs.clear()
        self._evals.clear()
        self._pending.clear()
        self._outstanding_jobs.clear()
        self._unack.clear()
        self._dequeue_count.clear()
        self._delay_heap = []
        self._shed_entries.clear()
        self._waiting_follow_ups = 0
        self._coalesced.clear()
        self._shutdown = True
        # every stat is maintained incrementally (+=/-=) against the
        # queues just cleared — zero them ALL or the stats endpoint
        # reports a phantom backlog for the life of the process
        self.stats["total_ready"] = 0
        self.stats["total_unacked"] = 0
        self.stats["total_pending"] = 0
        self.stats["total_waiting"] = 0
        self.stats["total_failed"] = 0
        metrics.set_gauge("nomad.broker.failed_queue_depth", 0)
        self._notify_inflight()

    # ---------------------------------------------------- overload (ISSUE 8)

    def _overload_knobs(self) -> tuple[int, float]:
        """(depth_cap, eval_deadline_s) from the live scheduler config
        when wired, else the explicit attributes. Reads are two attribute
        lookups on an in-memory dataclass — cheap enough per enqueue."""
        cfg = self.config_fn() if self.config_fn is not None else None
        if cfg is None:
            return self.depth_cap, self.eval_deadline_s
        try:
            return (max(0, int(getattr(cfg, "broker_depth_cap", 0))),
                    max(0.0, float(getattr(cfg, "eval_deadline_s", 0.0))))
        except (TypeError, ValueError):
            return 0, 0.0

    def depth(self) -> int:
        """Queued backlog the depth cap bounds: ready + job-pending +
        delayed, MINUS dead letters (they ride the ready stat but await
        the reaper — counting them would let a shed storm re-trigger
        itself) and unacked (bounded by worker count, already in flight)."""
        with self._lock:
            return self._depth_locked()

    def _depth_locked(self) -> int:
        return max(0, self.stats["total_ready"] - self.stats["total_failed"]
                   + self.stats["total_pending"]
                   + self.stats["total_waiting"]
                   - self._waiting_follow_ups)

    def _delay_push_locked(self, when: float, ev: Evaluation) -> None:
        # callers are bounded: enqueue is depth-cap/shed gated, nack by
        # the delivery limit
        # nomadlint: disable=QUEUE001 — caller-bounded (above)
        heapq.heappush(self._delay_heap, (when, next(self._seq), ev))
        self.stats["total_waiting"] += 1
        if ev.triggered_by == TRIGGER_FAILED_FOLLOW_UP:
            self._waiting_follow_ups += 1

    def _shed_candidates_locked(self):
        """Live, non-exempt ready entries: (neg_priority, seq, eval_id)
        tuples. The victim is max() of these — lowest priority first,
        newest seq within a priority (deterministic by (priority, seq)).
        Deliberately O(ready) per shed: this is the over-cap emergency
        path only (bounded by the cap itself), and a mirrored max-heap
        would need exact-entry liveness tracking across dequeue/nack/
        drain to avoid double-delivery — complexity the correctness
        tests would have to re-prove. Revisit if shed-path lock hold
        time ever shows up in the bench."""
        out = []
        for qname, heap in self._ready.items():
            if qname == FAILED_QUEUE or qname in SHED_EXEMPT_TYPES:
                continue
            out.extend(
                e for e in heap
                if e[2] in self._evals and e not in self._shed_entries
                # exempt triggers are never victims: re-shedding the
                # shed channel's own retries (follow-ups) is a
                # reap<->shed cycle, and shedding lost-alloc
                # replacement work (node-update) dead-letters exactly
                # the evals that keep dead nodes' work alive
                and self._evals[e[2]].triggered_by
                not in SHED_EXEMPT_TRIGGERS)
        return out

    def _shed_locked(self, incoming: Evaluation, incoming_key) -> bool:
        """Make room for `incoming` by dead-lettering the lowest-priority
        queued eval (possibly `incoming` itself). Returns True when the
        incoming eval was the victim (caller must not enqueue it). The
        shed eval re-enters via the failed-eval backoff lifecycle: the
        reaper terminates it and emits a delayed failed-follow-up, so
        shed work retries instead of vanishing (core_sched.py)."""
        victims = self._shed_candidates_locked()
        if incoming.type not in SHED_EXEMPT_TYPES:
            victims.append(incoming_key)
        if not victims:
            # backlog is all core/system work: admit over cap — shedding
            # the cluster's own housekeeping is never the right trade
            metrics.incr("nomad.broker.shed_exempt_overflow")
            return False
        victim = max(victims)
        neg_p, seq, eval_id = victim
        self.shed_log.append((-neg_p, seq, eval_id))
        metrics.incr("nomad.broker.shed")
        self.stats["total_shed"] = self.stats.get("total_shed", 0) + 1
        if victim is incoming_key:
            ev = incoming
            self._evals[eval_id] = ev
            job_key = (ev.namespace, ev.job_id)
            if ev.job_id and job_key not in self._ready_jobs and \
                    job_key not in self._outstanding_jobs:
                # claim the job only when unclaimed: a shed incoming
                # whose job already has a ready/outstanding eval must
                # not steal that eval's dedup registration
                self._ready_jobs[job_key] = eval_id
        else:
            ev = self._evals[eval_id]
            self._shed_entries.add(victim)
            self.stats["total_ready"] -= 1
            # the eval stays in self._evals and keeps its _ready_jobs
            # claim — it is still "ready", just on the dead-letter queue
            # (exactly the nack-at-delivery-limit shape)
        # fresh seq on the dead-letter entry: the tombstone set matches
        # by tuple VALUE, so the failed-queue twin must never compare
        # equal to the invalidated original
        heapq.heappush(self._ready.setdefault(FAILED_QUEUE, []),
                       (neg_p, next(self._seq), eval_id))
        self.stats["total_ready"] += 1
        self.stats["total_failed"] += 1
        metrics.set_gauge("nomad.broker.failed_queue_depth",
                          self.stats["total_failed"])
        # the shed disposition ends the eval's trace (PR-7): the retry
        # is a NEW eval (the follow-up) with its own trace
        trace.end_eval(eval_id, "shed", owner=id(self),
                       priority=ev.priority, shed_seq=seq)
        self._cond.notify_all()
        return victim is incoming_key

    # ------------------------------------------------------------- enqueue

    def enqueue(self, eval: Evaluation) -> None:
        with self._lock:
            self._enqueue_locked(eval)

    def enqueue_all(self, evals: list[tuple[Evaluation, str]]) -> None:
        """Enqueue evals with optional ack tokens: an eval being re-enqueued
        while outstanding is requeued once its current delivery acks/nacks
        (ref eval_broker.go EnqueueAll)."""
        with self._lock:
            for ev, token in evals:
                if token and ev.id in self._unack:
                    # mark for requeue on ack
                    self._unack[ev.id]["requeue"] = ev
                else:
                    self._enqueue_locked(ev)

    def _enqueue_locked(self, ev: Evaluation) -> None:
        if not self._enabled:
            return
        if ev.id in self._evals:
            return
        if ev.triggered_by == TRIGGER_NODE_UPDATE and ev.job_id and \
                self._node_update_coalesce_locked(ev):
            return
        # the eval's trace begins at broker ENQUEUE: queue/delay/pending
        # wait is attributed as `broker.wait` when it dequeues. Idempotent
        # for live traces (delayed/pending re-enqueues keep theirs); a
        # fresh trace starts after a completed one ended (requeue-on-ack).
        trace.begin_eval(ev.id, "eval", owner=id(self), job=ev.job_id,
                         type=ev.type, trigger=ev.triggered_by,
                         priority=ev.priority)
        now = time.time()
        cap, ttl = self._overload_knobs()
        parking = bool((ev.wait_until_unix and ev.wait_until_unix > now)
                       or ev.wait_sec)
        if ttl > 0 and not ev.deadline_unix and not parking and \
                ev.type not in SHED_EXEMPT_TYPES and \
                ev.triggered_by not in DEADLINE_EXEMPT_TRIGGERS:
            # enqueue TTL (ISSUE 8): stamped on a COPY — the caller's
            # object may be the raft-replicated state eval, which this
            # leader-local deadline must not mutate. The clock starts
            # when the eval becomes RUNNABLE offered load: evals headed
            # for the delay heap (backed-off follow-ups, delayed
            # reschedules) are deliberately parked future work and get
            # their TTL at graduation — stamping them here would expire
            # every retry whose backoff exceeds the TTL, silently
            # voiding the shed/dead-letter contract. Requeues of
            # already-stamped evals (nack delay, pending release) keep
            # the ORIGINAL deadline. Core/system evals are
            # deadline-exempt like they are shed-exempt: expiring
            # housekeeping under load would drop exactly the work that
            # keeps the cluster healthy.
            ev = ev.copy()
            ev.deadline_unix = now + ttl
        if cap > 0 and self._depth_locked() >= cap and \
                ev.triggered_by not in SHED_EXEMPT_TRIGGERS:
            # exempt triggers BYPASS the cap: follow-ups are the shed/
            # dead-letter lifecycle's own retry channel (capping them
            # re-sheds what shedding just parked, a cycle by
            # construction), and node-update replacement work is
            # bounded by the coalescer (at most one per affected job)
            # so admitting it over cap cannot run away
            try:
                faults.fire("broker.shed")
                incoming_was_victim = self._shed_locked(
                    ev, (-ev.priority, next(self._seq), ev.id))
            except Exception as e:   # noqa: BLE001 — injected/shed failure
                # a failed shed (injected fault, accounting error) must
                # not lose the INCOMING eval: admit over cap, loudly —
                # availability beats a strict cap when the shedder breaks
                record_swallowed_error("broker.shed", e)
                incoming_was_victim = False
            if self.on_overflow is not None:
                # pressure reacts NOW, not at the next 1s leader tick —
                # safe under the (reentrant) broker lock: tick reads
                # depth back through it on this same thread
                try:
                    self.on_overflow()
                except Exception as e:   # noqa: BLE001 — telemetry hook
                    record_swallowed_error("broker.overflow_hook", e)
            if incoming_was_victim:
                return
        if ev.wait_until_unix and ev.wait_until_unix > now:
            self._delay_push_locked(ev.wait_until_unix, ev)
            self._cond.notify_all()
            return
        if ev.wait_sec:
            self._delay_push_locked(now + ev.wait_sec, ev)
            self._cond.notify_all()
            return
        job_key = (ev.namespace, ev.job_id)
        if ev.job_id and (job_key in self._outstanding_jobs or
                          job_key in self._ready_jobs):
            # dedup: at most one eval per job ready-or-outstanding; later
            # ones wait in pending until it acks (ref eval_broker.go:182)
            self._pending.setdefault(job_key, []).append(ev)
            self.stats["total_pending"] += 1
            return
        self._evals[ev.id] = ev
        if ev.job_id:
            self._ready_jobs[job_key] = ev.id
        heapq.heappush(self._ready.setdefault(ev.type, []),
                       (-ev.priority, next(self._seq), ev.id))
        self.stats["total_ready"] += 1
        self._cond.notify_all()

    def _node_update_coalesce_locked(self, ev: Evaluation) -> bool:
        """Storm coalescing (ISSUE 10): a node-update eval whose job
        already has a not-yet-dispatched node-update eval queued (ready
        or job-pending) is redundant — the queued one will snapshot
        state AFTER this enqueue, so its scheduler pass covers this
        failure too. Mirrors the blocked-eval dedupe shape: keep the
        earliest, supersede the rest. An OUTSTANDING (dequeued,
        mid-solve) eval does NOT coalesce — its snapshot may predate
        this failure; the normal one-per-job dedupe parks the new eval
        in pending instead, which is exactly the coverage needed.
        Returns True when the incoming eval was superseded; the
        superseded eval is parked for take_coalesced() so the leader
        loop can mark it canceled in state."""
        job_key = (ev.namespace, ev.job_id)
        queued = None
        ready_id = self._ready_jobs.get(job_key)
        if ready_id is not None:
            cand = self._evals.get(ready_id)
            # a DEAD-LETTERED node-update eval never runs a scheduler
            # pass (the reaper terminates it into a backed-off
            # follow-up), so it covers nothing — the newcomer must park
            # via the ordinary one-per-job dedupe instead of being
            # canceled against it
            if cand is not None and \
                    cand.triggered_by == TRIGGER_NODE_UPDATE and \
                    not any(eid == ready_id for _, _, eid in
                            self._ready.get(FAILED_QUEUE, ())):
                queued = cand
        if queued is None:
            for pend in self._pending.get(job_key, ()):
                if pend.triggered_by == TRIGGER_NODE_UPDATE:
                    queued = pend
                    break
        if queued is None:
            return False
        self._coalesced.append(ev.id)
        if len(self._coalesced) > 65536:
            # a drop leaks a permanently-pending state record (the
            # cancel never happens) — the bound exists only as a
            # runaway-memory backstop, so it is ids-only, far above any
            # real storm (one entry per superseded eval between two
            # ~1s leader ticks), and every trim is COUNTED
            metrics.incr("nomad.broker.node_update_coalesce_dropped",
                         len(self._coalesced) - 65536)
            del self._coalesced[:-65536]
        metrics.incr("nomad.broker.node_update_coalesced")
        return True

    def take_coalesced(self) -> list[str]:
        """Drain the superseded node-update eval ids (leader loop): the
        caller cancels them in state so they terminate instead of
        sitting pending forever."""
        with self._lock:
            out, self._coalesced = self._coalesced, []
            return out

    def restash_coalesced(self, eval_ids: list[str]) -> None:
        """Return drained ids after a FAILED cancel apply — the leader
        re-drains them next tick. Losing them on a transient raft error
        leaks the superseded evals as permanently-pending state records
        (eval GC only reaps terminal evals)."""
        with self._lock:
            self._coalesced[:0] = eval_ids
            if len(self._coalesced) > 65536:
                metrics.incr("nomad.broker.node_update_coalesce_dropped",
                             len(self._coalesced) - 65536)
                del self._coalesced[:-65536]

    # ------------------------------------------------------------- dequeue

    def dequeue(self, schedulers: list[str], timeout: Optional[float] = None
                ) -> tuple[Optional[Evaluation], str]:
        """Blocking dequeue; returns (eval, ack_token) (ref :335)."""
        deadline = time.time() + timeout if timeout is not None else None
        with self._lock:
            while True:
                if not self._enabled:
                    return None, ""
                best = self._pick_locked(schedulers)
                if best is not None:
                    self._notify_inflight()
                    trace.mark_dequeued(
                        best[0].id,
                        deliveries=self._dequeue_count.get(best[0].id, 1))
                    return best
                if deadline is not None:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        return None, ""
                    self._cond.wait(remaining)
                else:
                    self._cond.wait(1.0)

    def _pick_locked(self, schedulers: list[str]
                     ) -> Optional[tuple[Evaluation, str]]:
        best_key = None
        best_queue = None
        for sched in schedulers:
            heap = self._ready.get(sched)
            # stale entries: acked/drained evals (id gone) and shed
            # tombstones (the eval moved to the dead-letter queue but
            # keeps its id registration — match by entry VALUE)
            while heap and (heap[0][2] not in self._evals
                            or heap[0] in self._shed_entries):
                self._shed_entries.discard(heap[0])
                heapq.heappop(heap)
            if not heap:
                continue
            if best_key is None or heap[0] < best_key:
                best_key = heap[0]
                best_queue = sched
        if best_queue is None:
            return None
        _, _, eval_id = heapq.heappop(self._ready[best_queue])
        ev = self._evals.pop(eval_id)
        if best_queue == FAILED_QUEUE:
            self.stats["total_failed"] -= 1
            metrics.set_gauge("nomad.broker.failed_queue_depth",
                              self.stats["total_failed"])
        if ev.job_id and self._ready_jobs.get((ev.namespace, ev.job_id)) == eval_id:
            del self._ready_jobs[(ev.namespace, ev.job_id)]
        self.stats["total_ready"] -= 1
        token = new_id()
        self._unack[eval_id] = {
            "token": token,
            "eval": ev,
            "deadline": time.time() + self.nack_timeout,
        }
        self.stats["total_unacked"] += 1
        self._dequeue_count[eval_id] = self._dequeue_count.get(eval_id, 0) + 1
        if ev.job_id:
            self._outstanding_jobs[(ev.namespace, ev.job_id)] = eval_id
        return ev, token

    def outstanding(self, eval_id: str) -> Optional[str]:
        with self._lock:
            rec = self._unack.get(eval_id)
            return rec["token"] if rec else None

    def outstanding_reset(self, eval_id: str, token: str) -> str:
        """Reset the nack timer (heartbeat from a busy worker)."""
        with self._lock:
            rec = self._unack.get(eval_id)
            if rec is None:
                return "not outstanding"
            if rec["token"] != token:
                return "token mismatch"
            rec["deadline"] = time.time() + self.nack_timeout
            return ""

    # ------------------------------------------------------------ ack/nack

    def ack(self, eval_id: str, token: str) -> None:
        """ref :537"""
        with self._lock:
            rec = self._unack.get(eval_id)
            if rec is None or rec["token"] != token:
                raise ValueError(f"token mismatch for eval {eval_id}")
            del self._unack[eval_id]
            self.stats["total_unacked"] -= 1
            self._dequeue_count.pop(eval_id, None)
            ev = rec["eval"]
            job_key = (ev.namespace, ev.job_id)
            if self._outstanding_jobs.get(job_key) == eval_id:
                del self._outstanding_jobs[job_key]
            # release one pending eval for this job
            pending = self._pending.get(job_key)
            if pending:
                nxt = pending.pop(0)
                if not pending:
                    del self._pending[job_key]
                self.stats["total_pending"] -= 1
                self._enqueue_locked(nxt)
            requeue = rec.get("requeue")
            if requeue is not None:
                self._enqueue_locked(requeue)
            self._notify_inflight()
            self._cond.notify_all()

    def nack(self, eval_id: str, token: str) -> None:
        """Failed delivery: requeue with delay or move to failed queue
        (ref :601)."""
        with self._lock:
            rec = self._unack.get(eval_id)
            if rec is None or rec["token"] != token:
                raise ValueError(f"token mismatch for eval {eval_id}")
            del self._unack[eval_id]
            self.stats["total_unacked"] -= 1
            ev = rec["eval"]
            job_key = (ev.namespace, ev.job_id)
            if self._outstanding_jobs.get(job_key) == eval_id:
                del self._outstanding_jobs[job_key]
            count = self._dequeue_count.get(eval_id, 1)
            if count >= self.delivery_limit:
                # dead-letter: deliver once more via the failed queue
                # (the leader's reaper terminates it and emits the
                # backed-off failed-follow-up, ref leader.go:782)
                self._evals[ev.id] = ev
                if ev.job_id:
                    self._ready_jobs[job_key] = ev.id
                heapq.heappush(self._ready.setdefault(FAILED_QUEUE, []),
                               (-ev.priority, next(self._seq), ev.id))
                self.stats["total_ready"] += 1
                self.stats["total_failed"] += 1
                metrics.incr("nomad.broker.dead_letter")
                metrics.set_gauge("nomad.broker.failed_queue_depth",
                                  self.stats["total_failed"])
            else:
                delay = (self.initial_nack_delay if count == 1
                         else self.subsequent_nack_delay)
                self._delay_push_locked(time.time() + delay, ev)
            self._notify_inflight()
            self._cond.notify_all()

    # ------------------------------------------------------ dead letters

    def failed_evals(self) -> list[Evaluation]:
        """The evals currently parked on the dead-letter queue (operator
        visibility via /v1/operator/broker/failed)."""
        with self._lock:
            heap = self._ready.get(FAILED_QUEUE, [])
            return [self._evals[eid] for _, _, eid in heap
                    if eid in self._evals]

    def drain_failed(self) -> tuple[list[Evaluation], list[Evaluation]]:
        """Operator drain: atomically remove every dead-lettered eval
        AND every not-yet-dispatched failed-follow-up (delay heap or
        ready, not outstanding) from the queue. One lock acquisition
        covers both, so the leader reaper — which converts dead letters
        into delayed follow-ups every tick — cannot interleave: whatever
        form the broken eval currently takes, the drain catches it. The
        caller terminates them in state and RESTORES them via
        enqueue/restore_failed if that commit fails. Pending evals
        blocked behind a drained eval's job are released, like an ack
        would. Returns (dead_letters, follow_ups)."""
        with self._lock:
            heap = self._ready.get(FAILED_QUEUE, [])
            drained = [self._evals.pop(eid) for _, _, eid in heap
                       if eid in self._evals]
            self._ready.pop(FAILED_QUEUE, None)
            self.stats["total_ready"] -= len(drained)
            self.stats["total_failed"] -= len(drained)
            # waiting follow-ups in the delay heap
            follows = []
            keep = []
            for item in self._delay_heap:
                if item[2].triggered_by == TRIGGER_FAILED_FOLLOW_UP:
                    follows.append(item[2])
                    self.stats["total_waiting"] -= 1
                    self._waiting_follow_ups = max(
                        0, self._waiting_follow_ups - 1)
                else:
                    keep.append(item)
            if follows:
                heapq.heapify(keep)
                self._delay_heap = keep
            # ready (undelivered) follow-ups; outstanding ones are left
            # to finish — their result commits through the normal path
            for qname, qheap in self._ready.items():
                for _, _, eid in list(qheap):
                    ev = self._evals.get(eid)
                    if ev is not None and \
                            ev.triggered_by == TRIGGER_FAILED_FOLLOW_UP:
                        follows.append(self._evals.pop(eid))
                        self.stats["total_ready"] -= 1
            removed = drained + follows
            for ev in removed:
                self._dequeue_count.pop(ev.id, None)
                job_key = (ev.namespace, ev.job_id)
                if self._ready_jobs.get(job_key) == ev.id:
                    del self._ready_jobs[job_key]
                pending = self._pending.get(job_key)
                if pending:
                    nxt = pending.pop(0)
                    if not pending:
                        del self._pending[job_key]
                    self.stats["total_pending"] -= 1
                    self._enqueue_locked(nxt)
            if drained:
                metrics.incr("nomad.broker.dead_letter_drained",
                             len(drained))
            metrics.set_gauge("nomad.broker.failed_queue_depth",
                              self.stats["total_failed"])
            self._cond.notify_all()
            return drained, follows

    def restore_failed(self, evals: list[Evaluation]) -> None:
        """Put drained evals back (the drain's raft commit failed): they
        re-enter the normal queues; their preserved dequeue counts send
        repeat offenders straight back to the dead-letter path."""
        with self._lock:
            for ev in evals:
                self._enqueue_locked(ev)

    # -------------------------------------------------------- delay watcher

    def _run_delayed_watcher(self) -> None:
        """ref :758 runDelayedEvalsWatcher"""
        while True:
            with self._lock:
                if self._shutdown or not self._enabled:
                    return
                now = time.time()
                while self._delay_heap and self._delay_heap[0][0] <= now:
                    _, _, ev = heapq.heappop(self._delay_heap)
                    self.stats["total_waiting"] -= 1
                    if ev.triggered_by == TRIGGER_FAILED_FOLLOW_UP:
                        # graduating from backoff: it becomes real
                        # offered load again (counts toward the cap)
                        self._waiting_follow_ups = max(
                            0, self._waiting_follow_ups - 1)
                    ev = ev.copy()
                    ev.wait_sec = 0.0
                    ev.wait_until_unix = 0.0
                    self._enqueue_locked(ev)
                wait = 0.2
                if self._delay_heap:
                    wait = min(wait, max(0.01, self._delay_heap[0][0] - now))
                self._cond.wait(wait)

    def check_nack_timeouts(self) -> list[str]:
        """Requeue unacked evals past their deadline; returns timed-out ids.
        Called by the leader loop tick."""
        out = []
        with self._lock:
            now = time.time()
            for eval_id, rec in list(self._unack.items()):
                if rec["deadline"] <= now:
                    out.append(eval_id)
                    try:
                        self.nack(eval_id, rec["token"])
                    except ValueError:
                        pass
        return out
