"""FSM + replicated log (ref nomad/fsm.go:194 nomadFSM.Apply and
hashicorp/raft usage in nomad/server.go:1221).

The FSM applies typed log messages to the state store. The log abstraction
(`RaftLog`) assigns monotonically increasing indexes and (in the single-node
implementation) applies synchronously; a multi-node consensus backend slots
in behind the same `apply()` contract over DCN (SURVEY.md §2.7: consensus is
a control-plane-host protocol, not a TPU workload).

Snapshots (checkpoint/resume, SURVEY.md §5): pickle the state store tables +
last index; restore rebuilds indexes.
"""
from __future__ import annotations

import dataclasses
import pickle
import threading
from collections import OrderedDict
from typing import Callable, Optional

from ..state import StateStore
from ..structs import (
    Allocation, Deployment, DeploymentStatusUpdate, Evaluation, Job, Node,
    SchedulerConfiguration,
)

# message types (ref nomad/structs.go MessageType consts / fsm.go:211-307)
NODE_REGISTER = "NodeRegisterRequestType"
NODE_DEREGISTER = "NodeDeregisterRequestType"
NODE_UPDATE_STATUS = "NodeUpdateStatusRequestType"
NODE_UPDATE_DRAIN = "NodeUpdateDrainRequestType"
NODE_UPDATE_ELIGIBILITY = "NodeUpdateEligibilityRequestType"
JOB_REGISTER = "JobRegisterRequestType"
JOB_DEREGISTER = "JobDeregisterRequestType"
EVAL_UPDATE = "EvalUpdateRequestType"
EVAL_DELETE = "EvalDeleteRequestType"
ALLOC_CLIENT_UPDATE = "AllocClientUpdateRequestType"
ALLOC_UPDATE_DESIRED_TRANSITION = "AllocUpdateDesiredTransitionRequestType"
APPLY_PLAN_RESULTS = "ApplyPlanResultsRequestType"
# a coalesced commit batch: N verified plan results in ONE log entry (one
# encode, one replication round, one FSM apply) — applied strictly in list
# order so replay equals the serial one-entry-per-plan sequence
APPLY_PLAN_RESULTS_BATCH = "ApplyPlanResultsBatchRequestType"
DEPLOYMENT_STATUS_UPDATE = "DeploymentStatusUpdateRequestType"
DEPLOYMENT_PROMOTE = "DeploymentPromoteRequestType"
DEPLOYMENT_ALLOC_HEALTH = "DeploymentAllocHealthRequestType"
SCHEDULER_CONFIG = "SchedulerConfigRequestType"
PERIODIC_LAUNCH = "PeriodicLaunchRequestType"
BATCH_NODE_UPDATE_DRAIN = "BatchNodeUpdateDrainRequestType"
# one heartbeat-sweep's expired nodes flipped down in ONE log entry
# (ISSUE 10): a 10%-of-the-fleet partition costs ceil(K/rate-cap) raft
# rounds instead of K — the batch applies under one store lock hold so
# blocking readers see whole sweeps, never a half-marked storm
BATCH_NODE_UPDATE_STATUS = "BatchNodeUpdateStatusRequestType"
DEPLOYMENT_DELETE = "DeploymentDeleteRequestType"
ACL_POLICY_UPSERT = "ACLPolicyUpsertRequestType"
ACL_POLICY_DELETE = "ACLPolicyDeleteRequestType"
ACL_TOKEN_UPSERT = "ACLTokenUpsertRequestType"
ACL_TOKEN_DELETE = "ACLTokenDeleteRequestType"
ACL_TOKEN_BOOTSTRAP = "ACLTokenBootstrapRequestType"
NAMESPACE_UPSERT = "NamespaceUpsertRequestType"
NAMESPACE_DELETE = "NamespaceDeleteRequestType"
SCALING_EVENT_REGISTER = "ScalingEventRegisterRequestType"
JOB_STABILITY = "JobStabilityRequestType"
RECONCILE_SUMMARIES = "ReconcileJobSummariesRequestType"
CSI_VOLUME_REGISTER = "CSIVolumeRegisterRequestType"
CSI_VOLUME_DEREGISTER = "CSIVolumeDeregisterRequestType"
CSI_VOLUME_CLAIM = "CSIVolumeClaimRequestType"
AUTOPILOT_CONFIG = "AutopilotRequestType"
SERVICE_REGISTER = "ServiceRegistrationUpsertRequestType"
SERVICE_DEREGISTER = "ServiceRegistrationDeleteRequestType"
INTENTION_UPSERT = "ServiceIntentionUpsertRequestType"
INTENTION_DELETE = "ServiceIntentionDeleteRequestType"


@dataclasses.dataclass
class PlanApplyRequest:
    """ApplyPlanResultsRequest (ref structs.go) — what the plan applier
    commits through the log."""
    alloc_updates: list = dataclasses.field(default_factory=list)
    alloc_placements: list = dataclasses.field(default_factory=list)
    alloc_preemptions: list = dataclasses.field(default_factory=list)
    deployment: Optional[Deployment] = None
    deployment_updates: list = dataclasses.field(default_factory=list)
    eval_id: str = ""


class NomadFSM:
    """ref nomad/fsm.go:194"""

    def __init__(self, state: Optional[StateStore] = None):
        self.state = state or StateStore()
        # callbacks fired after specific message types commit (e.g. the
        # leader enqueues evals into the broker, ref fsm.go:760)
        self.on_eval_update: list[Callable[[list[Evaluation]], None]] = []
        # fired after plan results apply (ISSUE 6 warm standby): a
        # FOLLOWER feeds its passive solver state-cache twin from here,
        # so promotion finds the device tensors already current. Best-
        # effort: a callback failure must never fail the FSM apply
        self.on_plan_apply: list[Callable[[int], None]] = []
        # apply_batch deferral buffer (ISSUE 20): while a batched apply
        # window is open, eval/plan callbacks collect here and fire once
        # after the store lock drops. Only the single applier thread
        # opens windows, so a plain attribute suffices.
        self._defer: Optional[tuple[list, list]] = None

    def apply(self, index: int, msg_type: str, payload: dict) -> object:
        """ref fsm.go:194 Apply (type switch :211-307)"""
        s = self.state
        # RPC write-dedup ack (ISSUE 18): an entry stamped by
        # rpc/dedup.stamp() records (token -> index) into the replicated
        # table on EVERY server as part of the same apply — a failover
        # cannot forget the ack. `.get`, never `.pop`: this payload dict
        # is aliased by the in-memory raft log entry and WAL, and
        # stripping the stamp here would desync followers and replays.
        tok = payload.get("_dedup") if isinstance(payload, dict) else None
        if tok is not None:
            s.record_rpc_dedup(index, tok)
        if msg_type == NODE_REGISTER:
            s.upsert_node(index, payload["node"])
        elif msg_type == NODE_DEREGISTER:
            s.delete_node(index, payload["node_ids"])
        elif msg_type == NODE_UPDATE_STATUS:
            # replay determinism (ISSUE 13): applying a log entry must
            # be a pure function of the entry — a wall-clock default
            # here would re-stamp a DIFFERENT time when the entry
            # re-applies after a restart, so restored state silently
            # diverged from the state the cluster acked. Every emitter
            # stamps updated_at explicitly (PR-10 satellite).
            s.update_node_status(index, payload["node_id"], payload["status"],
                                 payload.get("updated_at", 0.0))
        elif msg_type == NODE_UPDATE_DRAIN:
            s.update_node_drain(index, payload["node_id"], payload.get("drain"),
                                payload.get("mark_eligible", False))
        elif msg_type == BATCH_NODE_UPDATE_STATUS:
            s.update_node_status_batch(index, payload["node_ids"],
                                       payload["status"],
                                       payload.get("updated_at", 0.0))
            # the batch's deduped replacement evals ride the SAME entry
            # (the JOB_REGISTER shape): status flip + evals commit
            # atomically, so neither a crash nor a leadership loss
            # between two entries can strand down nodes with no evals
            evs = payload.get("evals") or []
            if evs:
                s.upsert_evals(index, evs)
                self._notify_evals(evs)
        elif msg_type == BATCH_NODE_UPDATE_DRAIN:
            for node_id, drain in payload["updates"].items():
                s.update_node_drain(index, node_id, drain,
                                    payload.get("mark_eligible", False))
        elif msg_type == NODE_UPDATE_ELIGIBILITY:
            s.update_node_eligibility(index, payload["node_id"],
                                      payload["eligibility"],
                                      payload.get("flap_until"))
        elif msg_type == JOB_REGISTER:
            s.upsert_job(index, payload["job"])
            evs = payload.get("evals") or []
            if evs:
                s.upsert_evals(index, evs)
                self._notify_evals(evs)
        elif msg_type == JOB_DEREGISTER:
            if payload.get("purge"):
                s.delete_job(index, payload["namespace"], payload["job_id"])
            else:
                job = s.job_by_id(payload["namespace"], payload["job_id"])
                if job is not None:
                    job = job.copy()
                    job.stop = True
                    s.upsert_job(index, job)
            evs = payload.get("evals") or []
            if evs:
                s.upsert_evals(index, evs)
                self._notify_evals(evs)
        elif msg_type == EVAL_UPDATE:
            evs = payload["evals"]
            s.upsert_evals(index, evs)
            self._notify_evals(evs)
        elif msg_type == EVAL_DELETE:
            s.delete_evals(index, payload["eval_ids"],
                           payload.get("alloc_ids", []))
        elif msg_type == ALLOC_CLIENT_UPDATE:
            s.update_allocs_from_client(index, payload["allocs"])
        elif msg_type == ALLOC_UPDATE_DESIRED_TRANSITION:
            s.update_alloc_desired_transitions(
                index, payload["transitions"], payload.get("evals", []))
            self._notify_evals(payload.get("evals", []))
        elif msg_type == APPLY_PLAN_RESULTS:
            from ..obs import trace
            with trace.span("fsm.apply", index=index, plans=1):
                s.upsert_plan_results(index, payload["result"])
            self._notify_plan_apply(index)
        elif msg_type == APPLY_PLAN_RESULTS_BATCH:
            # per-plan order within the entry IS commit order; every plan
            # of the batch shares the entry's index, and the store applies
            # them under ONE lock hold so a blocking reader that observes
            # the index always sees the WHOLE entry (serial-path parity).
            # The fsm.apply span nests under the applier's shared
            # plan.commit span (same thread); a follower's replicated
            # apply has no trace context and records nothing.
            from ..obs import trace
            with trace.span("fsm.apply", index=index,
                            plans=len(payload["results"])):
                s.upsert_plan_results_batch(index, payload["results"])
            self._notify_plan_apply(index)
        elif msg_type == DEPLOYMENT_STATUS_UPDATE:
            s.update_deployment_status(index, payload["update"],
                                       payload.get("job"),
                                       payload.get("eval"))
            if payload.get("eval"):
                self._notify_evals([payload["eval"]])
        elif msg_type == DEPLOYMENT_PROMOTE:
            s.update_deployment_promotion(index, payload["deployment_id"],
                                          payload.get("groups"))
            if payload.get("eval"):
                s.upsert_evals(index, [payload["eval"]])
                self._notify_evals([payload["eval"]])
        elif msg_type == DEPLOYMENT_ALLOC_HEALTH:
            # timestamp default 0.0, not time.time(): restart replay
            # must reproduce the originally-applied state bit-for-bit
            # (the watcher always stamps from its injectable clock)
            s.update_deployment_alloc_health(
                index, payload["deployment_id"],
                payload.get("healthy", []), payload.get("unhealthy", []),
                payload.get("timestamp", 0.0))
            if payload.get("eval"):
                s.upsert_evals(index, [payload["eval"]])
                self._notify_evals([payload["eval"]])
        elif msg_type == DEPLOYMENT_DELETE:
            s.delete_deployments(index, payload["deployment_ids"])
        elif msg_type == SCHEDULER_CONFIG:
            s.set_scheduler_config(index, payload["config"])
        elif msg_type == PERIODIC_LAUNCH:
            s.upsert_periodic_launch(index, payload["namespace"],
                                     payload["job_id"], payload["launch"])
        elif msg_type == ACL_POLICY_UPSERT:
            s.upsert_acl_policies(index, payload["policies"])
        elif msg_type == ACL_POLICY_DELETE:
            s.delete_acl_policies(index, payload["names"])
        elif msg_type in (ACL_TOKEN_UPSERT, ACL_TOKEN_BOOTSTRAP):
            s.upsert_acl_tokens(index, payload["tokens"])
        elif msg_type == ACL_TOKEN_DELETE:
            s.delete_acl_tokens(index, payload["accessor_ids"])
        elif msg_type == NAMESPACE_UPSERT:
            s.upsert_namespaces(index, payload["namespaces"])
        elif msg_type == NAMESPACE_DELETE:
            s.delete_namespaces(index, payload["names"])
        elif msg_type == SCALING_EVENT_REGISTER:
            s.upsert_scaling_event(index, payload["namespace"],
                                   payload["job_id"], payload["group"],
                                   payload["event"])
        elif msg_type == RECONCILE_SUMMARIES:
            s.reconcile_job_summaries(index)
        elif msg_type == JOB_STABILITY:
            s.update_job_stability(index, payload["namespace"],
                                   payload["job_id"], payload["version"],
                                   payload["stable"])
        elif msg_type == CSI_VOLUME_REGISTER:
            for vol in payload["volumes"]:
                s.upsert_csi_volume(index, vol)
        elif msg_type == CSI_VOLUME_DEREGISTER:
            s.delete_csi_volume(index, payload["namespace"],
                                payload["volume_id"],
                                payload.get("force", False))
        elif msg_type == CSI_VOLUME_CLAIM:
            s.csi_volume_claim(index, payload["namespace"],
                               payload["volume_id"], payload["claim"])
        elif msg_type == AUTOPILOT_CONFIG:
            s.set_autopilot_config(index, payload["config"])
        elif msg_type == SERVICE_REGISTER:
            s.upsert_service_registrations(index, payload["services"])
        elif msg_type == SERVICE_DEREGISTER:
            s.delete_service_registrations(
                index, payload.get("alloc_id", ""), payload.get("keys"))
        elif msg_type == INTENTION_UPSERT:
            s.upsert_intention(index, payload["intention"])
        elif msg_type == INTENTION_DELETE:
            s.delete_intention(index, payload["namespace"],
                               payload["source"], payload["destination"])
        else:
            raise ValueError(f"unknown message type {msg_type!r}")
        return None

    def apply_batch(self, items: list, on_error=None) -> None:
        """Apply N contiguous committed entries as ONE window (ISSUE 20
        group commit): one store write-lock hold, one snapshot-memo
        displacement cycle, one event-broker publish batch, one
        blocking-query wakeup — instead of N of each. Entry order
        inside the window IS log order, so replay equals the serial
        per-entry sequence bit for bit.

        Broker/standby callbacks (`on_eval_update`, `on_plan_apply`)
        are DEFERRED and fired once per window after the store lock
        drops: firing them under the held lock would mint new
        store->broker lock edges for the whole-program lock-order lint
        to choke on, and the serial path never ran them under the lock
        either. `on_error(index, exc)` preserves the applier's
        per-entry error isolation — one malformed entry must not drop
        its batch-mates. Caller contract: ONE applier thread opens
        windows at a time (RaftNode._run_apply is strictly serial)."""
        if not items:
            return
        deferred: tuple[list, list] = ([], [])
        self._defer = deferred
        try:
            with self.state.batch_window():
                for index, msg_type, payload in items:
                    try:
                        self.apply(index, msg_type, payload)
                    except Exception as ex:   # noqa: BLE001
                        if on_error is None:
                            raise
                        on_error(index, ex)
        finally:
            self._defer = None
        evals, plan_indexes = deferred
        if evals:
            for cb in self.on_eval_update:
                cb(evals)
        for idx in plan_indexes:
            self._notify_plan_apply(idx)

    def _notify_evals(self, evals: list[Evaluation]) -> None:
        if self._defer is not None:
            self._defer[0].extend(evals)
            return
        for cb in self.on_eval_update:
            cb(evals)

    def _notify_plan_apply(self, index: int) -> None:
        if self._defer is not None:
            self._defer[1].append(index)
            return
        for cb in self.on_plan_apply:
            try:
                cb(index)
            except Exception as e:      # noqa: BLE001 — standby feed is
                from ..metrics import record_swallowed_error   # telemetry
                record_swallowed_error("fsm.on_plan_apply", e)

    # ------------------------------------------------------ snapshot/restore

    def snapshot_bytes(self) -> bytes:
        """ref fsm.go Snapshot/Persist"""
        s = self.state
        with s._lock:
            blob = {
                "index": s._index,
                "table_index": dict(s._table_index),
                "nodes": s.nodes, "jobs": s.jobs,
                "job_versions": s.job_versions,
                "job_summaries": s.job_summaries,
                "evals": s.evals, "allocs": s.allocs,
                "deployments": s.deployments,
                "periodic_launches": s.periodic_launches,
                "scheduler_config": s.scheduler_config,
                "namespaces": s.namespaces,
                "acl_policies": s.acl_policies,
                "acl_tokens": s.acl_tokens,
                "scaling_policies": s.scaling_policies,
                "scaling_policy_by_target": s._scaling_policy_by_target,
                "scaling_events": s.scaling_events,
                "csi_volumes": s.csi_volumes,
                "csi_plugins": s.csi_plugins,
                "autopilot_config": s.autopilot_config,
                "services": s.services,
                "intentions": s.intentions,
                "rpc_dedup": s.rpc_dedup,
            }
            return pickle.dumps(blob)

    def restore_bytes(self, data: bytes) -> None:
        """ref fsm.go Restore"""
        blob = pickle.loads(data)
        s = self.state
        with s._lock:
            s._index = blob["index"]
            s._table_index = dict(blob["table_index"])
            s.nodes = dict(blob["nodes"])
            s.jobs = dict(blob["jobs"])
            s.job_versions = dict(blob["job_versions"])
            s.job_summaries = dict(blob["job_summaries"])
            s.evals = dict(blob["evals"])
            s.allocs = dict(blob["allocs"])
            s.deployments = dict(blob["deployments"])
            s.periodic_launches = dict(blob["periodic_launches"])
            s.scheduler_config = blob["scheduler_config"]
            s.namespaces = dict(blob["namespaces"])
            s.acl_policies = dict(blob.get("acl_policies", {}))
            s.acl_tokens = dict(blob.get("acl_tokens", {}))
            s.scaling_policies = dict(blob.get("scaling_policies", {}))
            s._scaling_policy_by_target = dict(
                blob.get("scaling_policy_by_target", {}))
            s.scaling_events = dict(blob.get("scaling_events", {}))
            s.csi_volumes = dict(blob.get("csi_volumes", {}))
            s.csi_plugins = dict(blob.get("csi_plugins", {}))
            s.autopilot_config = dict(
                blob.get("autopilot_config", s.autopilot_config))
            s.services = dict(blob.get("services", {}))
            s.intentions = dict(blob.get("intentions", {}))
            # .get: snapshots from before ISSUE 18 carry no dedup table
            s.rpc_dedup = OrderedDict(blob.get("rpc_dedup", {}))
            s._acl_token_by_secret = {
                t.secret_id: t.accessor_id for t in s.acl_tokens.values()}
            # rebuild secondary indexes
            s._allocs_by_node.clear()
            s._allocs_by_job.clear()
            s._allocs_by_eval.clear()
            s._evals_by_job.clear()
            for alloc in s.allocs.values():
                s._index_alloc(alloc)
            for ev in s.evals.values():
                s._index_eval(ev)
            s.usage.rebuild(s.nodes.values(), s.allocs.values())
            s._snap_memo = None     # restore bypasses _bump: drop the
            s._cond.notify_all()    # shared snapshot memo explicitly


class RaftLog:
    """Single-node replicated log: serial apply with index assignment.

    The contract multi-node consensus must keep: apply() returns only after
    the message is durably committed and visible in the FSM's state store at
    the returned index."""

    def __init__(self, fsm: NomadFSM):
        self.fsm = fsm
        self._lock = threading.Lock()
        self._index = fsm.state.latest_index()
        # single-node leadership epoch: never changes in normal operation
        # (a single-node log cannot be deposed), but the fence machinery
        # is exercised end-to-end — restore() bumps it, matching the one
        # event that invalidates prepared writes here (docs/FAILOVER.md)
        self._fence = 0

    def fence_token(self) -> Optional[int]:
        """Single-node twin of RaftNode.fence_token (ISSUE 6)."""
        with self._lock:
            return self._fence

    def quorum_fresh(self, window: Optional[float] = None) -> bool:
        """Single-node twin of RaftNode.quorum_fresh (ISSUE 18): a
        single-node log cannot be deposed, so its local state is always
        current and fast-path acks from it are always safe."""
        return True

    def apply(self, msg_type: str, payload: dict,
              timeout: float = 30.0, fence: Optional[int] = None) -> int:
        # `timeout` mirrors the multi-server RaftNode.apply budget (the
        # coalescing applier threads its per-BATCH remaining budget
        # through); the single-node log commits synchronously, so there
        # is nothing to wait on here.
        from .. import faults
        faults.fire("raft.apply")
        # idempotency stamp (ISSUE 18): if this thread is dispatching a
        # dedup-tokened RPC, the token rides THIS entry's payload so the
        # ack commits atomically with the write (rpc/dedup.py)
        from ..rpc import dedup as rpc_dedup
        payload = rpc_dedup.stamp(payload)
        # the lock spans index assignment AND application so state-store
        # mutations happen in strict log order (replay determinism)
        with self._lock:
            if fence is not None and fence != self._fence:
                from ..rpc.codec import FencedWriteError
                from ..metrics import metrics
                from ..obs import trace
                metrics.incr("nomad.raft.fence_rejected")
                trace.annotate(fence_rejected=True, fence_expected=fence,
                               fence_current=self._fence)
                raise FencedWriteError(self._fence, fence)
            self._index += 1
            index = self._index
            # attribute the assigned log index onto whatever span is in
            # flight (the applier's plan.commit span) — ISSUE 7
            from ..obs import trace
            trace.annotate(raft_index=index)
            self.fsm.apply(index, msg_type, payload)
            return index

    def barrier(self) -> int:
        """Latest committed index (leader barrier analog)."""
        with self._lock:
            return self._index

    def snapshot(self) -> bytes:
        return self.fsm.snapshot_bytes()

    def restore(self, data: bytes) -> None:
        self.fsm.restore_bytes(data)
        with self._lock:
            self._index = self.fsm.state.latest_index()
            # a restore replaces the world under any prepared write —
            # the single-node analog of losing leadership mid-batch
            self._fence += 1
