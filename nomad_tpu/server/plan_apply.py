"""Serial plan applier + plan queue (ref nomad/plan_apply.go:71 planApply,
nomad/plan_queue.go) with cross-eval commit coalescing (ISSUE 5).

The optimistic-concurrency heart of the design (kept untouched per the
north star): workers submit plans computed against possibly-stale snapshots;
the leader-serial applier re-checks every touched node against latest state
(ref :638 evaluateNodePlan) and commits only the slices that still fit.
Workers see rejections in the PlanResult and retry with a fresher snapshot.

Commit coalescing (Tesserae's observation that placement pipelines are
throughput-bound on the commit path): the applier drains up to
`plan_commit_batch_max` verified pending plans per cycle and lands them as
ONE raft entry / FSM batch apply — one payload encode, one shared
`snapshot_min_index` fetch, one `state_cache.note_commit` replay window —
while preserving the serial path's observable semantics:

  * per-plan commit ORDERING: plans are drained in queue (priority, FIFO)
    order and evaluated in that order against the shared snapshot PLUS the
    accumulated effects of every earlier plan in the batch (`_BatchCtx`),
    exactly the state each plan would have seen had the previous plans
    committed one at a time;
  * per-plan FAILURE isolation at evaluation: a plan whose evaluation
    raises (or whose nodes are all rejected) fails alone — it contributes
    nothing to the batch entry and later plans evaluate as if it never
    queued. Only a failure of the single batch raft commit fails every
    plan in that entry (the entry is atomic by construction);
  * the 30s raft-apply budget covers the WHOLE batch, not 30s per
    message: a timeout surfaces `nomad.plan.commit_timeout` per plan
    instead of letting one slow entry starve the queue.

Plan evaluation itself is tensorized (CvxCluster: keep allocation
*evaluation* in batched tensor form): the touched node rows of every plan
in the batch are gathered once — straight from the solver's device-resident
TensorCache when it is current (state_cache.gather: same bits as the view
by construction), else from the snapshot's dense usage view — and all
dense-eligible (plan, node) pairs are verdicted in one vectorized AllocsFit
pass. Rows where plans interact (overlapping placements with stops /
negative deltas / exact-path neighbors) fall back to an ordered per-plan
pass with the accumulated in-batch deltas, and nodes with sequential
resources keep the scalar `_evaluate_node_plan` oracle — which is also the
whole-batch path under NOMAD_PLAN_TENSOR_EVAL=0 (the differential tests'
oracle switch). Knobs + semantics: docs/COMMIT_COALESCING.md.
"""
from __future__ import annotations

import heapq
import itertools
import os
import threading
import time
from typing import Optional

import numpy as np

from .. import faults
from ..metrics import metrics
from ..obs import trace
from ..rpc.codec import NotLeaderError
from ..state import StateStore
from ..structs import (
    Allocation, NetworkIndex, Plan, PlanResult, allocs_fit,
)
from .fsm import (
    APPLY_PLAN_RESULTS, APPLY_PLAN_RESULTS_BATCH, PlanApplyRequest, RaftLog,
)

_FIT_EPS = 1e-3

# the distinct disposition a pending plan gets when the applier loses
# leadership under it (step-down, fence rejection, revoke): workers see
# it instead of a generic failure, and `nomad.plan.leadership_lost`
# counts every occurrence (ISSUE 6 satellite)
LEADERSHIP_LOST = "leadership lost"

# _fence_token sentinel: "fencing is supported and we are NOT leader"
# (None means "no fencing on this log at all")
_NOT_LEADER = object()


class PlanExpiredError(RuntimeError):
    """The submitting eval's enqueue deadline lapsed before this plan
    reached the applier (ISSUE 8): the plan is rejected BEFORE the raft
    round — the caller already gave up, so committing it would spend a
    consensus round-trip (and follower applies) on anti-goodput. The
    worker sees the distinct `expired` disposition; an expired plan can
    never reach a raft entry by construction."""

    def __init__(self, plan: Plan, now: float):
        super().__init__(
            f"plan for eval {plan.eval_id[:8]} expired "
            f"{now - plan.deadline_unix:.2f}s past its deadline")


class LeadershipLostPlanError(RuntimeError):
    """A plan (or whole drained batch) could not commit because this
    server stopped being the leader. NotLeaderError/FencedWriteError
    from the log, or the revoke path failing pendings, all collapse to
    this one worker-visible disposition."""

    def __init__(self, detail: str = ""):
        super().__init__(LEADERSHIP_LOST + (f": {detail}" if detail else ""))


class _PendingPlan:
    __slots__ = ("plan", "event", "result", "error", "ctx", "t0")

    def __init__(self, plan: Plan):
        self.plan = plan
        self.event = threading.Event()
        self.result: Optional[PlanResult] = None
        self.error: Optional[str] = None
        # trace context of the submitting eval + enqueue time: the
        # applier attributes `plan.queue_wait` from these at drain
        self.ctx = trace.eval_ctx(plan.eval_id) or trace.current()
        self.t0 = time.perf_counter()

    def respond(self, result, error) -> None:
        self.result = result
        self.error = error
        self.event.set()

    def wait(self, timeout: Optional[float] = None
             ) -> tuple[Optional[PlanResult], Optional[str]]:
        self.event.wait(timeout)
        return self.result, self.error


class PlanQueue:
    """Priority FIFO of pending plans (ref nomad/plan_queue.go)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._heap: list = []
        self._seq = itertools.count()
        self._enabled = False

    def set_enabled(self, enabled: bool,
                    reason: str = "plan queue disabled") -> int:
        """Returns the number of pendings failed by a disable (0 when
        enabling) — the caller's metric source, exact under the lock."""
        failed = 0
        with self._lock:
            self._enabled = enabled
            if not enabled:
                for _, _, pending in self._heap:
                    pending.respond(None, reason)
                    failed += 1
                self._heap = []
            self._cond.notify_all()
        return failed

    def drain_stale(self, reason: str) -> int:
        """Fail every queued pending WITHOUT toggling enablement — the
        new leader's recovery barrier empties anything that survived the
        previous leadership before scheduling resumes (ISSUE 6)."""
        with self._lock:
            stale = [pending for _, _, pending in self._heap]
            self._heap = []
            for pending in stale:
                pending.respond(None, reason)
            return len(stale)

    def enqueue(self, plan: Plan) -> _PendingPlan:
        pending = _PendingPlan(plan)
        with self._lock:
            if not self._enabled:
                pending.respond(None, "plan queue disabled")
                return pending
            heapq.heappush(self._heap,
                           (-plan.priority, next(self._seq), pending))
            self._cond.notify_all()
        return pending

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)

    def dequeue(self, timeout: float = 1.0) -> Optional[_PendingPlan]:
        with self._lock:
            if not self._heap:
                self._cond.wait(timeout)
            if not self._heap:
                return None
            _, _, pending = heapq.heappop(self._heap)
            return pending

    def drain(self, max_n: int, timeout: float = 1.0,
              linger_s: float = 0.0,
              expected: int = 0) -> list[_PendingPlan]:
        """Pop up to `max_n` pendings in (priority, FIFO) order — the
        coalescing batch. Blocks for `timeout` only when empty. A lone
        plan with nothing behind it commits immediately; the short
        `linger_s` window only engages while MORE evals than the drained
        count are known to be in flight (`expected`, the micro-batcher's
        concurrency signal) — the commit-path twin of the eval-stream
        coalescing window, bounded at a few ms so it can never starve a
        quiet queue."""
        with self._lock:
            if not self._heap:
                self._cond.wait(timeout)
            if not self._heap:
                return []
            out: list[_PendingPlan] = []

            def _pop_ready() -> None:
                while self._heap and len(out) < max_n:
                    out.append(heapq.heappop(self._heap)[2])

            _pop_ready()
            if linger_s > 0 and expected > len(out):
                deadline = time.monotonic() + linger_s
                while len(out) < min(max_n, expected) and self._enabled:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(min(remaining, 0.001))
                    _pop_ready()
            # queue_depth = everything that was waiting when the applier
            # came around (pressure); queue_residual = what the drain
            # left behind — nonzero residual means plan_commit_batch_max
            # is saturating, which healthy coalescing alone never shows
            depth = len(out) + len(self._heap)
            metrics.set_gauge("nomad.plan.queue_depth", depth)
            metrics.add_sample("nomad.plan.queue_depth", depth)
            metrics.set_gauge("nomad.plan.queue_residual", len(self._heap))
            metrics.add_sample("nomad.plan.queue_residual",
                               len(self._heap))
            return out


class _BatchCtx:
    """The committed effects of earlier plans in a coalescing batch,
    overlaid on the shared snapshot: row-wise usage deltas for the dense
    check plus object-level placements/removals for the exact oracle.
    A plan evaluated with this ctx sees exactly the state it would have
    seen on the serial path after those plans committed one at a time."""

    __slots__ = ("used_delta", "placed_by_node", "placed_ids",
                 "removed_ids")

    def __init__(self):
        # row -> accumulated XR delta as a plain python list: the absorb
        # loop runs per ALLOC (50k for a headline plan), so it must stay
        # scalar-python-add cheap — consumers lift to numpy per ROW once
        self.used_delta: dict[int, list] = {}
        self.placed_by_node: dict[str, list] = {}
        self.placed_ids: dict[str, Allocation] = {}
        self.removed_ids: set[str] = set()

    def empty(self) -> bool:
        return not (self.used_delta or self.placed_by_node
                    or self.removed_ids)

    def _add(self, row: int, delta, sign: float) -> None:
        acc = self.used_delta.get(row)
        if acc is None:
            acc = self.used_delta[row] = [0.0] * len(delta)
        for i, x in enumerate(delta):
            acc[i] += x * sign

    def live_twin(self, snap, alloc_id: str):
        """The live alloc this batch currently knows under `alloc_id` —
        an in-batch placement wins over the snapshot; a removed id is
        dead."""
        twin = self.placed_ids.get(alloc_id)
        if twin is not None:
            return twin
        if alloc_id in self.removed_ids:
            return None
        a = snap.alloc_by_id(alloc_id)
        if a is not None and not a.terminal_status():
            return a
        return None

    def absorb(self, snap, view, plan: Plan, result: PlanResult) -> None:
        """Fold one plan's COMMITTED slices in, mirroring what
        upsert_plan_results does to the usage matrices."""
        from ..state.usage_index import alloc_usage_tuple

        def retire(a) -> None:
            src = self.live_twin(snap, a.id)
            if src is None:
                return
            if a.id in self.placed_ids:
                del self.placed_ids[a.id]
                bucket = self.placed_by_node.get(src.node_id)
                if bucket:
                    self.placed_by_node[src.node_id] = \
                        [x for x in bucket if x.id != a.id]
            self.removed_ids.add(a.id)
            if view is not None:
                r = view.row.get(src.node_id)
                if r is not None:
                    self._add(r, alloc_usage_tuple(src), -1.0)

        for allocs in result.node_update.values():
            for a in allocs:
                retire(a)
        for allocs in result.node_preemptions.values():
            for a in allocs:
                retire(a)
        for node_id, allocs in result.node_allocation.items():
            r = view.row.get(node_id) if view is not None else None
            for a in allocs:
                prev = self.live_twin(snap, a.id)
                if prev is not None:
                    # in-place update: the old twin's usage retires with
                    # the replacement (upsert_plan_results semantics).
                    # retire() may REBIND placed_by_node[node_id], so the
                    # bucket must be fetched after it, per alloc
                    retire(prev)
                self.removed_ids.discard(a.id)
                self.placed_ids[a.id] = a
                self.placed_by_node.setdefault(node_id, []).append(a)
                if r is not None:
                    self._add(r, alloc_usage_tuple(a), +1.0)


class _PlanShape:
    """Phase-1 product for one plan of a batch: dense-eligible (node, row,
    ask) triples, exact-path node ids, and pre-resolved verdicts."""

    __slots__ = ("plan", "error", "dense_nodes", "dense_rows", "dense_asks",
                 "exact_nodes", "verdicts")

    def __init__(self, plan: Plan):
        self.plan = plan
        self.error: Optional[BaseException] = None
        self.dense_nodes: list[str] = []
        self.dense_rows: list[int] = []
        self.dense_asks: list[tuple] = []
        self.exact_nodes: list[str] = []
        self.verdicts: dict[str, bool] = {}


def _tensor_eval_enabled() -> bool:
    return os.environ.get("NOMAD_PLAN_TENSOR_EVAL", "") != "0"


class Planner:
    """The serial applier thread (ref plan_apply.go planApply:71), now
    draining coalesced batches per cycle."""

    def __init__(self, raft: RaftLog, state: StateStore):
        self.raft = raft
        self.state = state
        self.queue = PlanQueue()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # the batch the applier thread has drained but not yet responded
        # to — stop() must fail it if the thread dies/outlives the join,
        # or a pipelined worker blocks on wait() forever (ISSUE 3)
        self._inflight: list[_PendingPlan] = []

    # -------------------------------------------------------------- knobs

    def _coalesce_max(self) -> int:
        """Batch ceiling from the hot-reloadable scheduler config;
        NOMAD_PLAN_COALESCE=0 forces the serial one-plan path."""
        if os.environ.get("NOMAD_PLAN_COALESCE", "") == "0":
            return 1
        cfg = getattr(self.state, "scheduler_config", None)
        try:
            return max(1, int(getattr(cfg, "plan_commit_batch_max", 32)))
        except (TypeError, ValueError):
            return 32

    def _commit_budget(self) -> float:
        cfg = getattr(self.state, "scheduler_config", None)
        try:
            return max(0.1, float(getattr(cfg, "plan_commit_timeout_s",
                                          30.0)))
        except (TypeError, ValueError):
            return 30.0

    def _commit_window_s(self) -> float:
        cfg = getattr(self.state, "scheduler_config", None)
        try:
            return max(0.0, float(getattr(cfg, "plan_commit_window_ms",
                                          5.0))) / 1000.0
        except (TypeError, ValueError):
            return 0.005

    @staticmethod
    def _expected_in_flight() -> int:
        """The eval-stream's in-flight signal (placer + eval broker feed
        the micro-batcher): how many evals might still submit a plan.
        Gates the drain linger so an idle cluster's lone plan never
        waits; a stripped solver-less build just reports 0."""
        try:
            from ..solver import microbatch
            return microbatch.concurrency()
        except Exception:   # noqa: BLE001 — optional signal
            return 0

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        self.queue.set_enabled(True)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="plan-applier")
        self._thread.start()

    def stop(self, timeout: float = 5.0,
             reason: str = "planner stopped") -> None:
        """`reason` becomes every failed pending's disposition. The
        revoke-leadership path passes LEADERSHIP_LOST so workers (and
        `nomad.plan.leadership_lost`) can tell a step-down from a crash
        (ISSUE 6 satellite)."""
        lost = reason.startswith(LEADERSHIP_LOST)
        self._stop.set()
        n_queued = self.queue.set_enabled(False, reason=reason)
        if self._thread:
            try:
                self._thread.join(timeout=timeout)
            except RuntimeError:
                # start() raced us between Thread() and .start() (a
                # shutdown landing mid-establish): the daemon thread
                # sees _stop set on its first drain and exits
                pass
        # a batch mid-apply when the join gave up (or the thread died)
        # must still resolve — waiters see an error, not a hang. respond
        # after a late applier respond is a harmless overwrite: every
        # waiter already woke on the first event.set(). These are NOT
        # counted toward nomad.plan.leadership_lost: the applier's own
        # commit-error path owns that count for drained plans, and a
        # late-resolving applier would double-count them here.
        for pending in self._inflight:
            if not pending.event.is_set():
                pending.respond(None, reason)
        if lost and n_queued:
            metrics.incr("nomad.plan.leadership_lost", n_queued)

    def _run(self) -> None:
        while not self._stop.is_set():
            max_n = self._coalesce_max()
            batch = self.queue.drain(
                max_n, timeout=0.5,
                linger_s=self._commit_window_s() if max_n > 1 else 0.0,
                expected=self._expected_in_flight() if max_n > 1 else 0)
            if not batch:
                continue
            self._inflight = batch
            t_drain = time.perf_counter()
            for pending in batch:
                trace.record_span("plan.queue_wait", pending.ctx,
                                  pending.t0, drained=len(batch))
            try:
                # the batch's fence: captured ONCE at drain, checked
                # atomically at the raft append — a step-down anywhere in
                # the evaluate window rejects the whole entry instead of
                # racing the new leader's commits (docs/FAILOVER.md)
                fence = self._fence_token()
                if fence is _NOT_LEADER:
                    # measured from DRAIN like the commit path's t_batch —
                    # from pending.t0 it would re-count the queue wait the
                    # span above already attributed
                    for pending in batch:
                        trace.record_span("plan.commit_wait", pending.ctx,
                                          t_drain,
                                          status="leadership_lost")
                        pending.respond(None, LEADERSHIP_LOST)
                    metrics.incr("nomad.plan.leadership_lost", len(batch))
                    continue
                outcomes = self.apply_plan_batch([p.plan for p in batch],
                                                 fence=fence)
                for pending, (result, err) in zip(batch, outcomes):
                    pending.respond(result,
                                    str(err) if err is not None else None)
            except Exception as e:   # noqa: BLE001 - report to workers
                for pending in batch:
                    if not pending.event.is_set():
                        pending.respond(None, str(e))
            finally:
                self._inflight = []

    def _fence_token(self):
        """The raft fence for one drained batch: None on logs without
        fencing (plain test fakes), the sentinel when this server is not
        currently the leader (drain raced a revoke)."""
        fence_fn = getattr(self.raft, "fence_token", None)
        if fence_fn is None:
            return None
        fence = fence_fn()
        return _NOT_LEADER if fence is None else fence

    # ------------------------------------------------------------ evaluate

    def apply_plan(self, plan: Plan) -> PlanResult:
        """Evaluate one plan against latest state, then commit via the log
        (ref :204 applyPlan / :400 evaluatePlan) — a coalescing batch of
        one, byte-compatible with the pre-coalescing serial path."""
        result, err = self.apply_plan_batch([plan])[0]
        if err is not None:
            raise err
        return result

    def apply_plan_batch(self, plans: list[Plan], fence=None
                         ) -> list[tuple[Optional[PlanResult],
                                         Optional[BaseException]]]:
        """Evaluate + commit a drained batch. Returns (result, error)
        aligned with `plans`; raises only on batch-wide pre-evaluation
        failures (the shared snapshot fetch). `fence` (the drain-time
        fence_token) makes the raft commit atomic with the leadership
        check — a deposed applier's batch is rejected whole, reported as
        LEADERSHIP_LOST per plan, and never lands after the new leader's
        commits."""
        deadline = time.monotonic() + self._commit_budget()
        t_batch = time.perf_counter()
        # per-plan trace contexts: drained plans resolve via eval id
        # (their worker is on another thread); the inline apply_plan
        # path (a batch of one on the caller's thread) via current()
        ctxs = [trace.eval_ctx(p.eval_id) or trace.current()
                for p in plans]
        # ONE SnapshotMinIndex fetch shared by every plan of the batch
        # (each plan used to snapshot independently); the store memoizes
        # the snapshot per write-generation, so concurrent worker lanes
        # share the same fetch too (state/store.py).
        snap_index = max((p.snapshot_index for p in plans), default=0)
        snap = self.state.snapshot_min_index(snap_index, timeout=5.0)

        t0 = time.perf_counter()
        evaluated = self._evaluate_batch(snap, plans)
        # ref plan_apply.go:185 `nomad.plan.evaluate` (whole-batch sample)
        metrics.add_sample("nomad.plan.evaluate", time.perf_counter() - t0)

        # ------------------------------------------------------- commit
        reqs: list[PlanApplyRequest] = []
        committed_results: list[PlanResult] = []
        noop_results: list[PlanResult] = []
        commit_ctxs = []                # trace ctxs of committing plans
        for (plan, result, err), pctx in zip(evaluated, ctxs):
            if err is not None or result is None:
                continue
            if result.is_no_op() and not result.node_update:
                noop_results.append(result)
                continue
            if pctx is not None:
                commit_ctxs.append(pctx)
            reqs.append(PlanApplyRequest(
                alloc_updates=[a for allocs in result.node_update.values()
                               for a in allocs],
                alloc_placements=[a for allocs
                                  in result.node_allocation.values()
                                  for a in allocs],
                alloc_preemptions=[a for allocs
                                   in result.node_preemptions.values()
                                   for a in allocs],
                deployment=result.deployment,
                deployment_updates=result.deployment_updates,
                eval_id=plan.eval_id,
            ))
            committed_results.append(result)

        commit_err: Optional[BaseException] = None
        commit_ctx = None
        if reqs:
            # ref plan_apply.go:204 `nomad.plan.apply` (raft commit + FSM);
            # the budget spans the WHOLE batch — one slow entry may not
            # hold the queue for 30s per message (ISSUE 5 satellite).
            # ONE shared raft-apply span for the coalesced entry, linked
            # to every committing plan's eval span — the commit-path
            # fan-in twin of the micro-batch dispatch span (ISSUE 7).
            # Two amortization layers compose here, by design: this
            # coalescer folds queued PLANS into one log entry, and the
            # raft group-commit window (ISSUE 20, docs/DURABILITY.md)
            # then folds that entry with whatever OTHER writers —
            # heartbeat sweeps, client alloc updates, dedup records —
            # enqueued during the previous window's fsync. Neither
            # subsumes the other: coalescing cuts entries per fsync,
            # group commit cuts fsyncs per entry.
            remaining = deadline - time.monotonic()
            commit_sp = trace.start_span(
                "plan.commit",
                parent=commit_ctxs[0] if commit_ctxs else None,
                links=commit_ctxs, plans=len(reqs),
                coalesced=len(reqs) > 1)
            commit_ctx = commit_sp.ctx()
            try:
                if remaining <= 0:
                    raise TimeoutError(
                        "plan commit budget exhausted before raft apply")
                with metrics.measure("nomad.plan.apply"), \
                        trace.use(commit_sp):
                    if len(reqs) == 1:
                        index = self.raft.apply(
                            APPLY_PLAN_RESULTS, {"result": reqs[0]},
                            timeout=remaining, fence=fence)
                    else:
                        index = self.raft.apply(
                            APPLY_PLAN_RESULTS_BATCH, {"results": reqs},
                            timeout=remaining, fence=fence)
                        metrics.incr("nomad.plan.coalesced_commits")
                        metrics.incr("nomad.plan.coalesced_plans",
                                     len(reqs))
                metrics.add_sample("nomad.plan.commit_batch_size",
                                   len(reqs))
                commit_sp.end("ok")
            except TimeoutError as e:
                metrics.incr("nomad.plan.commit_timeout", len(reqs))
                commit_sp.end("timeout", error=repr(e)[:200])  # nomadlint: disable=RPC001 — closes the trace span with the failure verdict, not a re-attempt
                commit_err = e
            except NotLeaderError as e:
                # FencedWriteError (entry never appended) and
                # LeadershipLostError (appended, outcome unknown) both
                # surface as the distinct leadership-lost disposition:
                # either way THIS applier must not claim the commit
                metrics.incr("nomad.plan.leadership_lost", len(reqs))
                commit_sp.end("leadership_lost", error=repr(e)[:200])
                commit_err = LeadershipLostPlanError(str(e))
            except Exception as e:   # noqa: BLE001 — per-plan surfaced
                commit_sp.end("error", error=repr(e)[:200])
                commit_err = e
            if commit_err is None:
                for result in committed_results:
                    result.alloc_index = index
                # feed the committed batch's usage deltas to the solver's
                # device-resident tensor cache HERE, on the leader-serial
                # applier thread — ONE replay window covering every plan
                # of the batch (docs/DEVICE_STATE_CACHE.md). The plans ARE
                # committed at this point — no cache-feed failure may
                # surface as a failed apply; lazy import keeps a stripped
                # solver-less build booting.
                try:
                    from ..solver import state_cache
                    state_cache.note_commit(self.state)
                except Exception as e:   # noqa: BLE001 — telemetry feed
                    from ..metrics import record_swallowed_error
                    record_swallowed_error("plan_apply.state_cache_feed", e)
        for result in noop_results:
            result.alloc_index = self.raft.barrier()

        committed_ids = {id(r) for r in committed_results}
        noop_ids = {id(r) for r in noop_results}
        out = []
        for (plan, result, err), pctx in zip(evaluated, ctxs):
            if err is not None:
                out.append((None, err))
                status = "expired" if isinstance(err, PlanExpiredError) \
                    else "error"
                attrs = {"error": repr(err)[:200]}
            elif commit_err is not None and id(result) in committed_ids:
                out.append((None, commit_err))
                status = "leadership_lost" if isinstance(
                    commit_err, LeadershipLostPlanError) else \
                    "timeout" if isinstance(commit_err, TimeoutError) \
                    else "error"
                attrs = {"error": repr(commit_err)[:200]}
            else:
                out.append((result, None))
                status = "ok"
                attrs = {"noop": True} if id(result) in noop_ids else \
                    {"index": getattr(result, "alloc_index", 0),
                     "rejected": len(result.rejected_nodes)}
            # per-plan commit attribution in the EVAL's own trace,
            # linked to the shared raft-apply span it rode (fan-in),
            # plus the disposition-labeled commit-wait histogram
            trace.record_span(
                "plan.commit_wait", pctx, t_batch,
                links=(commit_ctx,)
                if commit_ctx is not None and id(result) in committed_ids
                else (), status=status, batch=len(plans), **attrs)
            metrics.observe("nomad.plan.commit_wait_seconds",
                            time.perf_counter() - t_batch,
                            labels={"disposition": status})
        return out

    # --------------------------------------------------- batch evaluation

    def _evaluate_batch(self, snap, plans: list[Plan]):
        """-> [(plan, result|None, error|None)] in plan order. One
        vectorized feasibility pass over every dense-eligible (plan, node)
        pair whose row is free of cross-plan interaction; interacting rows
        and sequential-resource nodes resolve in an ordered per-plan pass
        over the same gathered tensors."""
        view = getattr(snap, "usage", None)
        ctx = _BatchCtx()
        tensor = _tensor_eval_enabled()

        # phase 1: per-plan gather — fire the plan's fault site BEFORE
        # touching any shared state for it (a failed apply must not move
        # the tensor cache), then classify nodes dense vs exact and build
        # the dense ask rows against the shared snapshot. Plans whose
        # referenced alloc ids overlap an earlier plan's (impossible for
        # broker-serialized evals; pipelined chunks place disjoint fresh
        # allocs) drop to the exact ordered pass wholesale.
        # fused solver verdict (ISSUE 15): trusted ONLY for a batch of
        # one — the monotone fast path has no view of sibling plans'
        # asks on a shared row, and the batch machinery's prefix-order
        # verdicts must stay authoritative whenever plans can interact.
        # The stamp binds iff it describes exactly the usage bits this
        # evaluation reads (same uid/epoch/version).
        verdict_rows = None
        if tensor and view is not None and len(plans) == 1:
            sv = getattr(plans[0], "solver_verdict", None)
            if sv and sv.get("uid") == getattr(view, "uid", 0) and \
                    sv.get("epoch") == getattr(view, "epoch", -1) and \
                    sv.get("version") == getattr(view, "version", -2):
                verdict_rows = sv.get("rows") or None

        shapes: list[_PlanShape] = []
        seen_refs: set[str] = set()
        for plan in plans:
            shape = _PlanShape(plan)
            shapes.append(shape)
            try:
                # deadline gate FIRST (ISSUE 8): a past-deadline plan
                # fails alone — no shared-state work, no raft entry —
                # with the distinct `expired` disposition
                if plan.deadline_unix and \
                        time.time() >= plan.deadline_unix:
                    metrics.incr("nomad.plan.expired")
                    raise PlanExpiredError(plan, time.time())
                faults.fire("planner.apply")
                refs = self._plan_refs(plan)
                conflicted = bool(refs & seen_refs)
                seen_refs |= refs
                if view is None or not tensor or conflicted:
                    shape.exact_nodes = list(plan.node_allocation)
                    continue
                self._shape_dense(snap, view, plan, shape,
                                  verdict_rows=verdict_rows)
            except BaseException as e:   # noqa: BLE001 — isolate the plan
                # a malformed plan (bad alloc shapes, poisoned resources)
                # fails ALONE: it contributes no dense/exact work and the
                # siblings evaluate as if it never queued
                shape.error = e
                shape.dense_nodes = []
                shape.dense_rows = []
                shape.dense_asks = []
                shape.exact_nodes = []

        # gather every touched row ONCE — from the TensorCache when it is
        # current (same bits as the view by construction), else from the
        # view itself (the fallback when the cache misses or is disabled)
        all_rows = [r for s in shapes for r in s.dense_rows]
        cap_r = used_r = urow = None
        if all_rows:
            urow = np.unique(np.asarray(all_rows, np.int64))
            got = None
            try:
                from ..solver import state_cache
                got = state_cache.gather(view, urow)
            except Exception:   # noqa: BLE001 — view arrays serve below
                got = None
            if got is not None:
                cap_r, used_r = got.cap, got.used
            else:
                cap_r, used_r = view.cap[urow], view.used[urow]

        row_local = ({int(r): i for i, r in enumerate(urow)}
                     if urow is not None else {})

        # phase 2: the single vectorized pass. A row is "clean" when no
        # exact-path node maps to it, no plan's stops/preemptions touch
        # it, and its dense asks are either from one plan or all
        # non-negative — there the prefix-order verdicts collapse to one
        # elementwise compare (sum fits => every prefix fits).
        if all_rows:
            self._vector_pass(shapes, view, row_local, cap_r, used_r)

        # phase 3: ordered resolution. Each plan's remaining pairs see the
        # gathered rows plus the accumulated in-batch deltas; exact nodes
        # run the scalar oracle with the object-level ctx overlay.
        out = []
        live = [s for s in shapes if s.error is None]
        for shape in shapes:
            plan = shape.plan
            if shape.error is not None:
                out.append((plan, None, shape.error))
                continue
            try:
                result = self._resolve_plan(snap, view, plan, shape, ctx,
                                            row_local, cap_r, used_r)
            except BaseException as e:   # noqa: BLE001 — isolate the plan
                out.append((plan, None, e))
                continue
            # nothing after the LAST live plan consumes the overlay, so
            # a batch of one (the inline apply_plan path — the 50k
            # headline) never pays the per-alloc absorb walk at all
            if shape is not live[-1]:
                ctx.absorb(snap, view, plan, result)
            out.append((plan, result, None))
        return out

    @staticmethod
    def _plan_refs(plan: Plan) -> set:
        refs = set()
        for table in (plan.node_allocation, plan.node_update,
                      plan.node_preemptions):
            for allocs in table.values():
                for a in allocs:
                    refs.add(a.id)
        return refs

    def _shape_dense(self, snap, view, plan: Plan, shape: _PlanShape,
                     verdict_rows: dict = None) -> None:
        """Classify one plan's nodes and build its dense ask rows (the
        former per-plan `_evaluate_plan_dense` gather, ctx-free: phase 1
        runs before any in-batch commits exist for these plans)."""
        from ..state.usage_index import (
            alloc_usage_tuple, resources_sequential,
        )
        width = len(view.cap[0]) if len(view.cap) else 0
        for node_id, new_allocs in plan.node_allocation.items():
            node = snap.node_by_id(node_id)
            if node is None:
                shape.verdicts[node_id] = False
                continue
            r = view.row.get(node_id)
            if r is None or view.seq_rows.get(r):
                shape.exact_nodes.append(node_id)
                continue
            # NOTE: a node's own reserved_host_ports can't collide here —
            # no involved alloc uses ports (seq_rows + the per-alloc check
            # below), so the NetworkIndex part of allocs_fit is vacuous
            if node.drain or node.scheduling_eligibility != "eligible" or \
                    node.status != "ready":
                existing_ids = {a.id for a in snap.allocs_by_node(node_id)}
                if not all(a.id in existing_ids for a in new_allocs):
                    shape.verdicts[node_id] = False
                    continue
            ask = [0.0] * width
            seq = False
            for a in new_allocs:
                if resources_sequential(a.allocated_resources):
                    seq = True
                    break
                u = alloc_usage_tuple(a)
                for i, x in enumerate(u):
                    ask[i] += x
                existing = snap.alloc_by_id(a.id)
                if existing is not None and not existing.terminal_status() \
                        and existing.node_id == node_id:
                    # in-place update: replaces its state twin on this node
                    old = alloc_usage_tuple(existing)
                    for i, x in enumerate(old):
                        ask[i] -= x
            if seq:
                shape.exact_nodes.append(node_id)
                continue
            for a in list(plan.node_update.get(node_id, ())) + \
                    list(plan.node_preemptions.get(node_id, ())):
                existing = snap.alloc_by_id(a.id)
                if existing is not None and not existing.terminal_status() \
                        and existing.node_id == node_id:
                    old = alloc_usage_tuple(existing)
                    for i, x in enumerate(old):
                        ask[i] -= x
            if verdict_rows is not None:
                v = verdict_rows.get(r)
                if v is not None and np.all(
                        np.asarray(ask, np.float32) <= v):
                    # fused verdict fast path (ISSUE 15): the device
                    # proved used[r] + verified <= cap + eps at these
                    # exact usage bits; this plan's ask is elementwise
                    # <= verified and IEEE addition is monotone, so the
                    # dense compare must also pass. Node-status checks
                    # above still ran against LATEST state; only the
                    # row-fit re-gather is skipped. A False/absent/
                    # larger-ask row re-checks normally — fit is not
                    # monotone in the other direction.
                    shape.verdicts[node_id] = True
                    metrics.incr("nomad.plan.verdict_fastpath")
                    continue
            shape.dense_nodes.append(node_id)
            shape.dense_rows.append(r)
            shape.dense_asks.append(tuple(ask))

    def _vector_pass(self, shapes, view, local, cap_r, used_r) -> None:
        """Verdict every dense pair on a clean row — ONE vectorized
        compare over all (plan, node) pairs of the batch; the residual
        python loop is dict stores only. `local` is the caller's
        row -> gathered-index map (shared with phase 3)."""
        n_rows = cap_r.shape[0]
        # flatten all pairs into columns
        pair_li: list[int] = []
        for shape in shapes:
            if shape.error is not None:
                continue
            pair_li.extend(local[r] for r in shape.dense_rows)
        if not pair_li:
            return
        li = np.asarray(pair_li, np.int64)
        asks = np.asarray(
            [a for s in shapes if s.error is None for a in s.dense_asks],
            np.float32)
        touch = np.bincount(li, minlength=n_rows)
        total = np.zeros((n_rows, cap_r.shape[1]), np.float32)
        np.add.at(total, li, asks)
        neg = np.zeros(n_rows, bool)
        np.logical_or.at(neg, li, (asks < 0).any(axis=1))
        dirty = np.zeros(n_rows, bool)            # cross-plan interaction
        for shape in shapes:
            if shape.error is not None:
                continue
            for node_id in shape.exact_nodes:
                r = view.row.get(node_id)
                if r is not None and r in local:
                    dirty[local[r]] = True
            for table in (shape.plan.node_update,
                          shape.plan.node_preemptions):
                for node_id in table:
                    r = view.row.get(node_id)
                    if r is not None and r in local:
                        dirty[local[r]] = True
        fits_total = np.all(used_r + total <= cap_r + _FIT_EPS, axis=1)
        # clean single-toucher rows: the pair's own fit IS the verdict;
        # clean nonneg multi-toucher rows: total fits => all prefixes fit
        clean_multi = (~dirty) & (~neg) & (touch > 1) & fits_total
        clean_single = (~dirty) & (touch == 1)
        fit_pair = np.all(used_r[li] + asks <= cap_r[li] + _FIT_EPS,
                          axis=1)                 # the one AllocsFit pass
        cm, cs = clean_multi[li], clean_single[li]
        k = 0
        for shape in shapes:
            if shape.error is not None:
                continue
            kn: list = []
            kr: list = []
            ka: list = []
            for node_id, r, ask in zip(shape.dense_nodes, shape.dense_rows,
                                       shape.dense_asks):
                if cm[k]:
                    shape.verdicts[node_id] = True
                elif cs[k]:
                    shape.verdicts[node_id] = bool(fit_pair[k])
                else:
                    kn.append(node_id)
                    kr.append(r)
                    ka.append(ask)
                k += 1
            shape.dense_nodes, shape.dense_rows, shape.dense_asks = \
                kn, kr, ka

    def _resolve_plan(self, snap, view, plan: Plan, shape: _PlanShape,
                      ctx: _BatchCtx, row_local: dict, cap_r,
                      used_r) -> PlanResult:
        """Finish one plan: ordered dense pairs (with in-batch deltas),
        exact nodes via the scalar oracle, then the serial path's result
        assembly (all_at_once, refresh_index, no-op barrier handled by
        the caller)."""
        result = PlanResult(
            node_update=dict(plan.node_update),
            deployment=plan.deployment,
            deployment_updates=list(plan.deployment_updates),
        )
        verdicts = shape.verdicts
        if shape.dense_rows:
            li = np.asarray([row_local[r] for r in shape.dense_rows],
                            np.int64)
            asks = np.asarray(shape.dense_asks, np.float32)
            used = used_r[li]
            if ctx.used_delta:
                used = used.copy()
                for k, r in enumerate(shape.dense_rows):
                    acc = ctx.used_delta.get(r)
                    if acc is not None:
                        used[k] += np.asarray(acc, np.float32)
            ok = np.all(used + asks <= cap_r[li] + _FIT_EPS, axis=1)
            for node_id, fit in zip(shape.dense_nodes, ok):
                verdicts[node_id] = bool(fit)
        for node_id in shape.exact_nodes:
            verdicts[node_id] = self._evaluate_node_plan(snap, plan,
                                                         node_id, ctx)
        for node_id, allocs in plan.node_allocation.items():
            if verdicts.get(node_id, False):
                result.node_allocation[node_id] = allocs
                if node_id in plan.node_preemptions:
                    result.node_preemptions[node_id] = \
                        plan.node_preemptions[node_id]
            else:
                result.rejected_nodes.append(node_id)

        if plan.all_at_once and result.rejected_nodes:
            # all-or-nothing (ref structs.go Plan.AllAtOnce)
            result.node_allocation = {}
            result.node_preemptions = {}
            result.deployment = None
            result.deployment_updates = []

        if result.rejected_nodes:
            result.refresh_index = snap.latest_index()
        return result

    def _evaluate_plan_dense(self, snap, plan: Plan) -> dict:
        """Vectorized per-node re-check for nodes where every involved
        allocation is free of sequential resources (ports/cores/devices):
        there the exact allocs_fit reduces to an elementwise compare on the
        dense XR matrices the store maintains incrementally. Nodes needing
        the exact path are absent from the dict (ref plan_apply.go:638
        evaluateNodePlan — behavior identical, cost O(N·R')). Kept as the
        single-plan wrapper over the batch machinery (the differential
        tests' dense-vs-exact witness)."""
        view = getattr(snap, "usage", None)
        verdicts: dict = {}
        if view is None or not plan.node_allocation:
            return verdicts
        shape = _PlanShape(plan)
        self._shape_dense(snap, view, plan, shape)
        if shape.dense_rows:
            rows = np.asarray(shape.dense_rows, np.int64)
            asks = np.asarray(shape.dense_asks, np.float32)
            ok = np.all(view.used[rows] + asks <= view.cap[rows] + _FIT_EPS,
                        axis=1)
            for node_id, fit in zip(shape.dense_nodes, ok):
                shape.verdicts[node_id] = bool(fit)
        verdicts.update(shape.verdicts)
        return verdicts

    def _evaluate_node_plan(self, snap, plan: Plan, node_id: str,
                            ctx: Optional[_BatchCtx] = None) -> bool:
        """Per-node re-check against current state (ref :638
        evaluateNodePlan) — the vmapped fit check's scalar twin AND the
        whole batch's oracle under NOMAD_PLAN_TENSOR_EVAL=0. `ctx`
        overlays the effects of plans committed earlier in the same
        coalescing batch."""
        new_allocs = plan.node_allocation.get(node_id, [])
        if not new_allocs:
            return True
        node = snap.node_by_id(node_id)
        if node is None:
            return False
        batch_placed = (ctx.placed_by_node.get(node_id, ())
                        if ctx is not None else ())
        if node.drain or node.scheduling_eligibility != "eligible" or \
           node.status != "ready":
            # an existing-alloc update (inplace) is still allowed on
            # draining nodes; new placements are not
            existing_ids = {a.id for a in snap.allocs_by_node(node_id)}
            existing_ids |= {a.id for a in batch_placed}
            if not all(a.id in existing_ids for a in new_allocs):
                return False

        existing = [a for a in snap.allocs_by_node(node_id)
                    if not a.terminal_status()]
        if ctx is not None and not ctx.empty():
            existing = [a for a in existing
                        if a.id not in ctx.removed_ids
                        and a.id not in ctx.placed_ids]
            existing.extend(batch_placed)
        remove_ids = {a.id for a in plan.node_update.get(node_id, ())}
        remove_ids |= {a.id for a in plan.node_preemptions.get(node_id, ())}
        proposed = [a for a in existing if a.id not in remove_ids]
        new_ids = {a.id for a in new_allocs}
        proposed = [a for a in proposed if a.id not in new_ids]
        proposed.extend(new_allocs)
        fit, _, _ = allocs_fit(node, proposed)
        return fit

    # --------------------------------------------------- worker-facing API

    def submit_plan(self, plan: Plan,
                    timeout: float = 10.0) -> Optional[PlanResult]:
        # the queue's enabled flag IS the fence here: a non-leader's
        # queue is disabled and fails the pending immediately; the
        # commit itself is fence-checked in _run
        # nomadlint: disable=LEAD001 — queue-gated (see comment above)
        pending = self.queue.enqueue(plan)
        result, err = pending.wait(timeout)
        if err:
            return None
        return result

    def submit_plan_async(self, plan: Plan) -> _PendingPlan:
        """Enqueue without blocking (the pipelined plan lifecycle): the
        applier thread evaluates and commits in queue order while the
        caller keeps materializing later chunks; callers resolve the
        returned pending before submitting anything that must order
        after it. Chunk plans enqueued back-to-back coalesce into one
        commit batch (ordering preserved: drain is priority+FIFO)."""
        # nomadlint: disable=LEAD001 — queue-gated like submit_plan
        pending = self.queue.enqueue(plan)
        # depth is a LEVEL, not an event: gauge+sample like the sync
        # drain path above (the old `queue_depth_async` counter only
        # ever counted submissions — ISSUE 8 satellite)
        depth = self.queue.depth()
        metrics.set_gauge("nomad.plan.queue_depth", depth)
        metrics.add_sample("nomad.plan.queue_depth", depth)
        return pending
