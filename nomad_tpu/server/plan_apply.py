"""Serial plan applier + plan queue (ref nomad/plan_apply.go:71 planApply,
nomad/plan_queue.go).

The optimistic-concurrency heart of the design (kept untouched per the
north star): workers submit plans computed against possibly-stale snapshots;
the leader-serial applier re-checks every touched node against latest state
(ref :638 evaluateNodePlan) and commits only the slices that still fit.
Workers see rejections in the PlanResult and retry with a fresher snapshot.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Optional

from .. import faults
from ..metrics import metrics
from ..state import StateStore
from ..structs import (
    Allocation, NetworkIndex, Plan, PlanResult, allocs_fit,
)
from .fsm import APPLY_PLAN_RESULTS, PlanApplyRequest, RaftLog


class _PendingPlan:
    __slots__ = ("plan", "event", "result", "error")

    def __init__(self, plan: Plan):
        self.plan = plan
        self.event = threading.Event()
        self.result: Optional[PlanResult] = None
        self.error: Optional[str] = None

    def respond(self, result, error) -> None:
        self.result = result
        self.error = error
        self.event.set()

    def wait(self, timeout: Optional[float] = None
             ) -> tuple[Optional[PlanResult], Optional[str]]:
        self.event.wait(timeout)
        return self.result, self.error


class PlanQueue:
    """Priority FIFO of pending plans (ref nomad/plan_queue.go)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._heap: list = []
        self._seq = itertools.count()
        self._enabled = False

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                for _, _, pending in self._heap:
                    pending.respond(None, "plan queue disabled")
                self._heap = []
            self._cond.notify_all()

    def enqueue(self, plan: Plan) -> _PendingPlan:
        pending = _PendingPlan(plan)
        with self._lock:
            if not self._enabled:
                pending.respond(None, "plan queue disabled")
                return pending
            heapq.heappush(self._heap,
                           (-plan.priority, next(self._seq), pending))
            self._cond.notify_all()
        return pending

    def dequeue(self, timeout: float = 1.0) -> Optional[_PendingPlan]:
        with self._lock:
            if not self._heap:
                self._cond.wait(timeout)
            if not self._heap:
                return None
            _, _, pending = heapq.heappop(self._heap)
            return pending


class Planner:
    """The serial applier thread (ref plan_apply.go planApply:71)."""

    def __init__(self, raft: RaftLog, state: StateStore):
        self.raft = raft
        self.state = state
        self.queue = PlanQueue()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # the plan the applier thread has dequeued but not yet responded
        # to — stop() must fail it if the thread dies/outlives the join,
        # or a pipelined worker blocks on wait() forever (ISSUE 3)
        self._inflight: Optional[_PendingPlan] = None

    def start(self) -> None:
        self.queue.set_enabled(True)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="plan-applier")
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self.queue.set_enabled(False)      # queued pendings fail here
        if self._thread:
            self._thread.join(timeout=timeout)
        # a plan mid-apply when the join gave up (or the thread died)
        # must still resolve — waiters see an error, not a hang. respond
        # after a late applier respond is a harmless overwrite: every
        # waiter already woke on the first event.set().
        pending = self._inflight
        if pending is not None and not pending.event.is_set():
            pending.respond(None, "planner stopped")

    def _run(self) -> None:
        while not self._stop.is_set():
            pending = self.queue.dequeue(timeout=0.5)
            if pending is None:
                continue
            self._inflight = pending
            try:
                result = self.apply_plan(pending.plan)
                pending.respond(result, None)
            except Exception as e:       # noqa: BLE001 - report to worker
                pending.respond(None, str(e))
            finally:
                self._inflight = None

    # ------------------------------------------------------------ evaluate

    def apply_plan(self, plan: Plan) -> PlanResult:
        """Evaluate against latest state, then commit via the log
        (ref :204 applyPlan / :400 evaluatePlan)."""
        faults.fire("planner.apply")
        t0 = time.perf_counter()
        snap = self.state.snapshot_min_index(plan.snapshot_index,
                                            timeout=5.0)
        result = PlanResult(
            node_update=dict(plan.node_update),
            deployment=plan.deployment,
            deployment_updates=list(plan.deployment_updates),
        )
        dense = self._evaluate_plan_dense(snap, plan)
        for node_id, allocs in plan.node_allocation.items():
            verdict = dense.get(node_id)
            if verdict is None:         # sequential resources: exact check
                verdict = self._evaluate_node_plan(snap, plan, node_id)
            if verdict:
                result.node_allocation[node_id] = allocs
                if node_id in plan.node_preemptions:
                    result.node_preemptions[node_id] = \
                        plan.node_preemptions[node_id]
            else:
                result.rejected_nodes.append(node_id)
        # ref plan_apply.go:185 `nomad.plan.evaluate`
        metrics.add_sample("nomad.plan.evaluate", time.perf_counter() - t0)

        if plan.all_at_once and result.rejected_nodes:
            # all-or-nothing (ref structs.go Plan.AllAtOnce)
            result.node_allocation = {}
            result.node_preemptions = {}
            result.deployment = None
            result.deployment_updates = []

        if result.rejected_nodes:
            result.refresh_index = snap.latest_index()

        if result.is_no_op() and not result.node_update:
            result.alloc_index = self.raft.barrier()
            return result

        req = PlanApplyRequest(
            alloc_updates=[a for allocs in result.node_update.values()
                           for a in allocs],
            alloc_placements=[a for allocs in result.node_allocation.values()
                              for a in allocs],
            alloc_preemptions=[a for allocs in result.node_preemptions.values()
                               for a in allocs],
            deployment=result.deployment,
            deployment_updates=result.deployment_updates,
            eval_id=plan.eval_id,
        )
        # ref plan_apply.go:204 `nomad.plan.apply` (raft commit + FSM)
        with metrics.measure("nomad.plan.apply"):
            index = self.raft.apply(APPLY_PLAN_RESULTS, {"result": req})
        result.alloc_index = index
        # feed the committed plan's usage deltas to the solver's device-
        # resident tensor cache HERE, on the leader-serial applier thread:
        # the journal replay (host np.add.at + one batched device scatter)
        # runs off the eval critical path, so the next eval's tensorize is
        # a pure cache hit (ISSUE 4; docs/DEVICE_STATE_CACHE.md). The plan
        # IS committed at this point — no cache-feed failure may surface
        # as a failed apply (the worker would fail an eval whose plan
        # landed); lazy import keeps a stripped solver-less build booting.
        try:
            from ..solver import state_cache
            state_cache.note_commit(self.state)
        except Exception as e:   # noqa: BLE001 — telemetry-grade feed
            from ..metrics import record_swallowed_error
            record_swallowed_error("plan_apply.state_cache_feed", e)
        return result

    def _evaluate_plan_dense(self, snap, plan: Plan) -> dict:
        """Vectorized per-node re-check for nodes where every involved
        allocation is free of sequential resources (ports/cores/devices):
        there the exact allocs_fit reduces to an elementwise compare on the
        dense XR matrices the store maintains incrementally, so a 50k-alloc
        plan pays one numpy compare instead of 50k object walks. Nodes
        needing the exact path map to None (ref plan_apply.go:638
        evaluateNodePlan — behavior identical, cost O(N·R')).
        """
        import numpy as np
        from ..state.usage_index import (
            alloc_usage_tuple, resources_sequential,
        )
        view = getattr(snap, "usage", None)
        verdicts: dict = {}
        if view is None or not plan.node_allocation:
            return verdicts
        rows: list[int] = []
        asks: list[tuple] = []
        ids: list[str] = []
        for node_id, new_allocs in plan.node_allocation.items():
            node = snap.node_by_id(node_id)
            if node is None:
                verdicts[node_id] = False
                continue
            r = view.row.get(node_id)
            if r is None or view.seq_rows.get(r):
                continue                          # exact path
            # NOTE: a node's own reserved_host_ports can't collide here —
            # no involved alloc uses ports (seq_rows + the per-alloc check
            # below), so the NetworkIndex part of allocs_fit is vacuous
            if node.drain or node.scheduling_eligibility != "eligible" or \
                    node.status != "ready":
                existing_ids = {a.id for a in snap.allocs_by_node(node_id)}
                if not all(a.id in existing_ids for a in new_allocs):
                    verdicts[node_id] = False
                    continue
            ask = [0.0] * len(view.cap[0])
            seq = False
            for a in new_allocs:
                if resources_sequential(a.allocated_resources):
                    seq = True
                    break
                u = alloc_usage_tuple(a)
                for i, x in enumerate(u):
                    ask[i] += x
                existing = snap.alloc_by_id(a.id)
                if existing is not None and not existing.terminal_status() \
                        and existing.node_id == node_id:
                    # in-place update: replaces its state twin on this node
                    old = alloc_usage_tuple(existing)
                    for i, x in enumerate(old):
                        ask[i] -= x
            if seq:
                continue                          # exact path
            for a in list(plan.node_update.get(node_id, ())) + \
                    list(plan.node_preemptions.get(node_id, ())):
                existing = snap.alloc_by_id(a.id)
                if existing is not None and not existing.terminal_status() \
                        and existing.node_id == node_id:
                    old = alloc_usage_tuple(existing)
                    for i, x in enumerate(old):
                        ask[i] -= x
            rows.append(r)
            asks.append(tuple(ask))
            ids.append(node_id)
        if ids:
            ridx = np.asarray(rows, np.int64)
            delta = np.asarray(asks, np.float32)
            ok = np.all(view.used[ridx] + delta <= view.cap[ridx] + 1e-3,
                        axis=1)
            for node_id, fit in zip(ids, ok):
                verdicts[node_id] = bool(fit)
        return verdicts

    def _evaluate_node_plan(self, snap, plan: Plan, node_id: str) -> bool:
        """Per-node re-check against current state (ref :638
        evaluateNodePlan) — the vmapped fit check's scalar twin."""
        new_allocs = plan.node_allocation.get(node_id, [])
        if not new_allocs:
            return True
        node = snap.node_by_id(node_id)
        if node is None:
            return False
        if node.drain or node.scheduling_eligibility != "eligible" or \
           node.status != "ready":
            # an existing-alloc update (inplace) is still allowed on
            # draining nodes; new placements are not
            existing_ids = {a.id for a in snap.allocs_by_node(node_id)}
            if not all(a.id in existing_ids for a in new_allocs):
                return False

        existing = [a for a in snap.allocs_by_node(node_id)
                    if not a.terminal_status()]
        remove_ids = {a.id for a in plan.node_update.get(node_id, ())}
        remove_ids |= {a.id for a in plan.node_preemptions.get(node_id, ())}
        proposed = [a for a in existing if a.id not in remove_ids]
        new_ids = {a.id for a in new_allocs}
        proposed = [a for a in proposed if a.id not in new_ids]
        proposed.extend(new_allocs)
        fit, _, _ = allocs_fit(node, proposed)
        return fit

    # --------------------------------------------------- worker-facing API

    def submit_plan(self, plan: Plan,
                    timeout: float = 10.0) -> Optional[PlanResult]:
        pending = self.queue.enqueue(plan)
        result, err = pending.wait(timeout)
        if err:
            return None
        return result

    def submit_plan_async(self, plan: Plan) -> _PendingPlan:
        """Enqueue without blocking (the pipelined plan lifecycle): the
        applier thread evaluates and commits in queue order while the
        caller keeps materializing later chunks; callers resolve the
        returned pending before submitting anything that must order
        after it."""
        metrics.incr("nomad.plan.queue_depth_async")
        return self.queue.enqueue(plan)
