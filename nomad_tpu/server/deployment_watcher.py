"""Deployment watcher (ref nomad/deploymentwatcher/deployments_watcher.go:60,
per-deployment deployment_watcher.go): drives rolling updates, canaries,
auto-promote/auto-revert, and progress deadlines.

Health flow: alloc runners report deployment_status through the client sync;
the watcher folds unseen health verdicts into the deployment via
DEPLOYMENT_ALLOC_HEALTH, then evaluates the state machine and emits
follow-up evals so the scheduler places the next max_parallel batch.
"""
from __future__ import annotations

import threading
from typing import Optional

from .. import chrono
from ..structs import (
    Deployment, DeploymentStatusUpdate, Evaluation,
    DEPLOYMENT_STATUS_FAILED, DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL, EVAL_STATUS_PENDING,
    TRIGGER_DEPLOYMENT_WATCHER, TRIGGER_ROLLING_UPDATE,
)
from .lifecycle import LoopHandle
from .fsm import (
    DEPLOYMENT_ALLOC_HEALTH, DEPLOYMENT_PROMOTE, DEPLOYMENT_STATUS_UPDATE,
    EVAL_UPDATE, JOB_REGISTER,
)

DESC_PROGRESS_DEADLINE = "Failed due to progress deadline"
DESC_UNHEALTHY_ALLOCS = "Failed due to unhealthy allocations"
DESC_SUCCESSFUL = "Deployment completed successfully"
DESC_AUTO_PROMOTED = "Deployment promoted automatically"
DESC_FAILED_REVERT = ("Failed due to unhealthy allocations - rolling back "
                      "to job version %d")


class DeploymentWatcher:
    def __init__(self, server, poll_interval: float = 0.25,
                 clock: Optional[chrono.Clock] = None):
        self.server = server
        self.poll_interval = poll_interval
        # progress-deadline DECISIONS ride the clock (ISSUE 8 satellite):
        # "the deployment made no progress for progress_deadline_sec" is
        # testable with ManualClock.advance() instead of real sleeps
        self.clock = clock or chrono.REAL
        # explicit start/join lifecycle state (server/lifecycle.py): the
        # handle owns the stop event so set+join and clear+spawn are
        # atomic pairs (a leadership re-acquire can no longer clear the
        # event out from under a mid-join stop and leak a second watcher)
        self._loop = LoopHandle()
        self._stop = self._loop.stop_event
        # deployment_id -> alloc_id -> last folded verdict; a changed verdict
        # (healthy flipping to unhealthy) must be re-processed
        self._seen_health: dict[str, dict[str, bool]] = {}
        self._progress_by: dict[str, float] = {}

    def start(self) -> None:
        self._loop.start(self._run, "deployment-watcher")

    def stop(self) -> None:
        self._loop.stop(timeout=5.0)

    def _run(self) -> None:
        """ref deployments_watcher.go:164 watchDeployments"""
        while not self._stop.wait(self.poll_interval):
            try:
                self.tick()
            except Exception as e:      # noqa: BLE001
                self.server.logger(f"deployment-watcher: {e!r}")

    def tick(self) -> None:
        """One watcher pass over every deployment. Public so bounded-
        wait tests can drive the state machine directly inside their
        poll instead of racing the 0.25s loop on a loaded box (the PR-6
        gossip-promote deflake pattern); an extra concurrent pass is
        harmless — health folding dedups via _seen_health and the
        status updates are idempotent."""
        for d in self.server.state.iter_deployments():
            if d.active():
                self._watch_one(d)
            else:
                self._seen_health.pop(d.id, None)
                self._progress_by.pop(d.id, None)

    # ----------------------------------------------------------- per-deploy

    def _watch_one(self, d: Deployment) -> None:
        state = self.server.state
        seen = self._seen_health.setdefault(d.id, {})
        healthy, unhealthy = [], []
        for alloc in state.allocs_by_job(d.namespace, d.job_id):
            if alloc.deployment_id != d.id:
                continue
            ds = alloc.deployment_status
            if ds is None or ds.healthy is None:
                continue
            if seen.get(alloc.id) == ds.healthy:
                continue
            seen[alloc.id] = ds.healthy
            (healthy if ds.healthy else unhealthy).append(alloc.id)

        made_progress = bool(healthy)
        if healthy or unhealthy:
            self.server.raft.apply(DEPLOYMENT_ALLOC_HEALTH, {
                "deployment_id": d.id, "healthy": healthy,
                "unhealthy": unhealthy, "timestamp": self.clock.time()})
            d = state.deployment_by_id(d.id)
            if d is None or not d.active():
                return

        # progress deadline bookkeeping
        deadline = self._progress_by.get(d.id)
        if deadline is None:
            deadline = self.clock.time() + max(
                (st.progress_deadline_sec or 600.0)
                for st in d.task_groups.values()) if d.task_groups else \
                self.clock.time() + 600.0
            self._progress_by[d.id] = deadline
        if made_progress:
            self._progress_by[d.id] = self.clock.time() + max(
                (st.progress_deadline_sec or 600.0)
                for st in d.task_groups.values())

        # unhealthy allocs fail the deployment (+ auto-revert)
        if unhealthy:
            self._fail(d, DESC_UNHEALTHY_ALLOCS)
            return

        if self.clock.time() >= self._progress_by[d.id] and \
           not self._complete_check(d):
            self._fail(d, DESC_PROGRESS_DEADLINE)
            return

        # auto-promote: every desired canary placed and healthy
        if d.requires_promotion() and d.has_auto_promote():
            if all(st.desired_canaries <= st.healthy_allocs
                   for st in d.task_groups.values()
                   if st.desired_canaries > 0):
                self.promote(d.id)
                return

        # success: all groups promoted (if needed) and fully healthy
        if self._complete_check(d):
            self.server.raft.apply(DEPLOYMENT_STATUS_UPDATE, {
                "update": DeploymentStatusUpdate(
                    deployment_id=d.id,
                    status=DEPLOYMENT_STATUS_SUCCESSFUL,
                    status_description=DESC_SUCCESSFUL)})
            return

        # progress: wake the scheduler to place the next batch
        if made_progress:
            self._create_eval(d, TRIGGER_DEPLOYMENT_WATCHER)

    def _complete_check(self, d: Deployment) -> bool:
        if not d.task_groups:
            return False
        for st in d.task_groups.values():
            if st.desired_canaries > 0 and not st.promoted:
                return False
            if st.healthy_allocs < st.desired_total:
                return False
        return True

    def _fail(self, d: Deployment, desc: str) -> None:
        state = self.server.state
        rollback_job = None
        if any(st.auto_revert for st in d.task_groups.values()):
            current = state.job_by_id(d.namespace, d.job_id)
            if current is not None and d.job_version > 0:
                for version in range(d.job_version - 1, -1, -1):
                    candidate = state.job_by_version(d.namespace, d.job_id,
                                                     version)
                    if candidate is not None and candidate.stable:
                        rollback_job = candidate
                        break
        if rollback_job is not None:
            desc = DESC_FAILED_REVERT % rollback_job.version
        self.server.raft.apply(DEPLOYMENT_STATUS_UPDATE, {
            "update": DeploymentStatusUpdate(
                deployment_id=d.id, status=DEPLOYMENT_STATUS_FAILED,
                status_description=desc)})
        if rollback_job is not None:
            job = rollback_job.copy()
            ev = Evaluation(
                namespace=d.namespace, priority=job.priority, type=job.type,
                triggered_by=TRIGGER_DEPLOYMENT_WATCHER, job_id=d.job_id,
                deployment_id=d.id, status=EVAL_STATUS_PENDING)
            self.server.raft.apply(JOB_REGISTER, {"job": job, "evals": [ev]})
        else:
            self._create_eval(d, TRIGGER_DEPLOYMENT_WATCHER)

    def _create_eval(self, d: Deployment, trigger: str) -> None:
        job = self.server.state.job_by_id(d.namespace, d.job_id)
        if job is None:
            return
        ev = Evaluation(
            namespace=d.namespace, priority=job.priority, type=job.type,
            triggered_by=trigger, job_id=d.job_id, deployment_id=d.id,
            status=EVAL_STATUS_PENDING)
        self.server.raft.apply(EVAL_UPDATE, {"evals": [ev]})

    # ---------------------------------------------------------- public API

    def promote(self, deployment_id: str,
                groups: Optional[list[str]] = None) -> dict:
        """ref deploymentwatcher PromoteDeployment"""
        d = self.server.state.deployment_by_id(deployment_id)
        if d is None:
            raise KeyError(f"deployment {deployment_id} not found")
        for name, st in d.task_groups.items():
            if groups is not None and name not in groups:
                continue
            if st.desired_canaries > 0 and \
               st.healthy_allocs < st.desired_canaries:
                raise ValueError(
                    f"group {name!r}: {st.healthy_allocs}/"
                    f"{st.desired_canaries} canaries healthy")
        ev = None
        job = self.server.state.job_by_id(d.namespace, d.job_id)
        if job is not None:
            ev = Evaluation(
                namespace=d.namespace, priority=job.priority, type=job.type,
                triggered_by=TRIGGER_DEPLOYMENT_WATCHER, job_id=d.job_id,
                deployment_id=d.id, status=EVAL_STATUS_PENDING)
        self.server.raft.apply(DEPLOYMENT_PROMOTE, {
            "deployment_id": deployment_id, "groups": groups, "eval": ev})
        return {"eval_id": ev.id if ev else ""}

    def fail_deployment(self, deployment_id: str) -> dict:
        d = self.server.state.deployment_by_id(deployment_id)
        if d is None:
            raise KeyError(f"deployment {deployment_id} not found")
        self._fail(d, "Deployment marked as failed")
        return {}

    def pause(self, deployment_id: str, paused: bool) -> dict:
        from ..structs import DEPLOYMENT_STATUS_PAUSED
        d = self.server.state.deployment_by_id(deployment_id)
        if d is None:
            raise KeyError(f"deployment {deployment_id} not found")
        status = DEPLOYMENT_STATUS_PAUSED if paused else \
            DEPLOYMENT_STATUS_RUNNING
        self.server.raft.apply(DEPLOYMENT_STATUS_UPDATE, {
            "update": DeploymentStatusUpdate(
                deployment_id=deployment_id, status=status,
                status_description="paused" if paused else "resumed")})
        return {}
