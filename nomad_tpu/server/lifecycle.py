"""Explicit thread-lifecycle state for restartable daemon loops (ISSUE 11
satellite — the `test_raftnode_fence_rejects_after_term_moves` in-suite
flake).

The old per-component pattern

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

raced two ways. The leadership recovery barrier start()s these loops on
the election-callback thread while Server.shutdown() (or a revoke)
stop()s them from another:

  1. a stop() landing between the `_thread` assignment and the
     `.start()` call joins a thread that was never started —
     `RuntimeError("cannot join thread before it is started")`
     (observed in-suite under load in PR 10);
  2. a start() clearing the SHARED stop event while a stop() is
     mid-join un-stops the loop the join is waiting on — the join burns
     its whole timeout, the still-running loop leaks, and the restart
     spawns a second one beside it.

LoopHandle makes the state explicit by owning BOTH halves: the stop
event and the thread handle mutate under one lock, so `set + join` and
`clear + spawn` are atomic pairs that strictly order against each
other. The handle is only assigned AFTER `Thread.start()` returned (a
visible handle is always a started thread), and a failed spawn
(`can't start new thread` under load) leaves no handle behind.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional


class LoopHandle:
    """Start/stop state for one restartable daemon thread. The owning
    component reads `handle.stop_event` in its loop condition; start()
    clears it and stop() sets it — always under the handle lock."""

    def __init__(self, stop_event: Optional[threading.Event] = None):
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.stop_event = stop_event if stop_event is not None \
            else threading.Event()

    def start(self, target: Callable[[], None], name: str) -> bool:
        """Clear the stop event and spawn the loop thread; no-op (False)
        while a previous incarnation is still alive — a concurrent
        stop() orders strictly before or after on the same lock. An
        incarnation left DRAINING by a timed-out stop() (stop event set,
        thread still alive) is waited for briefly rather than duplicated
        or un-stopped; if it is genuinely wedged the restart is refused
        — one slow loop must never become two concurrent ones."""
        with self._lock:
            t = self._thread
            if t is not None and t.is_alive():
                if not self.stop_event.is_set():
                    return False            # already running healthy
                t.join(timeout=5.0)         # draining: let it finish
                if t.is_alive():
                    return False            # wedged: refuse to duplicate
            self.stop_event.clear()
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()               # raises -> nothing assigned below
            self._thread = t
            return True

    def stop(self, timeout: float = 5.0) -> bool:
        """Set the stop event and join the loop thread. Atomic under the
        handle lock: no concurrent start() can clear the event while the
        join is waiting on it. A join that exhausts `timeout` KEEPS the
        handle (False) — dropping it would let the next start() clear
        the stop event out from under the still-running loop and spawn
        a duplicate beside it."""
        with self._lock:
            self.stop_event.set()
            t = self._thread
            if t is None:
                return True
            t.join(timeout=timeout)
            if t.is_alive():
                return False                # still draining: keep handle
            self._thread = None
            return True

    def is_alive(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()
