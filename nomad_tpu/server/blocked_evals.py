"""Blocked-evals tracker (ref nomad/blocked_evals.go): evals that failed to
place wait here and unblock when capacity changes for a computed node class
they could use (or on any change, for escaped evals).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..metrics import metrics
from ..structs import Evaluation, TRIGGER_MAX_PLANS

# per-tracker capture ceiling (ISSUE 8): blocked evals dedup per job, so
# this only binds when MORE JOBS than this are simultaneously
# unplaceable — at which point capturing further evals just defers the
# same capacity verdict. Overflow drops the lowest-priority capture
# (counted), which simply re-blocks on its next evaluation.
DEFAULT_MAX_CAPTURED = 16_384


class BlockedEvals:
    def __init__(self, enqueue_fn: Callable[[Evaluation], None],
                 max_captured: int = DEFAULT_MAX_CAPTURED):
        self._lock = threading.Lock()
        self._enabled = False
        self.enqueue_fn = enqueue_fn
        self.max_captured = max_captured
        # eval_id -> eval
        self._captured: dict[str, Evaluation] = {}
        # (namespace, job_id) -> eval_id (one blocked eval per job)
        self._by_job: dict[tuple[str, str], str] = {}
        self._escaped: set[str] = set()
        self.stats = {"total_blocked": 0, "total_escaped": 0,
                      "total_unblocked": 0, "total_dropped": 0}

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                self._captured.clear()
                self._by_job.clear()
                self._escaped.clear()

    def block(self, ev: Evaluation) -> None:
        """ref blocked_evals.go Block"""
        with self._lock:
            if not self._enabled:
                return
            job_key = (ev.namespace, ev.job_id)
            # dedup: keep only the newest blocked eval per job
            old_id = self._by_job.get(job_key)
            if old_id and old_id in self._captured:
                old = self._captured.pop(old_id)
                self._escaped.discard(old_id)
            if self.max_captured > 0 and old_id is None and \
                    len(self._captured) >= self.max_captured:
                # cap (ISSUE 8): drop the lowest-priority capture (the
                # incoming eval included) — counted, never silent
                victim_id = min(self._captured,
                                key=lambda i: self._captured[i].priority)
                if self._captured[victim_id].priority >= ev.priority:
                    metrics.incr("nomad.blocked_evals.dropped")
                    self.stats["total_dropped"] += 1
                    return
                victim = self._captured.pop(victim_id)
                self._escaped.discard(victim_id)
                self._by_job.pop((victim.namespace, victim.job_id), None)
                metrics.incr("nomad.blocked_evals.dropped")
                self.stats["total_dropped"] += 1
            self._captured[ev.id] = ev
            self._by_job[job_key] = ev.id
            if ev.escaped_computed_class or not ev.class_eligibility:
                self._escaped.add(ev.id)
            self.stats["total_blocked"] = len(self._captured)
            self.stats["total_escaped"] = len(self._escaped)

    def untrack(self, namespace: str, job_id: str) -> None:
        """Job updated/deregistered: its blocked eval is obsolete."""
        with self._lock:
            eval_id = self._by_job.pop((namespace, job_id), None)
            if eval_id:
                self._captured.pop(eval_id, None)
                self._escaped.discard(eval_id)
            self.stats["total_blocked"] = len(self._captured)

    def unblock(self, computed_class: str, index: int = 0) -> None:
        """Capacity for `computed_class` changed — release matching evals
        (ref blocked_evals.go Unblock)."""
        to_run: list[Evaluation] = []
        with self._lock:
            if not self._enabled:
                return
            for eval_id in list(self._captured):
                ev = self._captured[eval_id]
                release = False
                if eval_id in self._escaped:
                    release = True
                elif computed_class in ev.class_eligibility:
                    # previously-ineligible classes can't help
                    release = ev.class_eligibility[computed_class]
                else:
                    # unseen class: might help
                    release = True
                if release:
                    to_run.append(ev)
                    del self._captured[eval_id]
                    self._escaped.discard(eval_id)
                    self._by_job.pop((ev.namespace, ev.job_id), None)
            self.stats["total_blocked"] = len(self._captured)
            self.stats["total_unblocked"] += len(to_run)
        for ev in to_run:
            out = ev.copy()
            out.status = "pending"
            out.snapshot_index = index
            self.enqueue_fn(out)

    def unblock_all(self, index: int = 0) -> None:
        with self._lock:
            evals = list(self._captured.values())
            self._captured.clear()
            self._by_job.clear()
            self._escaped.clear()
            self.stats["total_unblocked"] += len(evals)
        for ev in evals:
            out = ev.copy()
            out.status = "pending"
            out.snapshot_index = index
            self.enqueue_fn(out)
