"""Event broker: pub/sub of state-change events with per-subscriber
backpressure (ref nomad/stream/event_broker.go:30 EventBroker,
event_buffer.go).

A bounded ring buffer of event batches with per-subscriber queues. A
subscriber that falls behind rides three backpressure rungs, gentlest
first (ISSUE 16):

  1. **coalesce** — above `coalesce_after` queued batches, the queue is
     folded latest-wins per (topic, namespace, key); the threshold
     tightens with the overload pressure state (`pressure_fn`). Opt-in
     at construction (the Server opts in; a bare broker keeps the
     legacy deliver-every-event contract).
  2. **park** — blocking readers wait on `wait_for_index(topics, index)`
     instead of poll-looping the state store, so only writes on the
     watched topics wake them.
  3. **drop** — only when coalescing cannot shrink the queue under
     `max_pending` (that many *distinct* keys in flight) is the
     subscriber closed (the reference's ErrSubscriptionClosed contract,
     `nomad.event.subscriber_dropped`).

Events originate from the state store's `event_sinks` (our analog of
nomad/state/events.go eventsFromChanges). Feeds `/v1/event/stream` and
the HTTP blocking-query helpers.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional, Union

from ..metrics import metrics

ALL_KEYS = "*"

TOPIC_JOB = "Job"
TOPIC_EVAL = "Evaluation"
TOPIC_ALLOC = "Allocation"
TOPIC_DEPLOYMENT = "Deployment"
TOPIC_NODE = "Node"
TOPIC_ALL = "*"


class SubscriptionClosedError(Exception):
    """The subscriber fell behind the ring buffer and was dropped
    (ref stream/subscription.go ErrSubscriptionClosed)."""


@dataclass
class Event:
    topic: str
    type: str
    key: str = ""
    namespace: str = ""
    filter_keys: list[str] = field(default_factory=list)
    index: int = 0
    payload: Any = None

    def to_api(self) -> dict:
        from ..api_codec import to_api
        wrapper_key = {
            TOPIC_JOB: "Job", TOPIC_EVAL: "Evaluation",
            TOPIC_ALLOC: "Allocation", TOPIC_DEPLOYMENT: "Deployment",
            TOPIC_NODE: "Node",
        }.get(self.topic, "Payload")
        payload = self.payload
        if payload is not None and not isinstance(payload, (dict, str, int,
                                                            float, list)):
            payload = to_api(payload)
        return {"Topic": self.topic, "Type": self.type, "Key": self.key,
                "Namespace": self.namespace, "FilterKeys": self.filter_keys,
                "Index": self.index, "Payload": {wrapper_key: payload}}


def _match(req_topics: dict[str, list[str]], ev: Event) -> bool:
    for topic in (ev.topic, TOPIC_ALL):
        keys = req_topics.get(topic)
        if keys is None:
            continue
        for k in keys:
            if k == ALL_KEYS or k == ev.key or k in ev.filter_keys:
                return True
    return False


class Subscription:
    def __init__(self, broker: "EventBroker", topics: dict[str, list[str]],
                 namespace: str = ""):
        self._broker = broker
        self.topics = topics or {TOPIC_ALL: [ALL_KEYS]}
        self.namespace = namespace
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._closed = False

    def _offer(self, index: int, events: list[Event]) -> None:
        wanted = [e for e in events if _match(self.topics, e)
                  and (not self.namespace or not e.namespace
                       or e.namespace == self.namespace)]
        dropped = False
        with self._cond:
            if self._closed:
                return
            if wanted:
                self._queue.append((index, wanted))
                threshold = self._broker._coalesce_threshold()
                if threshold is not None and len(self._queue) > threshold:
                    self._coalesce_locked()
                if len(self._queue) > self._broker.max_pending:
                    self._closed = True   # slow consumer: drop (last rung)
                    self._queue.clear()
                    dropped = True
            self._cond.notify_all()
        if dropped:
            # the per-subscriber cap firing must be visible (ISSUE 8
            # satellite): a fleet of watchers silently re-subscribing in
            # a drop loop looks exactly like healthy streaming otherwise
            metrics.incr("nomad.event.subscriber_dropped")
            self._broker._unsubscribe(self)

    def _coalesce_locked(self) -> None:
        """Fold the queued batches latest-wins per (topic, namespace, key).

        The zero-loss contract is per key, not per event: after a
        coalesce a reader still observes the latest state of every key
        that was ever queued, in index order, but intermediate updates
        to the same key are superseded. Caller holds self._cond."""
        total = sum(len(evs) for _, evs in self._queue)
        latest: dict[tuple[str, str, str], Event] = {}
        max_index = 0
        for idx, evs in self._queue:
            max_index = max(max_index, idx)
            for e in evs:
                latest[(e.topic, e.namespace, e.key)] = e
        superseded = total - len(latest)
        if superseded <= 0:
            return
        merged = sorted(latest.values(), key=lambda e: e.index)
        self._queue.clear()
        # strictly shrinking: N queued batches fold into this single one,
        # and _offer still drops the subscriber past max_pending
        # nomadlint: disable=QUEUE001 — shrinking fold, bound in _offer
        self._queue.append((max_index, merged))
        metrics.incr("nomad.event.coalesced_batches")
        metrics.incr("nomad.event.coalesced_events", superseded)

    def next_events(self, timeout: Optional[float] = None
                    ) -> Optional[tuple[int, list[Event]]]:
        """Block until the next matching batch; None on timeout. Raises
        SubscriptionClosedError if dropped for falling behind."""
        # loop on a deadline: a bare cond.wait(timeout) returns early on
        # notify-without-data (e.g. a publish whose batch matched nothing,
        # or a batch consumed by a racing reader under the RLock), which
        # silently truncated the caller's timeout (ISSUE 16 satellite)
        deadline = (None if timeout is None
                    else time.monotonic() + max(0.0, timeout))
        with self._cond:
            while not self._queue and not self._closed:
                if deadline is None:
                    self._cond.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            if self._closed:
                raise SubscriptionClosedError()
            if self._queue:
                return self._queue.popleft()
            return None

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._broker._unsubscribe(self)


class EventBroker:
    """ref nomad/stream/event_broker.go:30; buffer_size mirrors
    EventBufferSize (default 100 batches)."""

    def __init__(self, buffer_size: int = 256, max_pending: int = 512,
                 coalesce_after: Optional[int] = None,
                 pressure_fn=None):
        # RLock: subscribe() replays into the sub while holding the lock; an
        # overflowing replay re-enters via _unsubscribe
        self._lock = threading.RLock()
        self._buffer: deque[tuple[int, list[Event]]] = deque(
            maxlen=buffer_size)
        self._subs: list[Subscription] = []
        self.max_pending = max_pending
        # backpressure rung 1: queued batches past this start coalescing
        # latest-wins per key. None (the default) keeps the legacy
        # deliver-every-event contract — rung 1 is OPT-IN at
        # construction because folding is only sound for consumers that
        # want latest STATE per key, not an exhaustive event log; the
        # Server opts its broker in (server.py), bare brokers don't
        self.coalesce_after = coalesce_after
        # optional overload pressure feed ("ok"/"saturated"/"shedding");
        # pressure tightens the coalesce threshold so bursty fan-out
        # degrades to latest-state delivery before anything drops
        self.pressure_fn = pressure_fn
        self._latest_index = 0
        # highest published index per topic, for wait_for_index parking
        self._topic_index: dict[str, int] = {}
        self._pub_cond = threading.Condition(self._lock)

    def _coalesce_threshold(self) -> Optional[int]:
        ca = self.coalesce_after
        if ca is None:
            return None
        if self.pressure_fn is not None:
            try:
                pressure = self.pressure_fn()
            except Exception:
                pressure = "ok"
            if pressure == "saturated":
                return max(1, ca // 4)
            if pressure == "shedding":
                return 1
        return ca

    # ------------------------------------------------------------- publish

    def publish(self, index: int, events: list[Event]) -> None:
        """ref event_broker.go:95 Publish"""
        if not events:
            return
        with self._lock:
            self._latest_index = max(self._latest_index, index)
            for ev in events:
                if index > self._topic_index.get(ev.topic, 0):
                    self._topic_index[ev.topic] = index
            # the ring bound lives in __init__: deque(maxlen=buffer_size)
            # nomadlint: disable=QUEUE001 — deque maxlen ring (above)
            self._buffer.append((index, events))
            subs = list(self._subs)
            self._pub_cond.notify_all()
        for sub in subs:
            sub._offer(index, events)

    def sink(self, topic: str, etype: str, index: int, payload) -> None:
        """Adapter matching StateStore.event_sinks signature."""
        self.publish(index, [make_event(topic, etype, index, payload)])

    def sink_batch(self, rows: list) -> None:
        """Adapter matching StateStore.event_batch_sinks (ISSUE 20): a
        whole apply-batch window's events — [(topic, etype, index,
        payload)] — as ONE publish: one broker-lock round, one ring
        batch, one _offer per subscriber, published at the window's
        highest index (each event keeps its own index; a watcher woken
        at the window index re-reads state that already contains the
        whole window, the same visibility rule as the store's
        one-lock-hold batch applies)."""
        if not rows:
            return
        self.publish(max(r[2] for r in rows),
                     [make_event(t, e, i, p) for t, e, i, p in rows])

    # ----------------------------------------------------------- subscribe

    def subscribe(self, topics: Optional[dict[str, list[str]]] = None,
                  index: int = 0, namespace: str = "") -> Subscription:
        """ref event_broker.go:138 Subscribe — replays buffered batches with
        index > `index` before going live."""
        sub = Subscription(self, topics or {}, namespace)
        with self._lock:
            # replay while holding the broker lock, BEFORE the sub becomes
            # visible to publish(), so batch order stays index-monotonic
            if index:
                for i, evs in self._buffer:
                    if i > index:
                        sub._offer(i, evs)
            self._subs.append(sub)
        return sub

    def _unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def latest_index(self) -> int:
        with self._lock:
            return self._latest_index

    def topic_index(self, topic: str) -> int:
        """Highest index that has published an event on `topic`."""
        with self._lock:
            if topic == TOPIC_ALL:
                return self._latest_index
            return self._topic_index.get(topic, 0)

    # ------------------------------------------------------------- parking

    def wait_for_index(self, topics: Union[dict, Iterable[str], None],
                       index: int, timeout: float = 30.0) -> int:
        """Park until an event on one of `topics` carries index > `index`;
        backpressure rung 2 for blocking queries.

        `topics` is a subscribe()-style dict (only the topic names are
        consulted — wakeups are topic-granular), an iterable of topic
        names, or None/"*" for any topic. Returns the highest published
        index across the watched topics at wake time, which may still be
        <= `index` on timeout: writes that emit no event (rare GC paths)
        move the store index without waking the broker, so callers keep
        a deadline re-check of their own index_fn. That bounded re-check
        is the correctness backstop; the broker is the fast path that
        avoids waking every watcher on every unrelated write."""
        names: Optional[list[str]] = None
        if topics:
            names = list(topics.keys() if isinstance(topics, dict)
                         else topics)
            if TOPIC_ALL in names:
                names = None

        def current_locked() -> int:
            if names is None:
                return self._latest_index
            return max((self._topic_index.get(t, 0) for t in names),
                       default=0)

        deadline = time.monotonic() + max(0.0, timeout)
        with self._pub_cond:
            cur = current_locked()
            if cur > index:
                return cur
            metrics.incr("nomad.event.waiters_parked")
            while cur <= index:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._pub_cond.wait(remaining)
                cur = current_locked()
            return cur


def make_event(topic: str, etype: str, index: int, payload) -> Event:
    """Derive key/namespace/filter-keys from the state object
    (ref nomad/state/events.go eventFromChange)."""
    key, ns, fkeys = "", "", []
    if isinstance(payload, tuple):          # (ns, job_id) deregister form
        ns, key = payload
        payload = {"ID": key, "Namespace": ns}
    else:
        key = getattr(payload, "id", "") or ""
        ns = getattr(payload, "namespace", "") or ""
        job_id = getattr(payload, "job_id", "") or ""
        node_id = getattr(payload, "node_id", "") or ""
        if job_id:
            fkeys.append(job_id)
        if node_id:
            fkeys.append(node_id)
    return Event(topic=topic, type=etype, key=key, namespace=ns,
                 filter_keys=fkeys, index=index, payload=payload)
