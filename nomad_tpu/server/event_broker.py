"""Event broker: at-most-once pub/sub of state-change events
(ref nomad/stream/event_broker.go:30 EventBroker, event_buffer.go).

A bounded ring buffer of event batches with per-subscriber cursors: slow
subscribers that fall off the tail are closed and must re-subscribe (the
reference's ErrSubscriptionClosed contract). Feeds `/v1/event/stream`.

Events originate from the state store's `event_sinks` (our analog of
nomad/state/events.go eventsFromChanges).
"""
from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

from ..metrics import metrics

ALL_KEYS = "*"

TOPIC_JOB = "Job"
TOPIC_EVAL = "Evaluation"
TOPIC_ALLOC = "Allocation"
TOPIC_DEPLOYMENT = "Deployment"
TOPIC_NODE = "Node"
TOPIC_ALL = "*"


class SubscriptionClosedError(Exception):
    """The subscriber fell behind the ring buffer and was dropped
    (ref stream/subscription.go ErrSubscriptionClosed)."""


@dataclass
class Event:
    topic: str
    type: str
    key: str = ""
    namespace: str = ""
    filter_keys: list[str] = field(default_factory=list)
    index: int = 0
    payload: Any = None

    def to_api(self) -> dict:
        from ..api_codec import to_api
        wrapper_key = {
            TOPIC_JOB: "Job", TOPIC_EVAL: "Evaluation",
            TOPIC_ALLOC: "Allocation", TOPIC_DEPLOYMENT: "Deployment",
            TOPIC_NODE: "Node",
        }.get(self.topic, "Payload")
        payload = self.payload
        if payload is not None and not isinstance(payload, (dict, str, int,
                                                            float, list)):
            payload = to_api(payload)
        return {"Topic": self.topic, "Type": self.type, "Key": self.key,
                "Namespace": self.namespace, "FilterKeys": self.filter_keys,
                "Index": self.index, "Payload": {wrapper_key: payload}}


def _match(req_topics: dict[str, list[str]], ev: Event) -> bool:
    for topic in (ev.topic, TOPIC_ALL):
        keys = req_topics.get(topic)
        if keys is None:
            continue
        for k in keys:
            if k == ALL_KEYS or k == ev.key or k in ev.filter_keys:
                return True
    return False


class Subscription:
    def __init__(self, broker: "EventBroker", topics: dict[str, list[str]],
                 namespace: str = ""):
        self._broker = broker
        self.topics = topics or {TOPIC_ALL: [ALL_KEYS]}
        self.namespace = namespace
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._closed = False

    def _offer(self, index: int, events: list[Event]) -> None:
        wanted = [e for e in events if _match(self.topics, e)
                  and (not self.namespace or not e.namespace
                       or e.namespace == self.namespace)]
        dropped = False
        with self._cond:
            if self._closed:
                return
            if wanted:
                self._queue.append((index, wanted))
                if len(self._queue) > self._broker.max_pending:
                    self._closed = True   # slow consumer: drop
                    self._queue.clear()
                    dropped = True
            self._cond.notify_all()
        if dropped:
            # the per-subscriber cap firing must be visible (ISSUE 8
            # satellite): a fleet of watchers silently re-subscribing in
            # a drop loop looks exactly like healthy streaming otherwise
            metrics.incr("nomad.event.subscriber_dropped")
            self._broker._unsubscribe(self)

    def next_events(self, timeout: Optional[float] = None
                    ) -> Optional[tuple[int, list[Event]]]:
        """Block until the next matching batch; None on timeout. Raises
        SubscriptionClosedError if dropped for falling behind."""
        with self._cond:
            if not self._queue and not self._closed:
                self._cond.wait(timeout)
            if self._closed:
                raise SubscriptionClosedError()
            if self._queue:
                return self._queue.popleft()
            return None

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._broker._unsubscribe(self)


class EventBroker:
    """ref nomad/stream/event_broker.go:30; buffer_size mirrors
    EventBufferSize (default 100 batches)."""

    def __init__(self, buffer_size: int = 256, max_pending: int = 512):
        # RLock: subscribe() replays into the sub while holding the lock; an
        # overflowing replay re-enters via _unsubscribe
        self._lock = threading.RLock()
        self._buffer: deque[tuple[int, list[Event]]] = deque(
            maxlen=buffer_size)
        self._subs: list[Subscription] = []
        self.max_pending = max_pending
        self._latest_index = 0

    # ------------------------------------------------------------- publish

    def publish(self, index: int, events: list[Event]) -> None:
        """ref event_broker.go:95 Publish"""
        if not events:
            return
        with self._lock:
            self._latest_index = max(self._latest_index, index)
            # the ring bound lives in __init__: deque(maxlen=buffer_size)
            # nomadlint: disable=QUEUE001 — deque maxlen ring (above)
            self._buffer.append((index, events))
            subs = list(self._subs)
        for sub in subs:
            sub._offer(index, events)

    def sink(self, topic: str, etype: str, index: int, payload) -> None:
        """Adapter matching StateStore.event_sinks signature."""
        self.publish(index, [make_event(topic, etype, index, payload)])

    # ----------------------------------------------------------- subscribe

    def subscribe(self, topics: Optional[dict[str, list[str]]] = None,
                  index: int = 0, namespace: str = "") -> Subscription:
        """ref event_broker.go:138 Subscribe — replays buffered batches with
        index > `index` before going live."""
        sub = Subscription(self, topics or {}, namespace)
        with self._lock:
            # replay while holding the broker lock, BEFORE the sub becomes
            # visible to publish(), so batch order stays index-monotonic
            if index:
                for i, evs in self._buffer:
                    if i > index:
                        sub._offer(i, evs)
            self._subs.append(sub)
        return sub

    def _unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def latest_index(self) -> int:
        with self._lock:
            return self._latest_index


def make_event(topic: str, etype: str, index: int, payload) -> Event:
    """Derive key/namespace/filter-keys from the state object
    (ref nomad/state/events.go eventFromChange)."""
    key, ns, fkeys = "", "", []
    if isinstance(payload, tuple):          # (ns, job_id) deregister form
        ns, key = payload
        payload = {"ID": key, "Namespace": ns}
    else:
        key = getattr(payload, "id", "") or ""
        ns = getattr(payload, "namespace", "") or ""
        job_id = getattr(payload, "job_id", "") or ""
        node_id = getattr(payload, "node_id", "") or ""
        if job_id:
            fkeys.append(job_id)
        if node_id:
            fkeys.append(node_id)
    return Event(topic=topic, type=etype, key=key, namespace=ns,
                 filter_keys=fkeys, index=index, payload=payload)
