"""Volume watcher: CSI claim reaping (ref nomad/volumewatcher/
volumes_watcher.go + volume_watcher.go — the leader-only loop that releases
claims held by terminal allocations so volumes become schedulable again).

The reference drives controller/node Unpublish RPCs through the claimed
node's plugin; our detach path is the claim state machine only (the client's
csimanager unmounts on its side when the alloc stops), so reaping advances
claims straight to ready-to-free.
"""
from __future__ import annotations

import threading

from ..structs.csi import CSIVolumeClaim, CLAIM_STATE_READY_TO_FREE


class VolumeWatcher:
    """ref volumeswatcher.Watcher"""

    def __init__(self, server, interval: float = 5.0):
        self.server = server
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="volume-watcher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # join before a leadership re-acquire clears the stop event, else
        # the old loop never observes it and two watchers run
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.reap_once()
            except Exception as e:      # noqa: BLE001
                self.server.logger(f"volumewatcher: {e!r}")

    def reap_once(self) -> int:
        """Release claims whose alloc is gone or terminal (ref
        volume_watcher.go volumeReapImpl)."""
        from .fsm import CSI_VOLUME_CLAIM
        state = self.server.state
        released = 0
        for vol in state.iter_csi_volumes():
            for alloc_id in list(vol.read_claims) + list(vol.write_claims):
                alloc = state.alloc_by_id(alloc_id)
                if alloc is not None and not alloc.terminal_status():
                    continue
                self.server.raft.apply(CSI_VOLUME_CLAIM, {
                    "namespace": vol.namespace, "volume_id": vol.id,
                    "claim": CSIVolumeClaim(
                        alloc_id=alloc_id,
                        state=CLAIM_STATE_READY_TO_FREE)})
                released += 1
        return released
