"""Volume watcher: the CSI claim-detach state machine (ref
nomad/volumewatcher/volumes_watcher.go + volume_watcher.go — the
leader-only loop that releases claims held by terminal allocations so
volumes become schedulable again).

Claim lifecycle (ref volume_watcher.go volumeReapImpl):

    taken --node unpublish--> node-detached
          --controller unpublish (if plugin requires one)-->
    controller-detached --> ready-to-free (claim dropped)

The reference pushes Node/ControllerUnpublish RPCs to clients; here the
detach RPCs ride the PULL model the rest of the client does (alloc watch,
heartbeats): this watcher gates claim-state transitions, the claimed
node's csimanager polls CSIVolume.NodeDetachPending / a controller node
polls ControllerDetachPending, performs the plugin RPC, and confirms via
a claim update. A claim reaches ready-to-free ONLY after the plugin
round succeeds — except when the claimed node is gone from state (its
plugin can never answer; the reference force-detaches there too).
"""
from __future__ import annotations

import threading

from ..structs.csi import (
    CSIVolumeClaim, CLAIM_STATE_CONTROLLER_DETACHED,
    CLAIM_STATE_NODE_DETACHED, CLAIM_STATE_READY_TO_FREE,
    CLAIM_STATE_TAKEN,
)
from .lifecycle import LoopHandle


class VolumeWatcher:
    """ref volumeswatcher.Watcher"""

    def __init__(self, server, interval: float = 5.0):
        self.server = server
        self.interval = interval
        # explicit start/join lifecycle state (server/lifecycle.py):
        # see deployment_watcher — the handle owns the stop event
        self._loop = LoopHandle()
        self._stop = self._loop.stop_event

    def start(self) -> None:
        self._loop.start(self._run, "volume-watcher")

    def stop(self) -> None:
        self._loop.stop(timeout=self.interval + 5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.reap_once()
            except Exception as e:      # noqa: BLE001
                self.server.logger(f"volumewatcher: {e!r}")

    def reap_once(self) -> int:
        """Advance past-claims through the detach machine (ref
        volume_watcher.go volumeReapImpl). Returns transitions applied."""
        from .fsm import CSI_VOLUME_CLAIM
        state = self.server.state
        moved = 0
        for vol in state.iter_csi_volumes():
            plug = state.csi_plugin_by_id(vol.plugin_id)
            needs_controller = bool(plug and plug.controller_required)
            claims = list(vol.read_claims.values()) + \
                list(vol.write_claims.values())
            for claim in claims:
                alloc = state.alloc_by_id(claim.alloc_id)
                if alloc is not None and not alloc.terminal_status():
                    continue            # live claim: nothing to reap
                cur = claim.state
                # chain the transitions this pass can decide WITHOUT a
                # client confirmation (forced node round, controller-less
                # free) so a reapable claim frees in one pass
                while True:
                    nxt = None
                    if cur == CLAIM_STATE_TAKEN:
                        node = state.node_by_id(claim.node_id)
                        if node is None or node.status == "down":
                            # the node left the cluster (or is down with
                            # its alloc already terminal): its plugin
                            # can't confirm — force past the node round,
                            # like the reference's no-node past-claim path
                            nxt = CLAIM_STATE_NODE_DETACHED
                        # else: wait for the node csimanager's
                        # NodeDetachPending pull; recoverable on failure
                    elif cur == CLAIM_STATE_NODE_DETACHED:
                        if not needs_controller:
                            nxt = CLAIM_STATE_READY_TO_FREE
                        # else: wait for a controller node's confirmation
                    elif cur == CLAIM_STATE_CONTROLLER_DETACHED:
                        nxt = CLAIM_STATE_READY_TO_FREE
                    if nxt is None:
                        break
                    self.server.raft.apply(CSI_VOLUME_CLAIM, {
                        "namespace": vol.namespace, "volume_id": vol.id,
                        "claim": CSIVolumeClaim(
                            alloc_id=claim.alloc_id, node_id=claim.node_id,
                            state=nxt)})
                    moved += 1
                    if nxt == CLAIM_STATE_READY_TO_FREE:
                        break
                    cur = nxt
        return moved
