"""Overload protection: ingress admission control + pressure-driven
brownout (ISSUE 8 tentpole).

The solve path is CvxCluster-fast, so under burst traffic the control
plane's QUEUES are the failure mode, not the solver: an unbounded eval
backlog grows memory without bound and spends device time on evals whose
callers gave up long ago. This module is the leader's shared overload
brain; the eval broker's depth cap / priority shed and the worker's
deadline drop (eval_broker.py, worker.py) consume its knobs, and its
pressure state drives the brownout levers.

Three layers, goodput over throughput (docs/OVERLOAD.md):

  * **Admission** — per-endpoint-class token buckets (`write` / `read` /
    `blocking`) at the HTTP and RPC front doors. Over-rate callers get
    429 + Retry-After (HTTP) or a `RateLimitError` envelope (RPC)
    *before* any state is touched; the Python client honors Retry-After
    with jittered backoff (api/client.py). Rates are hot-reloadable
    `SchedulerConfiguration` fields; 0 (the default) disables a class.

  * **Pressure** — broker backlog + plan-queue depth fold into one
    ok -> saturated -> shedding state, exported via /v1/status and
    `nomad.pressure.state` (0/1/2). Transitions are counted
    (`nomad.pressure.transitions`), so the bench can assert a burst
    entered and LEFT the shedding state (recovery, not collapse).

  * **Brownout** — under pressure the micro-batcher's coalescing window
    WIDENS (amortize dispatch: more lanes per device round trip), trace
    head-sampling downshifts (error retention unaffected — trace.py),
    and blocking queries get shortened hold timeouts so parked
    connections return capacity. All three revert on recovery.

The controller is per-Server (pressure is leader-scoped state) but its
brownout levers hit the process-wide singletons (solver/microbatch.py,
obs/trace.py) — only a LEADER's controller ticks, and `reset()` on
revoke restores every lever, so a demoted server cannot keep a stale
brownout pinned.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..metrics import metrics

# pressure states, in escalation order
PRESSURE_OK = "ok"
PRESSURE_SATURATED = "saturated"
PRESSURE_SHEDDING = "shedding"
_PRESSURE_LEVEL = {PRESSURE_OK: 0, PRESSURE_SATURATED: 1,
                   PRESSURE_SHEDDING: 2}

# endpoint classes the admission buckets key on
CLASS_WRITE = "write"
CLASS_READ = "read"
CLASS_BLOCKING = "blocking"

# brownout levers (constants, not knobs: the operator tunes WHEN pressure
# engages via SchedulerConfiguration; what brownout does is a contract)
WINDOW_BOOST_SATURATED = 2.0     # micro-batch window multiplier
WINDOW_BOOST_SHEDDING = 4.0
TRACE_FACTOR_SATURATED = 0.5     # head-sampling multiplier (errors kept)
TRACE_FACTOR_SHEDDING = 0.1
BLOCKING_CAP_OK_S = 30.0         # blocking-query hold ceiling per state
BLOCKING_CAP_SATURATED_S = 5.0
BLOCKING_CAP_SHEDDING_S = 1.0

# hysteresis: saturation engages at `pressure_saturated_frac` of the
# broker cap and releases below half of that, so a backlog hovering at
# the threshold doesn't flap the brownout levers every tick
_RELEASE_FRAC = 0.5


class RateLimitExceeded(Exception):
    """An ingress admission bucket rejected the request. `retry_after_s`
    is the earliest time a retry can succeed (the HTTP layer surfaces it
    as a Retry-After header, the RPC layer in the error envelope)."""

    def __init__(self, endpoint_class: str, retry_after_s: float):
        super().__init__(
            f"rate limit exceeded for {endpoint_class} requests; "
            f"retry after {retry_after_s:.2f}s")
        self.endpoint_class = endpoint_class
        self.retry_after_s = retry_after_s


class TokenBucket:
    """Classic token bucket: `rate` tokens/s, capacity `rate * burst_s`.
    Thread-safe; `rate <= 0` admits everything (the disabled default)."""

    def __init__(self, rate: float = 0.0, burst_s: float = 2.0):
        self._lock = threading.Lock()
        self._rate = 0.0
        self._capacity = 0.0
        self._tokens = 0.0
        self._t_last = time.monotonic()
        self.configure(rate, burst_s)

    def configure(self, rate: float, burst_s: float = 2.0) -> None:
        """Hot-reload. A rate change refills to the new capacity rather
        than carrying debt across a reconfigure — an operator RAISING the
        limit mid-incident expects immediate relief."""
        rate = max(0.0, float(rate))
        burst_s = max(0.1, float(burst_s))
        with self._lock:
            if rate != self._rate or rate * burst_s != self._capacity:
                self._rate = rate
                self._capacity = rate * burst_s
                self._tokens = self._capacity
                self._t_last = time.monotonic()

    @property
    def rate(self) -> float:
        return self._rate

    def take(self, n: float = 1.0) -> float:
        """Take `n` tokens. Returns 0.0 when admitted, else the seconds
        until `n` tokens will be available (the Retry-After hint)."""
        with self._lock:
            if self._rate <= 0.0:
                return 0.0
            now = time.monotonic()
            self._tokens = min(self._capacity,
                               self._tokens + (now - self._t_last)
                               * self._rate)
            self._t_last = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return max(0.001, (n - self._tokens) / self._rate)


class OverloadController:
    """One per server. `broker_depth_fn` / `plan_depth_fn` report the
    live queue backlogs; `config_fn` returns the current (raft-
    replicated, hot-reloadable) SchedulerConfiguration. The bench wires
    its own callables — no Server required."""

    def __init__(self, broker_depth_fn: Callable[[], int] = None,
                 plan_depth_fn: Callable[[], int] = None,
                 config_fn: Callable[[], object] = None):
        self._broker_depth_fn = broker_depth_fn or (lambda: 0)
        self._plan_depth_fn = plan_depth_fn or (lambda: 0)
        self._config_fn = config_fn or (lambda: None)
        self._lock = threading.Lock()
        self._state = PRESSURE_OK
        self.transitions = 0
        self.max_broker_depth = 0
        self._buckets = {CLASS_WRITE: TokenBucket(),
                         CLASS_READ: TokenBucket(),
                         CLASS_BLOCKING: TokenBucket()}

    # ------------------------------------------------------------ admission

    def _cfg(self, name: str, default):
        cfg = self._config_fn()
        try:
            value = getattr(cfg, name, default)
            return type(default)(value)
        except (TypeError, ValueError):
            return default

    def admit(self, endpoint_class: str) -> None:
        """Raise RateLimitExceeded when the class bucket is dry. Buckets
        re-read the hot-reloadable rates on every call (attribute reads
        on the in-memory config; configure() is a no-op when unchanged)."""
        bucket = self._buckets.get(endpoint_class)
        if bucket is None:
            return
        burst = self._cfg("ingress_burst_s", 2.0)
        bucket.configure(
            self._cfg(f"ingress_{endpoint_class}_rate", 0.0), burst)
        wait = bucket.take()
        if wait > 0.0:
            metrics.incr("nomad.ingress.rejected")
            # the three literal endpoint classes (write/read/blocking)
            # nomadlint: disable=OBS001 — bounded per-class breakdown
            metrics.incr(f"nomad.ingress.rejected.{endpoint_class}")
            raise RateLimitExceeded(endpoint_class, wait)

    @staticmethod
    def classify_http(method: str, query: dict) -> str:
        """Endpoint class of an HTTP request: blocking queries are GETs
        carrying a NONZERO ?index= (the handler's blocking() only parks
        then — `?index=0` is a plain read and must bill the read
        bucket); other GETs read; everything else writes (PUT/POST/
        DELETE all reach the raft log)."""
        if method == "GET":
            try:
                if int(query.get("index", 0) or 0) > 0:
                    return CLASS_BLOCKING
            except (TypeError, ValueError):
                pass
            return CLASS_READ
        return CLASS_WRITE

    # ------------------------------------------------------------- pressure

    def tick(self) -> str:
        """Recompute pressure from the live depths and apply/release the
        brownout levers. Called from the leader housekeeping loop (1s
        cadence) and via the broker's `on_overflow` hook whenever the
        depth cap trips (so a burst faster than the tick still engages
        brownout). Returns the current state."""
        broker_depth = int(self._broker_depth_fn())
        plan_depth = int(self._plan_depth_fn())
        cap = self._cfg("broker_depth_cap", 0)
        state = PRESSURE_OK
        if cap > 0:
            depth = broker_depth + plan_depth
            sat = max(1.0, cap * self._cfg("pressure_saturated_frac", 0.5))
            with self._lock:
                prev = self._state
            if depth >= cap:
                state = PRESSURE_SHEDDING
            elif depth >= sat:
                state = PRESSURE_SATURATED
            elif prev != PRESSURE_OK and depth >= sat * _RELEASE_FRAC:
                # hysteresis: stay one level engaged until well clear
                state = PRESSURE_SATURATED
        with self._lock:
            if broker_depth > self.max_broker_depth:
                self.max_broker_depth = broker_depth
            changed = state != self._state
            self._state = state
            if changed:
                self.transitions += 1
        metrics.set_gauge("nomad.pressure.state", _PRESSURE_LEVEL[state])
        metrics.set_gauge("nomad.broker.depth", broker_depth)
        if changed:
            metrics.incr("nomad.pressure.transitions")
            self._apply_brownout(state)
        return state

    def state(self) -> str:
        with self._lock:
            return self._state

    def _apply_brownout(self, state: str) -> None:
        """Point the process-wide levers at the new state. Lazy imports:
        a stripped solver-less build skips the micro-batcher lever."""
        from ..obs import trace
        if state == PRESSURE_SHEDDING:
            boost, factor = WINDOW_BOOST_SHEDDING, TRACE_FACTOR_SHEDDING
        elif state == PRESSURE_SATURATED:
            boost, factor = WINDOW_BOOST_SATURATED, TRACE_FACTOR_SATURATED
        else:
            boost, factor = 1.0, 1.0
        trace.set_pressure_factor(factor)
        try:
            from ..solver import microbatch
            microbatch.set_pressure_boost(boost)
        except ImportError:
            pass

    def blocking_cap_s(self) -> float:
        """The blocking-query hold ceiling for the CURRENT pressure state
        (agent/http.py clamps ?wait= with this): parked long-polls are
        the cheapest capacity to reclaim under load."""
        state = self.state()
        if state == PRESSURE_SHEDDING:
            return BLOCKING_CAP_SHEDDING_S
        if state == PRESSURE_SATURATED:
            return BLOCKING_CAP_SATURATED_S
        return BLOCKING_CAP_OK_S

    def reset(self) -> None:
        """Back to follower shape: levers released, state ok. Counters
        are kept — transitions/max-depth are evidence, not state."""
        with self._lock:
            changed = self._state != PRESSURE_OK
            self._state = PRESSURE_OK
        if changed:
            self._apply_brownout(PRESSURE_OK)
        metrics.set_gauge("nomad.pressure.state", 0)

    # -------------------------------------------------------------- surface

    def snapshot(self) -> dict:
        """The /v1/status pressure block."""
        with self._lock:
            state = self._state
            transitions = self.transitions
            max_depth = self.max_broker_depth
        return {
            "State": state,
            "BrokerDepth": int(self._broker_depth_fn()),
            "PlanQueueDepth": int(self._plan_depth_fn()),
            "BrokerDepthCap": self._cfg("broker_depth_cap", 0),
            "MaxBrokerDepth": max_depth,
            "Transitions": transitions,
            "BlockingCapS": self.blocking_cap_s(),
            "Limits": {c: self._cfg(f"ingress_{c}_rate", 0.0)
                       for c in (CLASS_WRITE, CLASS_READ, CLASS_BLOCKING)},
        }
