"""Control plane (ref nomad/): replicated log + FSM, eval broker, serial
plan applier, scheduler workers, heartbeats, periodic dispatch, core GC,
blocked evals."""
from .eval_broker import EvalBroker  # noqa: F401
from .blocked_evals import BlockedEvals  # noqa: F401
from .fsm import NomadFSM, RaftLog, PlanApplyRequest  # noqa: F401
from .plan_apply import Planner, PlanQueue  # noqa: F401
from .worker import Worker  # noqa: F401
from .heartbeat import HeartbeatTimers, create_node_evals  # noqa: F401
from .periodic import PeriodicDispatch, cron_next  # noqa: F401
from .core_sched import CoreScheduler  # noqa: F401
from .deployment_watcher import DeploymentWatcher  # noqa: F401
from .drainer import NodeDrainer  # noqa: F401
from .server import Server  # noqa: F401
