"""Node drainer (ref nomad/drainer/drainer.go:130 NodeDrainer, run:225,
watch_jobs.go, watch_nodes.go, drain_heap.go): migrates allocations off
draining nodes in batches bounded by each group's migrate strategy, force
drains at the deadline, and lifts the drain when the node is empty.
"""
from __future__ import annotations

import threading
from typing import Optional

from .. import chrono
from ..structs import (
    DesiredTransition, Evaluation, EVAL_STATUS_PENDING, JOB_TYPE_SYSTEM,
    TRIGGER_NODE_DRAIN,
)
from .fsm import ALLOC_UPDATE_DESIRED_TRANSITION, NODE_UPDATE_DRAIN
from .lifecycle import LoopHandle


class NodeDrainer:
    def __init__(self, server, poll_interval: float = 0.25,
                 clock: Optional[chrono.Clock] = None):
        self.server = server
        self.poll_interval = poll_interval
        # deadline DECISIONS ride the clock (ISSUE 8 satellite): a
        # ManualClock test advances virtual time past the force deadline
        # instead of sleeping it out; the poll cadence stays real
        self.clock = clock or chrono.REAL
        # explicit start/join lifecycle state (server/lifecycle.py):
        # see deployment_watcher — the handle owns the stop event
        self._loop = LoopHandle()
        self._stop = self._loop.stop_event

    def start(self) -> None:
        self._loop.start(self._run, "node-drainer")

    def stop(self) -> None:
        self._loop.stop(timeout=5.0)

    def track_node(self, node_id: str) -> None:
        """Hook for UpdateDrain; polling picks it up on the next tick."""

    def _run(self) -> None:
        """ref drainer.go:225 run"""
        while not self._stop.wait(self.poll_interval):
            try:
                for node in self.server.state.iter_nodes():
                    if node.drain_strategy is not None:
                        self._drain_node(node)
            except Exception as e:      # noqa: BLE001
                self.server.logger(f"drainer: {e!r}")

    def _drain_node(self, node) -> None:
        state = self.server.state
        strategy = node.drain_strategy
        force = (strategy.deadline_sec < 0 or
                 (strategy.force_deadline_unix and
                  self.clock.time() >= strategy.force_deadline_unix))

        remaining = []
        for alloc in state.allocs_by_node(node.id):
            if alloc.terminal_status():
                continue
            job = alloc.job
            if job is not None and job.type == JOB_TYPE_SYSTEM:
                # system allocs drain last (or never when ignored)
                if strategy.ignore_system_jobs:
                    continue
                remaining.append((alloc, True))
                continue
            remaining.append((alloc, False))

        non_system = [(a, s) for a, s in remaining if not s]
        system = [(a, s) for a, s in remaining if s]

        if not remaining:
            # empty: lift the drain, keep the node ineligible
            # (ref drainer.go handleMigratedAllocs -> NodeDrainComplete)
            self.server.raft.apply(NODE_UPDATE_DRAIN, {
                "node_id": node.id, "drain": None, "mark_eligible": False})
            return

        # system allocs stop once everything else has migrated
        batch = []
        if non_system:
            batch = self._select_batch(non_system, force)
        elif system and not strategy.ignore_system_jobs:
            batch = [a for a, _ in system]

        to_migrate = [a for a in batch
                      if not a.desired_transition.should_migrate()]
        if not to_migrate:
            return
        transitions = {a.id: DesiredTransition(migrate=True)
                       for a in to_migrate}
        evals = []
        seen_jobs = set()
        for a in to_migrate:
            key = (a.namespace, a.job_id)
            if key in seen_jobs:
                continue
            seen_jobs.add(key)
            job = a.job
            evals.append(Evaluation(
                namespace=a.namespace,
                priority=job.priority if job else 50,
                type=job.type if job else "service",
                triggered_by=TRIGGER_NODE_DRAIN, job_id=a.job_id,
                node_id=node.id, status=EVAL_STATUS_PENDING))
        self.server.raft.apply(ALLOC_UPDATE_DESIRED_TRANSITION, {
            "transitions": transitions, "evals": evals})

    def _select_batch(self, allocs, force: bool) -> list:
        """Respect each group's migrate max_parallel: only migrate more when
        enough replacements are healthy (ref drainer/watch_jobs.go)."""
        if force:
            return [a for a, _ in allocs]
        state = self.server.state
        out = []
        by_group: dict[tuple, list] = {}
        for a, _ in allocs:
            by_group.setdefault((a.namespace, a.job_id, a.task_group),
                                []).append(a)
        for (ns, job_id, tg_name), group_allocs in by_group.items():
            job = state.job_by_id(ns, job_id)
            tg = job.lookup_task_group(tg_name) if job else None
            max_parallel = tg.migrate.max_parallel if tg and tg.migrate else 1
            # in-flight migrations for this group (anywhere in the cluster)
            migrating = sum(
                1 for other in state.allocs_by_job(ns, job_id)
                if other.task_group == tg_name
                and not other.terminal_status()
                and other.desired_transition.should_migrate())
            allowed = max(0, max_parallel - migrating)
            waiting = [a for a in group_allocs
                       if not a.desired_transition.should_migrate()]
            out.extend(waiting[:allowed])
        return out
