"""ACL endpoints + token resolution (ref nomad/acl.go ResolveToken,
nomad/acl_endpoint.go ACL.* RPCs, bootstrap in acl_endpoint.go:53).

`ACLResolver` caches parsed policy objects and merged ACLs keyed by the
token's policy set — the reference's lru caches on the server
(nomad/server.go aclCache)."""
from __future__ import annotations

import threading
from typing import Optional

from ..acl import ACL, MANAGEMENT_ACL, PolicyParseError, parse_policy
from ..structs import (
    ACLPolicy, ACLToken, TOKEN_TYPE_CLIENT, TOKEN_TYPE_MANAGEMENT,
    anonymous_token,
)
from .fsm import (
    ACL_POLICY_DELETE, ACL_POLICY_UPSERT, ACL_TOKEN_BOOTSTRAP,
    ACL_TOKEN_DELETE, ACL_TOKEN_UPSERT,
)


class ACLDisabledError(Exception):
    pass


class PermissionDeniedError(Exception):
    pass


class TokenNotFoundError(Exception):
    pass


ANONYMOUS_POLICY_NAME = "anonymous"


class ACLEndpoint:
    """Mixed into / owned by the Server: self.server is the Server."""

    def __init__(self, server, enabled: bool = False):
        self.server = server
        self.enabled = enabled
        self._lock = threading.Lock()
        self._bootstrap_lock = threading.Lock()
        self._policy_cache: dict[tuple[str, int], object] = {}
        self._acl_cache: dict[tuple, ACL] = {}

    # ---------------------------------------------------------- resolution

    def resolve_token(self, secret_id: str) -> ACL:
        """ref nomad/acl.go ResolveToken. Empty secret = anonymous."""
        if not self.enabled:
            return MANAGEMENT_ACL
        state = self.server.state
        if not secret_id:
            # ref structs AnonymousACLToken: client token carrying only the
            # operator-defined "anonymous" policy; deny-all if unset
            token = anonymous_token()
            policies = [p for p in (state.acl_policy_by_name(n)
                                    for n in token.policies) if p]
            return self._acl_for_policies(policies)
        token: Optional[ACLToken] = state.acl_token_by_secret(secret_id)
        if token is None:
            raise TokenNotFoundError("ACL token not found")
        if token.is_management():
            return MANAGEMENT_ACL
        policies = []
        for name in token.policies:
            pol = state.acl_policy_by_name(name)
            if pol is not None:
                policies.append(pol)
        return self._acl_for_policies(policies)

    def _acl_for_policies(self, policies: list[ACLPolicy]) -> ACL:
        key = tuple(sorted((p.name, p.modify_index) for p in policies))
        with self._lock:
            cached = self._acl_cache.get(key)
            if cached is not None:
                return cached
        parsed = [self._parse_cached(p) for p in policies]
        acl = ACL(policies=parsed)
        with self._lock:
            if len(self._acl_cache) > 512:
                self._acl_cache.clear()
            self._acl_cache[key] = acl
        return acl

    def _parse_cached(self, pol: ACLPolicy):
        key = (pol.name, pol.modify_index)
        with self._lock:
            cached = self._policy_cache.get(key)
            if cached is not None:
                return cached
        parsed = parse_policy(pol.rules)
        with self._lock:
            if len(self._policy_cache) > 512:
                self._policy_cache.clear()
            self._policy_cache[key] = parsed
        return parsed

    # ------------------------------------------------------------ bootstrap

    def bootstrap(self) -> ACLToken:
        """One-shot management token creation (ref acl_endpoint.go:53
        Bootstrap — fails once any token exists)."""
        if not self.enabled:
            raise ACLDisabledError("ACL support disabled")
        with self._bootstrap_lock:     # serialize check-then-mint
            if self.server.state.iter_acl_tokens():
                raise PermissionDeniedError(
                    "ACL bootstrap already done")
            token = ACLToken.new(name="Bootstrap Token",
                                 type=TOKEN_TYPE_MANAGEMENT, global_=True)
            # one-shot cold path; the lock exists to serialize exactly
            # this apply against racers — nomadlint: disable=LOCK003
            self.server.raft.apply(ACL_TOKEN_BOOTSTRAP, {"tokens": [token]})
        return token

    def _require_enabled(self) -> None:
        """All ACL CRUD is rejected while ACLs are off (ref
        nomad/acl_endpoint.go: every method starts with aclDisabled check)
        — otherwise anonymous callers could persist tokens that later
        poison bootstrap."""
        if not self.enabled:
            raise ACLDisabledError("ACL support disabled")

    # -------------------------------------------------------------- policy

    def upsert_policies(self, policies: list[ACLPolicy]) -> int:
        self._require_enabled()
        for pol in policies:
            if not pol.name:
                raise ValueError("policy name required")
            try:
                parse_policy(pol.rules)
            except PolicyParseError as e:
                raise ValueError(f"invalid policy rules: {e}")
        return self.server.raft.apply(ACL_POLICY_UPSERT,
                                      {"policies": policies})

    def delete_policies(self, names: list[str]) -> int:
        self._require_enabled()
        return self.server.raft.apply(ACL_POLICY_DELETE, {"names": names})

    # -------------------------------------------------------------- tokens

    def upsert_tokens(self, tokens: list[ACLToken]) -> list[ACLToken]:
        self._require_enabled()
        out = []
        for tok in tokens:
            if tok.type not in (TOKEN_TYPE_CLIENT, TOKEN_TYPE_MANAGEMENT):
                raise ValueError(f"invalid token type {tok.type!r}")
            if tok.type == TOKEN_TYPE_CLIENT and not tok.policies:
                raise ValueError("client token requires policies")
            if tok.type == TOKEN_TYPE_MANAGEMENT and tok.policies:
                raise ValueError("management token cannot have policies")
            if not tok.accessor_id:
                fresh = ACLToken.new(name=tok.name, type=tok.type,
                                     policies=tok.policies,
                                     global_=tok.global_)
                out.append(fresh)
            else:
                existing = self.server.state.acl_token_by_accessor(
                    tok.accessor_id)
                if existing is None:
                    raise ValueError(
                        f"token {tok.accessor_id!r} does not exist")
                upd = existing.copy()
                upd.name = tok.name or existing.name
                upd.policies = tok.policies
                upd.type = tok.type
                out.append(upd)
        self.server.raft.apply(ACL_TOKEN_UPSERT, {"tokens": out})
        return out

    def delete_tokens(self, accessor_ids: list[str]) -> int:
        self._require_enabled()
        return self.server.raft.apply(ACL_TOKEN_DELETE,
                                      {"accessor_ids": accessor_ids})
