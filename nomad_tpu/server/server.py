"""Server: the control plane assembly (ref nomad/server.go:293 NewServer)
plus the RPC endpoint surface (ref nomad/job_endpoint.go, node_endpoint.go,
eval_endpoint.go, alloc_endpoint.go, deployment_endpoint.go,
operator_endpoint.go — one method family per resource).

Single-node for now: leadership is established immediately on start
(ref nomad/leader.go:224 establishLeadership) — broker/planner/periodic/
blocked-evals enabled, pending evals restored from state.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .. import faults
from ..metrics import metrics, record_swallowed_error
from ..obs import trace
from ..rpc.codec import NotLeaderError
from ..state import StateStore
from ..structs import (
    Allocation, DrainStrategy, Evaluation, Job, Node, SchedulerConfiguration,
    ALLOC_CLIENT_FAILED, ALLOC_CLIENT_COMPLETE, ALLOC_DESIRED_STOP,
    EVAL_STATUS_CANCELLED, EVAL_STATUS_PENDING,
    JOB_TYPE_BATCH, JOB_TYPE_SERVICE, JOB_TYPE_SYSTEM,
    JOB_TYPE_SYSBATCH, NODE_SCHED_ELIGIBLE, NODE_SCHED_INELIGIBLE,
    NODE_STATUS_DOWN, NODE_STATUS_READY,
    TRIGGER_ALLOC_STOP, TRIGGER_JOB_DEREGISTER, TRIGGER_JOB_REGISTER,
    TRIGGER_NODE_DRAIN, TRIGGER_NODE_UPDATE, TRIGGER_RETRY_FAILED_ALLOC,
    CORE_JOB_EVAL_GC, CORE_JOB_JOB_GC, CORE_JOB_NODE_GC,
    CORE_JOB_DEPLOYMENT_GC, CORE_JOB_FORCE_GC, JOB_TYPE_CORE,
    new_id,
)
from .blocked_evals import BlockedEvals
from .core_sched import CoreScheduler
from .deployment_watcher import DeploymentWatcher
from .drainer import NodeDrainer
from .eval_broker import EvalBroker
from .fsm import (
    ALLOC_CLIENT_UPDATE, ALLOC_UPDATE_DESIRED_TRANSITION, EVAL_UPDATE,
    JOB_DEREGISTER, JOB_REGISTER, NODE_REGISTER, NODE_UPDATE_DRAIN,
    NODE_UPDATE_ELIGIBILITY, NODE_UPDATE_STATUS, NomadFSM, RaftLog,
    SCHEDULER_CONFIG,
)
from .heartbeat import FlapDamper, HeartbeatTimers, create_node_evals
from .periodic import PeriodicDispatch
from .plan_apply import LEADERSHIP_LOST, Planner
from .worker import Worker

def _warmup_floor() -> int:
    """The node-count floor below which establish-time device work (AOT
    warmup, tensor reseed, standby twin feed) is skipped. Reads the
    solver's authoritative backend.WARMUP_MIN_NODES when that module is
    already loaded — WITHOUT importing it (the gates run before deciding
    whether jax should be touched at all) — else the same default."""
    import sys
    backend = sys.modules.get("nomad_tpu.solver.backend")
    return getattr(backend, "WARMUP_MIN_NODES", 256)


def _device_work_gate(env_var: str, node_count: int) -> bool:
    """ONE predicate for every establish/standby device-work gate
    (backend.warmup applies the same semantics to NOMAD_AOT_WARMUP):
    env "0" disables, "1" forces below the floor, default floor-gates."""
    import os
    mode = os.environ.get(env_var, "")
    if mode == "0":
        return False
    return mode == "1" or node_count >= _warmup_floor()


# workers do NOT consume "_failed": the leader reaps the dead-letter queue
# (ref nomad/leader.go:782 reapFailedEvaluations)
SCHEDULER_TYPES = [JOB_TYPE_SERVICE, JOB_TYPE_BATCH, JOB_TYPE_SYSTEM,
                   JOB_TYPE_SYSBATCH, JOB_TYPE_CORE]

# network RPC surface (ref nomad/server.go:1146 setupRpcServer):
# method name -> (Server attr, leader_only). Writes go through Raft and are
# leader-only; reads run on any server against its replicated state.
RPC_ENDPOINTS = {
    "Node.Register": ("node_register", True),
    "Node.UpdateStatus": ("node_update_status", True),
    "Node.UpdateDrain": ("node_update_drain", True),
    "Node.UpdateEligibility": ("node_update_eligibility", True),
    "Node.GetClientAllocs": ("node_get_client_allocs", False),
    "Node.UpdateAlloc": ("node_update_allocs", True),
    "Alloc.GetAlloc": ("alloc_get", False),
    "Alloc.Stop": ("alloc_stop", True),
    "Node.GetHTTPAddr": ("node_get_http_addr", False),
    "Job.Register": ("job_register", True),
    "Job.Deregister": ("job_deregister", True),
    "Job.Plan": ("job_plan", True),
    "Job.Dispatch": ("job_dispatch", True),
    "Job.Evaluate": ("job_evaluate", True),
    "Job.Scale": ("job_scale", True),
    "Job.ScaleStatus": ("job_scale_status", False),
    "Job.Revert": ("job_revert", True),
    "Job.Stable": ("job_stable", True),
    "Scaling.ListPolicies": ("scaling_policies_list", False),
    "Scaling.GetPolicy": ("scaling_policy_get", False),
    "Search.PrefixSearch": ("search_prefix", False),
    "Search.FuzzySearch": ("search_fuzzy", False),
    "CSIVolume.Register": ("csi_volume_register", True),
    "CSIVolume.Deregister": ("csi_volume_deregister", True),
    "CSIVolume.Claim": ("csi_volume_claim", True),
    "CSIVolume.List": ("csi_volume_list", False),
    "CSIVolume.Get": ("csi_volume_get", False),
    "CSIVolume.NodeDetachPending": ("csi_node_detach_pending", False),
    "CSIVolume.ControllerDetachPending":
        ("csi_controller_detach_pending", False),
    "CSIPlugin.List": ("csi_plugin_list", False),
    "CSIPlugin.Get": ("csi_plugin_get", False),
    "Service.Register": ("service_register", True),
    "Service.Deregister": ("service_deregister", True),
    "Service.List": ("service_list", False),
    "Service.Instances": ("service_instances", False),
    "Intention.Upsert": ("intention_upsert", True),
    "Intention.Delete": ("intention_delete", True),
    "Intention.List": ("intention_list", False),
    "Intention.Allowed": ("intention_allowed", False),
    "Vault.DeriveToken": ("vault_derive_token", True),
    "Node.DeriveSIToken": ("derive_si_token", True),
    "Vault.RenewToken": ("vault_renew_token", True),
    "Vault.RevokeToken": ("vault_revoke_token", True),
    # leader-only: the in-memory dev backend lives in one process; routing
    # every secret op at the leader keeps reads/renews consistent (a real
    # Vault backend is an external shared service, unaffected)
    "Vault.Read": ("secret_read", True),
    "Eval.Dequeue": ("eval_dequeue", True),
    "Eval.Ack": ("eval_ack", True),
    "Eval.Nack": ("eval_nack", True),
    "Deployment.List": ("deployment_list", False),
    "Deployment.Promote": ("deployment_promote", True),
    "Deployment.Fail": ("deployment_fail", True),
    "Deployment.Pause": ("deployment_pause", True),
    "Operator.SchedulerGetConfiguration": ("get_scheduler_configuration",
                                           False),
    "Operator.SchedulerSetConfiguration": ("set_scheduler_configuration",
                                           True),
    "Operator.SnapshotSave": ("snapshot_save", False),
    "Operator.SnapshotRestore": ("snapshot_restore", True),
    "Operator.RaftGetConfiguration": ("operator_raft_configuration", False),
    "Operator.RaftRemovePeer": ("operator_raft_remove_peer", True),
    "Operator.RaftAddPeer": ("operator_raft_add_peer", True),
    "Operator.AutopilotGetConfiguration": ("operator_autopilot_get_config",
                                           False),
    "Operator.AutopilotSetConfiguration": ("operator_autopilot_set_config",
                                           True),
    "Operator.ServerHealth": ("operator_server_health", False),
    "ACL.ListPolicies": ("acl_list_policies_wire", False),
    "ACL.ListTokens": ("acl_list_tokens_wire", False),
    "Status.Members": ("members", False),
    "Status.Regions": ("regions", False),
    # read plane (ISSUE 16): list/get served from any server's replicated
    # store; `stale=False` on a follower raises NotLeaderError so the
    # client's transparent redirect keeps default reads leader-consistent
    "Read.List": ("read_list", False),
    "Read.Get": ("read_get", False),
}


class Server:
    def __init__(self, num_workers: int = 2, logger: Optional[Callable] = None,
                 gc_interval: float = 300.0, acl_enabled: bool = False,
                 region: str = "global", authoritative_region: str = "",
                 name: str = "", secrets_file: str = ""):
        self.logger = logger or (lambda msg: None)
        self.region = region
        # cross-region ACL replication source (ref nomad/leader.go:1288);
        # empty or equal to `region` means this region is authoritative
        self.authoritative_region = authoritative_region or region
        # management token of the authoritative region used by the ACL
        # replication loop (ref config acl.replication_token)
        self.replication_token = ""
        # serf-style bootstrap_expect: >1 means wait until gossip sees
        # that many same-region servers, then all bootstrap with the
        # same config (ref nomad/serf.go maybeBootstrap)
        self.bootstrap_expect = 1
        self.name = name or f"server-{new_id()[:8]}"
        self.fsm = NomadFSM()
        self.state: StateStore = self.fsm.state
        # event-sink failures in _emit log through the agent (counted in
        # nomad.swallowed_errors either way)
        self.state.logger = self.logger
        self.raft = RaftLog(self.fsm)
        # the broker reads its overload knobs (depth cap, enqueue TTL)
        # straight from the raft-replicated scheduler config — the same
        # hot-reload path every other runtime knob rides (ISSUE 8)
        self.eval_broker = EvalBroker(
            config_fn=self.state.get_scheduler_config)
        from .event_broker import EventBroker
        # backpressure rung 1 (opt-in at construction: the server's
        # consumers watch latest STATE per key, not an exhaustive event
        # log) rides the overload pressure state: bursty fan-out
        # coalesces to latest-state delivery before anything drops
        # (self.overload is assigned below; the lambda defers)
        self.event_broker = EventBroker(
            coalesce_after=64,
            pressure_fn=lambda: self.overload.state())
        self.state.event_sinks.append(self.event_broker.sink)
        # batched twin (ISSUE 20): a whole FSM apply-batch window's
        # events land in the broker as ONE publish
        self.state.event_batch_sinks.append(self.event_broker.sink_batch)
        self.blocked_evals = BlockedEvals(self._enqueue_unblocked)
        from .acl_endpoint import ACLEndpoint
        self.acl = ACLEndpoint(self, enabled=acl_enabled)
        self.planner = Planner(self.raft, self.state)
        # overload brain (ISSUE 8): ingress admission buckets + the
        # ok->saturated->shedding pressure state driving the brownout
        # levers; ticked by the leader loop, reset on revoke
        from .overload import OverloadController
        self.overload = OverloadController(
            broker_depth_fn=self.eval_broker.depth,
            plan_depth_fn=self.planner.queue.depth,
            config_fn=self.state.get_scheduler_config)
        # a cap trip re-computes pressure immediately — a sub-second
        # burst must engage brownout before the next 1s leader tick
        self.eval_broker.on_overflow = self.overload.tick
        self.periodic = PeriodicDispatch(self)
        # RPC write-dedup (ISSUE 18): one per process, shared by the TCP
        # and virtual dispatchers (wired in rpc_listen*) — retried writes
        # whose reply was lost return the original committed result
        from ..rpc.dedup import WriteDedup
        self.write_dedup = WriteDedup(self.state)
        self.heartbeats = HeartbeatTimers(self)
        # flap damper (ISSUE 10): holds down/up-cycling nodes ineligible
        # with exponential re-admit backoff so reconnect churn cannot
        # oscillate the solver's eligibility mask; shares the heartbeat
        # clock so ManualClock tests drive both from one timeline
        # no explicit clock: the damper tracks heartbeats.clock
        # dynamically, so swapping in a ManualClock moves both
        self.flap_damper = FlapDamper(self)
        self.core_scheduler = CoreScheduler(self)
        self.deployment_watcher = DeploymentWatcher(self)
        self.drainer = NodeDrainer(self)
        from .volume_watcher import VolumeWatcher
        self.volume_watcher = VolumeWatcher(self)
        if secrets_file:
            from ..integrations.secrets import FileSecretsProvider
            self.secrets = FileSecretsProvider(secrets_file)
        else:
            from ..integrations.secrets import InMemorySecretsProvider
            self.secrets = InMemorySecretsProvider()
        self.scheduler_types = SCHEDULER_TYPES
        self.workers = [Worker(self, i) for i in range(num_workers)]
        self.gc_interval = gc_interval
        self._leader_stop = threading.Event()
        self._leader_thread: Optional[threading.Thread] = None
        self.is_leader = False
        self._shutdown_ev = threading.Event()
        # recovery-barrier per-step timings of the most recent successful
        # _establish_leadership (ISSUE 6; the bench failover probe reads
        # these for failover_detail), and the raft term that
        # establishment ran for — a re-election at a NEWER term must
        # re-run the barrier even when the old reign's revoke callback
        # lost the thread race (is_leader still True)
        self._establish_timings: dict[str, float] = {}
        self._established_term = -1
        # serializes _establish_leadership: the election callback and the
        # deferred establish-retry thread must never run the barrier (and
        # double-start every leader subsystem) concurrently
        self._establish_lock = threading.Lock()
        # network RPC (optional; wired by rpc_listen). leader_rpc_addr is
        # maintained by the consensus layer for follower->leader forwarding.
        self.rpc_server = None
        self.leader_rpc_addr = ""
        # multi-server consensus (optional; wired by enable_raft). When set,
        # leadership is election-driven instead of immediate-on-start.
        self.raft_node = None
        # gossip membership + federation (optional; wired by gossip_listen):
        # same-region members drive Raft peer management, cross-region
        # members populate the federation routing table (ref serf.go)
        self.gossip = None
        # region -> {server name -> rpc_addr} of ALIVE foreign servers
        self.region_servers: dict[str, dict[str, str]] = {}

        # the FSM tells the leader about new evals (ref fsm.go:760)
        self.fsm.on_eval_update.append(self._on_eval_update)
        # followers advance the passive solver tensor twin as replicated
        # plan results land (ISSUE 6 warm standby)
        self.fsm.on_plan_apply.append(self._feed_standby_twin)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        import os

        self._shutdown_ev.clear()
        from ..runtime import enable_compile_cache, tune_gc
        tune_gc()          # allocation-heavy plans vs default GC cadence
        if os.environ.get("NOMAD_COMPILE_CACHE"):
            # persistent XLA compile cache BEFORE the first jit: a warm
            # restart then replays serialized executables instead of
            # recompiling the solver grid as placement blackout
            enable_compile_cache()
        if self.raft_node is None:
            self._establish_leadership()
        else:
            self.raft_node.start()
            # warm standby (ISSUE 6): a follower pre-warms the AOT
            # compile grid in the background so a later promotion pays
            # ~0 compile instead of a cold-XLA placement blackout
            threading.Thread(target=self._standby_warmup_loop, daemon=True,
                             name="standby-warmup").start()
        for w in self.workers:
            w.start()

    def enable_raft(self, node_id: str, peers: dict[str, str],
                    data_dir: str = None, **raft_kw) -> None:
        """Switch from the single-node log to elected multi-server consensus
        (ref nomad/server.go:1221 setupRaft + leader.go:56 monitorLeadership).
        Must be called after rpc_listen() and before start()."""
        if self.rpc_server is None:
            raise RuntimeError("enable_raft requires rpc_listen() first")
        from .raft import RaftNode
        peers = dict(peers)
        peers.setdefault(node_id, self.rpc_server.addr)
        self.raft_node = RaftNode(self.fsm, node_id, self.rpc_server, peers,
                                  data_dir=data_dir, logger=self.logger,
                                  **raft_kw)
        self.raft = self.raft_node
        self.planner.raft = self.raft_node
        self.raft_node.on_leadership_change = self._on_leadership_change
        self.rpc_server.leadership_fn = self._raft_leadership

    # RPC methods the admission buckets never touch: raft consensus
    # traffic (rate-limiting replication/votes under load would turn an
    # overload into an outage) and the node heartbeat path (starving
    # heartbeats mass-invalidates the fleet exactly when it is busiest).
    _ADMISSION_EXEMPT_PREFIXES = ("Raft.",)
    _ADMISSION_EXEMPT = {"Node.UpdateStatus", "Status.Members",
                         "Status.Regions"}
    # long-hold methods billed against the blocking-query bucket
    _ADMISSION_BLOCKING = {"Node.GetClientAllocs", "Eval.Dequeue"}

    def _rpc_admission(self, method: str, leader_only: bool) -> None:
        """RpcDispatcher admission hook (ISSUE 8): classify the method
        (write / read / blocking) and probe the matching token bucket;
        raises overload.RateLimitExceeded for the dispatcher to envelope
        as a RateLimitError with the retry hint."""
        if method in self._ADMISSION_EXEMPT or \
                method.startswith(self._ADMISSION_EXEMPT_PREFIXES):
            return
        from .overload import CLASS_BLOCKING, CLASS_READ, CLASS_WRITE
        if method in self._ADMISSION_BLOCKING:
            cls = CLASS_BLOCKING
        elif leader_only:
            cls = CLASS_WRITE
        else:
            cls = CLASS_READ
        self.overload.admit(cls)

    def _raft_leadership(self) -> tuple[bool, str]:
        is_leader, leader_addr = self.raft_node.leadership()
        self.leader_rpc_addr = leader_addr
        return is_leader, leader_addr

    def _on_leadership_change(self, is_leader: bool) -> None:
        """ref nomad/leader.go:56 monitorLeadership"""
        if is_leader:
            self.logger("server: leadership acquired")
            self._establish_leadership()
        else:
            self.logger("server: leadership lost")
            self._revoke_leadership()

    def rpc_listen(self, bind: str = "127.0.0.1", port: int = 0,
                   key: bytes = None, tls=None) -> str:
        """Start serving the network RPC surface (ref nomad/rpc.go
        listen/handleConn). Returns the bound "host:port" address."""
        from ..rpc.server import DEFAULT_KEY, RpcServer
        self.rpc_server = RpcServer(bind=bind, port=port,
                                    key=key or DEFAULT_KEY,
                                    logger=self.logger, tls=tls)
        self.rpc_server.register_endpoints(self, RPC_ENDPOINTS)
        self.rpc_server.leadership_fn = \
            lambda: (self.is_leader, self.leader_rpc_addr)
        self.rpc_server.admission_fn = self._rpc_admission
        self.rpc_server.dedup = self.write_dedup
        self.rpc_server.start()
        return self.rpc_server.addr

    def rpc_listen_virtual(self, network, name: str,
                           key: bytes = None) -> str:
        """Attach this server to an in-memory `rpc.virtual.VirtualNetwork`
        instead of a TCP listener — the deterministic multi-server test
        transport (ISSUE 6). Interface-identical to rpc_listen():
        enable_raft()/forwarding ride on top unchanged, and the network's
        partition/drop/delay/crash controls apply to every hop."""
        from ..rpc.server import DEFAULT_KEY
        self.rpc_server = network.server(name, key=key or DEFAULT_KEY,
                                         logger=self.logger)
        self.rpc_server.register_endpoints(self, RPC_ENDPOINTS)
        self.rpc_server.leadership_fn = \
            lambda: (self.is_leader, self.leader_rpc_addr)
        self.rpc_server.admission_fn = self._rpc_admission
        self.rpc_server.dedup = self.write_dedup
        self.rpc_server.start()
        return self.rpc_server.addr

    @property
    def rpc_addr(self) -> str:
        return self.rpc_server.addr if self.rpc_server is not None else ""

    # ------------------------------------------------- gossip / federation

    def gossip_listen(self, bind: str = "127.0.0.1", port: int = 0,
                      key: bytes = None) -> str:
        """Join the gossip fabric (ref nomad/server.go:1388 setupSerf).
        Requires rpc_listen() first — the rpc addr rides in our tags so
        discovered servers are immediately routable."""
        if self.rpc_server is None:
            raise RuntimeError("gossip_listen requires rpc_listen() first")
        from ..rpc.server import DEFAULT_KEY
        from .gossip import Gossip
        tags = {"role": "nomad-server", "region": self.region,
                "rpc_addr": self.rpc_server.addr, "id": self.name}
        if getattr(self, "http_advertise", ""):
            # lets followers proxy HTTP writes to the leader's HTTP
            # surface (ref serf tags port/addr feeding rpc forwarding)
            tags["http_addr"] = self.http_advertise
        self.gossip = Gossip(
            name=self.name, bind=bind, port=port,
            key=key or DEFAULT_KEY, logger=self.logger,
            tags=tags,
            on_join=self._on_gossip_join,
            on_leave=self._on_gossip_leave,
            on_fail=self._on_gossip_fail)
        self.gossip.start()
        self.rpc_server.region = self.region
        self.rpc_server.region_servers_fn = self._region_servers_snapshot
        return self.gossip.addr

    def gossip_join(self, seeds: list[str]) -> int:
        """ref serf.Join via -join/retry_join"""
        return self.gossip.join(seeds)

    def _region_servers_snapshot(self) -> dict[str, dict[str, str]]:
        return {r: dict(servers) for r, servers in
                self.region_servers.items()}

    def members(self) -> list[dict]:
        """ref nomad/serf.go Members for `server members` / agent API"""
        return self.gossip.members_snapshot() if self.gossip else []

    def leader_http_addr(self) -> str:
        """The current raft leader's advertised HTTP address (via its
        gossip tags), or "" when unknown — the follower HTTP forwarding
        target (ref nomad/rpc.go forward; our proxy rides HTTP)."""
        if self.raft_node is None or self.gossip is None:
            return ""
        _, leader_rpc = self.raft_node.leadership()
        leader_id = self.raft_node.leader_id
        for m in self.members():
            t = m.get("tags", {})
            if t.get("role") != "nomad-server":
                continue
            if t.get("id") == leader_id or \
                    (leader_rpc and t.get("rpc_addr") == leader_rpc):
                return t.get("http_addr", "")
        return ""

    def regions(self) -> list[str]:
        out = {self.region} | set(self.region_servers)
        return sorted(out)

    def _maybe_bootstrap(self) -> None:
        """ref nomad/serf.go maybeBootstrap: once bootstrap_expect
        same-region servers are visible, every one of them bootstraps
        raft with the identical (sorted) initial configuration."""
        if self.raft_node is None or self.bootstrap_expect <= 1 or \
                self.raft_node.bootstrap:
            return
        if self.gossip is None:
            return
        servers = {}
        for m in self.gossip.alive_members():
            t = m.tags
            if t.get("role") == "nomad-server" and \
                    t.get("region", "") == self.region and \
                    t.get("id") and t.get("rpc_addr"):
                servers[t["id"]] = t["rpc_addr"]
        if len(servers) >= self.bootstrap_expect:
            peers = dict(sorted(servers.items()))
            if self.raft_node.bootstrap_with(peers):
                self.logger(
                    f"server: bootstrap_expect={self.bootstrap_expect} "
                    f"reached; bootstrapping with {sorted(peers)}")

    def _on_gossip_join(self, member) -> None:
        """ref nomad/serf.go:98 nodeJoin (+ maybeBootstrap)"""
        tags = member.tags
        if tags.get("role") != "nomad-server":
            return
        self._maybe_bootstrap()
        region = tags.get("region", "")
        if region != self.region:
            self.region_servers.setdefault(region, {})[member.name] = \
                tags.get("rpc_addr", "")
            self.logger(f"server: federated server {member.name} "
                        f"joined region {region}")
            return
        # same region: NEW servers are adopted as NON-VOTERS (leader-
        # driven serf-join -> raft-autopilot AddNonvoter) and promoted by
        # the autopilot tick after stabilizing. A member flapping
        # SUSPECT->ALIVE re-fires this join and must KEEP its voter
        # status — demoting an established voter would silently shrink
        # the commit quorum.
        if self.raft_node is not None and self.is_leader and \
                tags.get("id") and tags.get("rpc_addr"):
            pid = tags["id"]
            voter = (pid in self.raft_node.peers and
                     pid not in self.raft_node.nonvoters)
            try:
                self.raft_node.add_peer(pid, tags["rpc_addr"], voter=voter)
                self.logger(f"server: added raft peer {pid}"
                            f"{'' if voter else ' (non-voter)'}")
            except Exception as e:      # noqa: BLE001
                self.logger(f"server: add_peer {pid} failed: {e}")

    def _on_gossip_fail(self, member) -> None:
        """ref nomad/serf.go:163 nodeFailed + autopilot dead-server
        cleanup: the leader drops failed same-region servers from Raft."""
        tags = member.tags
        if tags.get("role") != "nomad-server":
            return
        region = tags.get("region", "")
        if region != self.region:
            self.region_servers.get(region, {}).pop(member.name, None)
            return
        if self.raft_node is not None and self.is_leader and tags.get("id"):
            try:
                self.raft_node.remove_peer(tags["id"])
                self.logger(f"server: removed failed peer {tags['id']}")
            except Exception as e:      # noqa: BLE001
                self.logger(f"server: remove_peer failed: {e}")

    def _on_gossip_leave(self, member) -> None:
        self._on_gossip_fail(member)

    def _reconcile_gossip_peers(self) -> None:
        """Leader tick: converge raft membership onto the gossip view of
        same-region servers (ref nomad/leader.go reconcileMember). Event
        callbacks handle the common case instantly; this heals joins that
        raced leadership establishment and any missed UDP event."""
        if self.gossip is None or self.raft_node is None or \
                not self.is_leader:
            return
        alive = {}
        for m in self.gossip.alive_members():
            tags = m.tags
            if tags.get("role") == "nomad-server" and \
                    tags.get("region", "") == self.region and \
                    tags.get("id") and tags.get("rpc_addr"):
                alive[tags["id"]] = tags["rpc_addr"]
        peers = dict(self.raft_node.peers)
        for pid, addr in alive.items():
            if peers.get(pid) != addr:
                # keep the existing voter/non-voter status: reconcile must
                # not promote ahead of the autopilot stabilization window
                voter = pid in peers and pid not in self.raft_node.nonvoters
                self.raft_node.add_peer(pid, addr, voter=voter)
                self.logger(f"server: reconciled raft peer {pid}")

    # --------------------------------------------------- ACL replication

    def _require_replication_token(self, secret: str) -> None:
        """Token listings carry SecretIDs: with ACLs on, only a management
        token may read them (ref acl_endpoint.go: replication endpoints
        require the replication/management token)."""
        if not self.acl.enabled:
            return
        acl = self.acl.resolve_token(secret)
        if not acl.is_management():
            from .acl_endpoint import PermissionDeniedError
            raise PermissionDeniedError(
                "ACL replication requires a management token")

    def acl_list_policies_wire(self, secret: str = "") -> list[dict]:
        """Replication source endpoint (ref acl_endpoint.go ListPolicies
        with the replication token)."""
        from ..api_codec import to_api
        self._require_replication_token(secret)
        return [to_api(p) for p in self.state.iter_acl_policies()]

    def acl_list_tokens_wire(self, global_only: bool = True,
                             secret: str = "") -> list[dict]:
        from ..api_codec import to_api
        self._require_replication_token(secret)
        return [to_api(t) for t in self.state.iter_acl_tokens()
                if t.global_ or not global_only]

    def _acl_replication_loop(self, interval: float = 1.0) -> None:
        """Mirror policies + global tokens from the authoritative region.
        Pull-based full-set diff per cycle — the reference diffs by
        modify_index; at control-plane ACL cardinality the full set is a
        single small RPC either way."""
        from ..api_codec import from_api
        from ..structs.acl_structs import ACLPolicy, ACLToken
        from .fsm import (
            ACL_POLICY_DELETE, ACL_POLICY_UPSERT, ACL_TOKEN_DELETE,
            ACL_TOKEN_UPSERT,
        )
        while not self._leader_stop.wait(interval):
            servers = self.region_servers.get(self.authoritative_region, {})
            addrs = [a for a in servers.values() if a]
            if not addrs:
                continue
            try:
                from ..rpc.client import RpcClient
                with RpcClient(addrs, key=self.rpc_server.key,
                               tls=self.rpc_server.tls) as cli:
                    pol_wire = cli.call("ACL.ListPolicies",
                                        secret=self.replication_token)
                    tok_wire = cli.call("ACL.ListTokens", True,
                                        secret=self.replication_token)
            except Exception as e:      # noqa: BLE001
                self.logger(f"server: acl replication fetch failed: {e}")
                continue
            try:
                want_pols = {p.name: p for p in
                             (from_api(ACLPolicy, w) for w in pol_wire)}
                want_toks = {t.accessor_id: t for t in
                             (from_api(ACLToken, w) for w in tok_wire)}
                have_pols = {p.name: p for p in
                             self.state.iter_acl_policies()}
                have_toks = {t.accessor_id: t for t in
                             self.state.iter_acl_tokens() if t.global_}
                up_p = [p for n, p in want_pols.items()
                        if n not in have_pols or
                        have_pols[n].rules != p.rules or
                        have_pols[n].description != p.description]
                del_p = [n for n in have_pols if n not in want_pols]
                up_t = [t for a, t in want_toks.items()
                        if a not in have_toks or
                        have_toks[a].secret_id != t.secret_id or
                        have_toks[a].policies != t.policies or
                        have_toks[a].type != t.type]
                del_t = [a for a in have_toks if a not in want_toks]
                if up_p:
                    self.raft.apply(ACL_POLICY_UPSERT, {"policies": up_p})
                if del_p:
                    self.raft.apply(ACL_POLICY_DELETE, {"names": del_p})
                if up_t:
                    self.raft.apply(ACL_TOKEN_UPSERT, {"tokens": up_t})
                if del_t:
                    self.raft.apply(ACL_TOKEN_DELETE,
                                    {"accessor_ids": del_t})
            except Exception as e:      # noqa: BLE001
                self.logger(f"server: acl replication apply failed: {e}")

    def shutdown(self) -> None:
        self._shutdown_ev.set()
        if self.gossip is not None:
            # broadcast LEFT and close the UDP socket — a shut-down
            # server must not keep acking probes and looking alive
            try:
                self.gossip.leave()
            except Exception:           # noqa: BLE001
                self.gossip.shutdown()
        if self.raft_node is not None:
            self.raft_node.shutdown()
        if self.rpc_server is not None:
            self.rpc_server.shutdown()
        self._leader_stop.set()
        for w in self.workers:
            w.stop()
        self.deployment_watcher.stop()
        self.drainer.stop()
        self.planner.stop()
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.periodic.set_enabled(False)
        self.heartbeats.stop()
        for w in self.workers:
            w.join(1.0)

    def _revoke_leadership(self) -> None:
        """ref nomad/leader.go revokeLeadership: disable every leader-only
        subsystem; scheduling resumes wherever the new leader is. Pendings
        failed here carry the distinct leadership-lost disposition
        (counted in `nomad.plan.leadership_lost`, ISSUE 6 satellite)."""
        with self._establish_lock:
            was_leader = self.is_leader
            root = trace.begin_root("leader.revoke", was_leader=was_leader)
            try:
                with trace.use(root):
                    self._revoke_leadership_locked()
            except BaseException as e:
                root.end("error", error=repr(e)[:200])
                raise
            root.end("ok" if was_leader and not self.is_leader else "stale")

    def _revoke_leadership_locked(self) -> None:
        if not self.is_leader:
            return
        if self._still_leader() and self.raft_node is not None and \
                self.raft_node.current_term == self._established_term:
            # stale revoke: the deposal this callback reports has already
            # been superseded by a re-election whose establishment RAN
            # (the term matches what the barrier last established;
            # callback threads are unordered). Tearing down now would
            # leave a live leader with every subsystem disabled.
            self.logger("server: ignoring stale leadership revoke")
            return
        self._teardown_leadership_locked(LEADERSHIP_LOST)

    def _teardown_leadership_locked(self, reason: str) -> None:
        self.is_leader = False
        self._leader_stop.set()
        # join before a re-election can clear the stop event, else the old
        # loop never observes it and two leader loops run after re-elect
        if self._leader_thread is not None:
            self._leader_thread.join(timeout=5.0)
            self._leader_thread = None
        self._disable_leader_subsystems(reason=reason)

    def _disable_leader_subsystems(self, reason: str) -> None:
        """Shared by revoke and by a recovery-barrier unwind: every
        leader-only subsystem back to the follower state."""
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.planner.stop(reason=reason)
        self.periodic.set_enabled(False)
        self.heartbeats.stop()
        self.deployment_watcher.stop()
        self.drainer.stop()
        self.volume_watcher.stop()
        # release the brownout levers: a demoted server must not keep a
        # stale pressure state pinned on the process-wide batcher/tracer
        self.overload.reset()
        # a follower must never re-admit flap-held nodes; the new
        # leader adopts the holds from replicated state at establish
        self.flap_damper.reset()

    def _still_leader(self) -> bool:
        """Is the CONSENSUS layer still calling us leader (independent of
        whether establishment finished)? A shutdown aborts establishment
        the same way a lost election does."""
        if self._shutdown_ev.is_set():
            return False
        return self.raft_node is None or self.raft_node.is_leader()

    # ----------------------------------------- post-election recovery barrier

    # ordered recovery-barrier steps (ISSUE 6; docs/FAILOVER.md). Each is
    # fault-injectable at `leader.establish.<name>` and metered as
    # `nomad.leader.establish.<name>`:
    #   barrier        raft Barrier: FSM reflects every prior-term commit
    #   plan_queue     fail stale plan pendings; start the serial applier
    #   state_cache    reseed/advance the device-resident tensor twins
    #                  (warm when the standby feed tracked this store)
    #   heartbeats     re-arm EVERY node TTL with the failover grace
    #                  window, then start the reaper
    #   watchers       periodic dispatch, deployment/drain/volume watchers
    #   broker_restore re-enqueue pending evals + re-track periodic jobs
    #                  from replicated state (runs after is_leader flips:
    #                  concurrent commits dedup through the broker)

    def _establish_leadership(self) -> None:
        """ref nomad/leader.go:224, hardened into an ordered, metered,
        fault-injectable recovery barrier (ISSUE 6). Establish and
        revoke serialize on one lock, so the election callback, the
        deferred retry thread, and a racing revoke can never interleave
        subsystem starts/stops; a second establish is an idempotent
        no-op (`is_leader` already set), and a stale revoke is detected
        inside (`_still_leader`)."""
        with self._establish_lock:
            # the recovery barrier is a ROOT trace (ISSUE 7): every
            # `leader.establish.<step>` below nests under it, and a
            # failover promotion shows up in /v1/traces next to the
            # evals it unblocked
            root = trace.begin_root(
                "leader.establish",
                term=self.raft_node.current_term
                if self.raft_node is not None else 0)
            try:
                with trace.use(root):
                    # establishment is exclusive by design; the lock
                    # serializes it — nomadlint: disable=LOCK003
                    self._establish_leadership_locked()
            except BaseException as e:
                root.end("error", error=repr(e)[:200])
                raise
            root.end("ok" if self.is_leader else "unwound",
                     is_leader=self.is_leader)

    def _establish_leadership_locked(self) -> None:
        term = self.raft_node.current_term \
            if self.raft_node is not None else 0
        if self.is_leader:
            if term == self._established_term:
                return          # idempotent re-entry, same reign
            # re-elected at a NEWER term while the old reign's subsystems
            # are still up (the deposal's revoke callback lost the thread
            # race to this election callback): tear down first so the new
            # term runs the FULL barrier — skipping it would skip the FSM
            # catch-up of an interim leader's commits and the heartbeat
            # re-arm, the two failure shapes the barrier exists for
            self.logger(f"server: re-elected at term {term} before the "
                        f"term-{self._established_term} revoke ran; "
                        f"re-running the recovery barrier")
            self._teardown_leadership_locked(LEADERSHIP_LOST)
        t_enter = time.perf_counter()
        timings: dict[str, float] = {}
        # Barrier FIRST (ref leader.go:236 raft.Barrier): everything below
        # reads the FSM, which must reflect every entry committed under
        # previous terms — otherwise a just-elected leader can re-enqueue
        # an already-planned eval and double-place it. A slow apply (big
        # replay) RETRIES rather than returning: bailing out would leave a
        # live raft leader with every leader subsystem permanently
        # disabled. Only losing leadership ends the wait.
        t0 = time.perf_counter()
        wait_barrier = getattr(self.raft, "wait_barrier", None)
        while wait_barrier is not None:
            if not self._still_leader():
                self.logger("server: leadership lost during barrier")
                return
            try:
                faults.fire("leader.establish.barrier")
                wait_barrier(timeout=30.0)
                break
            except TimeoutError as e:
                self.logger(f"server: leadership barrier slow, "
                            f"retrying: {e!r}")
            except NotLeaderError as e:     # lost lead mid-wait: done
                self.logger(f"server: leadership barrier failed: {e!r}")
                return
            except Exception as e:      # noqa: BLE001 — transient (incl.
                # injected barrier faults): retry while still leader —
                # returning here would leave a live raft leader with
                # every leader subsystem permanently disabled
                self.logger(f"server: leadership barrier error, "
                            f"retrying: {e!r}")
                # barrier retry backoff; nothing else contends this
                # lock while establishing — nomadlint: disable=LOCK003
                time.sleep(0.05)  # nomadlint: disable=RPC001 — in-process raft barrier retry on the real-time establish path, not a client RPC
        timings["barrier"] = time.perf_counter() - t0
        metrics.add_sample("nomad.leader.establish.barrier",
                           timings["barrier"])
        trace.record_span("leader.establish.barrier", None, t0)

        # step retries back off under the establish lock on purpose
        # (revoke waits for a clean stop) — nomadlint: disable=LOCK003
        ok = (self._establish_step("plan_queue", self._step_plan_queue,
                                   timings)
              and self._establish_step("state_cache", self._step_state_cache,
                                       timings)
              and self._establish_step("heartbeats", self._step_heartbeats,
                                       timings)
              and self._establish_step("watchers", self._step_watchers,
                                       timings))
        if ok:
            # the flip happens BEFORE broker_restore: evals committed while
            # the restore iterates reach the broker via _on_eval_update,
            # evals committed before it are found in state, and the overlap
            # dedups on eval id / job key inside the broker
            self.is_leader = True
            ok = self._establish_step("broker_restore",
                                      self._step_broker_restore, timings)
        if not ok:
            # leadership lost mid-barrier or a step exhausted its retries:
            # unwind to the follower state — a half-established leader
            # must not run — and, if consensus still names us leader,
            # retry the WHOLE barrier shortly (steps are idempotent)
            self.is_leader = False
            self._disable_leader_subsystems(reason=LEADERSHIP_LOST)
            if self._still_leader():
                metrics.incr("nomad.leader.establish_retry")
                threading.Thread(target=self._reestablish_later,
                                 daemon=True,
                                 name="establish-retry").start()
            return
        if not self._still_leader() or not self.is_leader:
            # a revoke raced the tail of the barrier (is_leader may
            # already be False): leave everything in the follower state
            # instead of starting a leader loop for a non-leader
            self.is_leader = False
            self._disable_leader_subsystems(reason=LEADERSHIP_LOST)
            return
        total = time.perf_counter() - t_enter
        timings["total"] = total
        self._establish_timings = timings
        # record the reign as of COMPLETION: if the term moved mid-barrier
        # (we lost and re-won), the queued establish callback for the new
        # term sees the mismatch and re-runs the barrier
        self._established_term = self.raft_node.current_term \
            if self.raft_node is not None else 0
        metrics.add_sample("nomad.leader.establish_s", total)
        metrics.set_gauge("nomad.leader.failover_s", total)
        self._leader_stop.clear()
        self._leader_thread = threading.Thread(
            target=self._leader_loop, daemon=True, name="leader-loop")
        self._leader_thread.start()
        # pre-compile the solver's (kernel, tier, bucket) grid for this
        # cluster size in the background (ISSUE 4): a freshly-promoted
        # leader should not pay cold XLA compiles as placement blackout
        # on its first real eval. Below backend.WARMUP_MIN_NODES this is
        # a no-op (unit-test servers must not compile the world). A
        # warm-standby follower already compiled the grid — warmup then
        # costs one cache probe.
        threading.Thread(target=self._solver_warmup, daemon=True,
                         name="solver-warmup").start()
        # non-authoritative region leaders mirror ACL state from the
        # authoritative region (ref nomad/leader.go:1288
        # replicateACLPolicies / :1368 replicateACLTokens)
        if self.region != self.authoritative_region:
            threading.Thread(target=self._acl_replication_loop, daemon=True,
                             name="acl-replication").start()

    def _establish_step(self, name: str, fn: Callable,
                        timings: dict) -> bool:
        """One barrier step: fault site, bounded retries, per-step timing.
        False aborts establishment (leadership gone or retries spent)."""
        for attempt in range(5):
            if not self._still_leader():
                self.logger(f"server: leadership lost during establish "
                            f"step {name}")
                return False
            t0 = time.perf_counter()
            try:
                with trace.span(f"leader.establish.{name}",
                                attempt=attempt):
                    faults.fire(f"leader.establish.{name}")
                    fn()
            except Exception as e:      # noqa: BLE001 — retried, bounded
                self.logger(f"server: establish step {name} failed "
                            f"(attempt {attempt + 1}/5): {e!r}")
                time.sleep(0.05 * (attempt + 1))
                continue
            timings[name] = time.perf_counter() - t0
            # `name` ranges over the five literal barrier step names
            # nomadlint: disable=OBS001 — bounded step-name set
            metrics.add_sample(f"nomad.leader.establish.{name}",
                               timings[name])
            return True
        metrics.incr("nomad.leader.establish_step_failed")
        self.logger(f"server: establish step {name} exhausted retries")
        return False

    def _step_plan_queue(self) -> None:
        """Stale pendings from a previous reign (or from a drain that
        raced the revoke) fail with the leadership-lost disposition
        before the serial applier restarts."""
        n = self.planner.queue.drain_stale(LEADERSHIP_LOST)
        if n:
            metrics.incr("nomad.plan.leadership_lost", n)
            self.logger(f"server: drained {n} stale plan pendings")
        self.planner.start()

    def _step_state_cache(self) -> None:
        """Promote/reseed the solver's device-resident cluster tensors
        for THIS store (new uid/epoch on a cold takeover; a journal-tail
        replay when the standby twin kept pace). Floor-gated like the AOT
        warmup — seeding builds DEVICE twins, and a unit-test server with
        three nodes must not pay jax backend attach at establish
        (NOMAD_AOT_WARMUP=1 forces, =0 disables, same as backend.warmup).
        Lazy import: a stripped solver-less build skips."""
        if not _device_work_gate("NOMAD_AOT_WARMUP",
                                 self.state.node_count()):
            return
        try:
            from ..solver import state_cache
        except ImportError:
            return
        out = state_cache.reseed(self.state)
        if not out.get("skipped"):
            self.logger(
                f"server: state cache "
                f"{'advanced (warm)' if out['warm'] else 'reseeded'}"
                f" for {out['rows']} nodes at establish")

    def _step_heartbeats(self) -> None:
        self.heartbeats.stop()      # idempotent under step retries
        self.heartbeats.initialize_heartbeat_timers()
        # inherit flap holds a deposed leader committed (flap_held_until
        # rides raft on the eligibility entry) so held nodes still
        # re-admit on schedule after a failover
        self.flap_damper.reset()
        self.flap_damper.adopt(self.state)
        self.heartbeats.start()

    def _step_watchers(self) -> None:
        self.eval_broker.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.periodic.set_enabled(True)
        # stop-then-start: a RETRY of this step after a partial failure
        # (e.g. thread creation failing midway) must not leak a second
        # watcher thread — start() is not idempotent, stop() is
        for watcher in (self.deployment_watcher, self.drainer,
                        self.volume_watcher):
            watcher.stop()
            watcher.start()

    def _step_broker_restore(self) -> None:
        # re-enqueue non-terminal evals, re-track periodic jobs
        for ev in self.state.iter_evals():
            if ev.status == EVAL_STATUS_PENDING:
                self.eval_broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)
        for job in self.state.iter_jobs():
            if job.is_periodic() and not job.stopped():
                self.periodic.add(job)

    def _reestablish_later(self) -> None:
        time.sleep(1.0)
        if self._still_leader() and not self.is_leader:
            self._establish_leadership()

    # ------------------------------------------------------- warm standby

    def _standby_warmup_loop(self) -> None:
        """Follower-side AOT warmup (ISSUE 6 warm standby): once the
        replicated cluster crosses the warmup floor, compile the solver
        grid NOW — so failover-to-first-solve is a cache probe, not a
        cold XLA compile. NOMAD_STANDBY_WARMUP=0 disables."""
        import os
        if os.environ.get("NOMAD_STANDBY_WARMUP", "") == "0":
            return
        while not self._shutdown_ev.wait(2.0):
            if self.is_leader:
                return          # the leader establish path owns warmup
            try:
                n = self.state.node_count()
                if n < _warmup_floor():
                    continue
                from ..solver import backend
                out = backend.warmup(
                    n, cfg=self.state.get_scheduler_config())
                if not out.get("skipped"):
                    self.logger(
                        f"server: standby warmup compiled "
                        f"{out['artifacts']} artifacts for bucket "
                        f"{out.get('bucket')} in {out['seconds']}s")
                # operator-visible: this follower is a WARM standby
                metrics.set_gauge("nomad.standby.warmed", 1)
                return
            except Exception as e:  # noqa: BLE001 — warmup is best-effort
                record_swallowed_error("server.standby_warmup", e,
                                       self.logger)
                return

    def _feed_standby_twin(self, index: int) -> None:
        """fsm.on_plan_apply hook: a FOLLOWER advances the passive tensor
        twin as replicated plan results land; the leader's own applier
        feeds the cache via plan_apply.note_commit instead (leader-only
        mutation stays inside the fence-checked applier, LEAD001).
        NOMAD_STANDBY_TWIN: "0" disables, "1" forces even below the
        warmup floor (the failover tests), default floor-gated so small
        in-process clusters never touch the device from an FSM apply."""
        if self.raft_node is None or self.is_leader:
            return
        if not _device_work_gate("NOMAD_STANDBY_TWIN",
                                 self.state.node_count()):
            return
        try:
            from ..solver import state_cache
        except ImportError:
            return
        state_cache.standby_feed(self.state)

    def _solver_warmup(self) -> None:
        """Leader-election AOT warmup (backend.warmup). Lazy import: a
        stripped build without the solver stays bootable; any failure is
        logged, never fatal — evals just pay the compiles lazily."""
        try:
            from ..solver import backend
            out = backend.warmup(len(self.state.iter_nodes()),
                                 cfg=self.state.get_scheduler_config())
            if not out.get("skipped"):
                self.logger(
                    f"server: solver warmup compiled {out['artifacts']} "
                    f"artifacts for bucket {out.get('bucket')} in "
                    f"{out['seconds']}s")
        except Exception as e:      # noqa: BLE001 — warmup is best-effort
            from ..metrics import record_swallowed_error
            record_swallowed_error("server.solver_warmup", e, self.logger)

    def _leader_loop(self) -> None:
        """Broker nack-timeout reaping + periodic core GC evals
        (ref leader.go schedulePeriodic / reapFailedEvaluations)."""
        last_gc = time.time()
        while not self._leader_stop.wait(1.0):
            self.eval_broker.check_nack_timeouts()
            try:
                # pressure recompute + brownout apply/release (ISSUE 8)
                self.overload.tick()
            except Exception as e:      # noqa: BLE001
                self.logger(f"overload tick: {e!r}")
            try:
                # a raft apply failing mid-reap (leadership transition,
                # injected raft.apply fault) must not kill the loop: the
                # dequeued eval's nack timeout redelivers it to the
                # failed queue and the next tick retries
                self._reap_failed_evaluations()
            except Exception as e:      # noqa: BLE001
                self.logger(f"failed-eval reap: {e!r}")
            try:
                self._autopilot_cleanup_dead_servers()
            except Exception as e:      # noqa: BLE001
                self.logger(f"autopilot: {e!r}")
            try:
                self._reap_stale_services()
            except Exception as e:      # noqa: BLE001
                self.logger(f"service reap: {e!r}")
            try:
                self._reconcile_gossip_peers()
            except Exception as e:      # noqa: BLE001
                self.logger(f"gossip reconcile: {e!r}")
            try:
                self._autopilot_promote_stable_servers()
            except Exception as e:      # noqa: BLE001
                self.logger(f"autopilot promote: {e!r}")
            try:
                # re-admit flap-held nodes whose hold expired (ISSUE 10)
                self._flap_readmit_tick()
            except Exception as e:      # noqa: BLE001
                self.logger(f"flap readmit: {e!r}")
            try:
                # terminate node-update evals the broker coalesced away
                # (the broker cannot raft-apply from the FSM callback)
                self._cancel_coalesced_evals()
            except Exception as e:      # noqa: BLE001
                self.logger(f"coalesced-eval cancel: {e!r}")
            if time.time() - last_gc >= self.gc_interval:
                last_gc = time.time()
                for kind in (CORE_JOB_EVAL_GC, CORE_JOB_JOB_GC,
                             CORE_JOB_NODE_GC, CORE_JOB_DEPLOYMENT_GC):
                    self.eval_broker.enqueue(Evaluation(
                        type=JOB_TYPE_CORE, job_id=kind,
                        priority=200, status="pending"))

    def _reap_failed_evaluations(self) -> None:
        """Dead-letter consumer (ref leader.go:782): the core scheduler
        owns the terminate + backed-off failed-follow-up lifecycle."""
        self.core_scheduler.reap_failed_evals()

    def _flap_readmit_tick(self) -> None:
        """Re-admit nodes whose flap hold expired (ISSUE 10): restore
        eligibility (which clears `flap_held_until` in the store) and
        wake blocked evals for the node's class. A node whose hold was
        already lifted by an operator eligibility write (flap_held_until
        cleared) just drops out of the damper's set."""
        for node_id in self.flap_damper.due():
            node = self.state.node_by_id(node_id)
            if node is None or not getattr(node, "flap_held_until", 0.0):
                self.flap_damper.release(node_id)
                continue
            index = self.raft.apply(NODE_UPDATE_ELIGIBILITY, {
                "node_id": node_id,
                "eligibility": NODE_SCHED_ELIGIBLE})
            self.flap_damper.release(node_id)
            metrics.incr("nomad.heartbeat.flap_readmitted")
            self.blocked_evals.unblock(node.computed_class, index)
            # the hold path suppressed the READY transition's system-job
            # evals ("nothing may schedule onto it yet") — emit them at
            # re-admission or the node comes back without its node-local
            # system allocs until some unrelated eval happens by
            evals = [e for e in create_node_evals(self.state, node_id)
                     if e.type == JOB_TYPE_SYSTEM]
            if evals:
                self.raft.apply(EVAL_UPDATE, {"evals": evals})

    def _cancel_coalesced_evals(self) -> None:
        """Storm-coalesced node-update evals (ISSUE 10) were superseded
        in the broker by an earlier queued eval for the same job; their
        state records would sit `pending` forever without this — cancel
        them so eval GC can reap."""
        superseded = self.eval_broker.take_coalesced()
        if not superseded:
            return
        canceled = []
        for eval_id in superseded:
            cur = self.state.eval_by_id(eval_id)
            if cur is None or cur.terminal_status():
                continue
            cur = cur.copy()
            cur.status = EVAL_STATUS_CANCELLED
            cur.status_description = ("superseded by a queued node-update "
                                      "eval (storm coalescing)")
            canceled.append(cur)
        if canceled:
            try:
                self.raft.apply(EVAL_UPDATE, {"evals": canceled})
            except Exception:
                # a transient apply failure must not lose the drained
                # ids — re-stash so the next tick retries the cancel
                self.eval_broker.restash_coalesced(superseded)
                raise
            metrics.incr("nomad.broker.node_update_canceled",
                         len(canceled))

    def eval_drain_failed(self) -> dict:
        """Operator drain of the broker dead-letter queue (agent HTTP
        /v1/operator/broker/drain-failed): each drained eval terminates
        as failed WITHOUT a follow-up — the operator is declaring it
        unrecoverable (bad jobspec, decommissioned node class) and
        taking it out of the retry loop."""
        from ..structs import EVAL_STATUS_CANCELLED, EVAL_STATUS_FAILED
        # one atomic broker removal covers dead letters AND their
        # waiting follow-ups (the leader reaper converts one into the
        # other every tick, so a two-step listing would race it); if the
        # terminating raft commit then fails, everything is restored to
        # the queue — nothing is lost, the operator simply retries
        drained, follows = self.eval_broker.drain_failed()
        updates = []
        for ev in drained:
            failed = ev.copy()
            failed.status = EVAL_STATUS_FAILED
            failed.status_description = \
                "dead-lettered evaluation drained by operator"
            updates.append(failed)
        for ev in follows:
            cancelled = ev.copy()
            cancelled.status = EVAL_STATUS_CANCELLED
            cancelled.status_description = \
                "failed-follow-up cancelled by operator drain"
            updates.append(cancelled)
        if updates:
            try:
                self.raft.apply(EVAL_UPDATE, {"evals": updates})
            except BaseException:
                self.eval_broker.restore_failed(drained + follows)
                raise
        return {"drained": [ev.id for ev in drained],
                "cancelled_follow_ups": [ev.id for ev in follows],
                "count": len(drained) + len(follows)}

    def _on_eval_update(self, evals: list[Evaluation]) -> None:
        if not self.is_leader:
            return
        for ev in evals:
            if ev.should_enqueue():
                self.eval_broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)

    def _enqueue_unblocked(self, ev: Evaluation) -> None:
        self.raft.apply(EVAL_UPDATE, {"evals": [ev]})

    # ------------------------------------------------------- Job endpoints

    def job_register(self, job: Job) -> dict:
        """ref nomad/job_endpoint.go:80 Job.Register (admission hooks:
        connect sidecar expansion + the jobspec layer's
        validate/canonicalize)."""
        from ..integrations.connect import connect_admission
        connect_admission(job)
        err = self._validate_job(job)
        if err:
            raise ValueError(err)
        evals = []
        if job.is_periodic():
            pass  # periodic parents don't get evals; dispatcher launches
        elif job.is_parameterized():
            pass
        else:
            evals.append(Evaluation(
                namespace=job.namespace, priority=job.priority, type=job.type,
                triggered_by=TRIGGER_JOB_REGISTER, job_id=job.id,
                status=EVAL_STATUS_PENDING))
        index = self.raft.apply(JOB_REGISTER, {"job": job, "evals": evals})
        # unconditional: PeriodicDispatch.add untracks jobs that are no
        # longer periodic/are stopped, so updates can't leave stale children
        stored = self.state.job_by_id(job.namespace, job.id)
        self.periodic.add(stored)
        self.blocked_evals.untrack(job.namespace, job.id)
        return {"eval_id": evals[0].id if evals else "", "index": index,
                "job_modify_index": index}

    def _validate_job(self, job: Job) -> str:
        if not job.id:
            return "missing job ID"
        if not job.task_groups:
            return "job requires at least one task group"
        seen = set()
        for tg in job.task_groups:
            if tg.name in seen:
                return f"duplicate task group {tg.name!r}"
            seen.add(tg.name)
            if not tg.tasks and job.type != JOB_TYPE_SYSTEM:
                pass
            for task in tg.tasks:
                if not task.driver:
                    return f"task {task.name!r} missing driver"
        if job.type not in (JOB_TYPE_SERVICE, JOB_TYPE_BATCH, JOB_TYPE_SYSTEM,
                            JOB_TYPE_SYSBATCH):
            return f"invalid job type {job.type!r}"
        cfg = self.state.get_scheduler_config()
        if cfg.reject_job_registration:
            return "job registration is disabled"
        return ""

    def namespace_upsert(self, namespaces: list[dict]) -> int:
        from .fsm import NAMESPACE_UPSERT
        return self.raft.apply(NAMESPACE_UPSERT, {"namespaces": namespaces})

    def namespace_delete(self, names: list[str]) -> int:
        from .fsm import NAMESPACE_DELETE
        # validate BEFORE the log apply: a raising FSM apply would burn a
        # log index and diverge across replicas
        for name in names:
            if name == "default":
                raise ValueError("default namespace cannot be deleted")
            if any(j.namespace == name for j in self.state.iter_jobs(name)):
                raise ValueError(f"namespace {name!r} has registered jobs")
        return self.raft.apply(NAMESPACE_DELETE, {"names": names})

    def job_plan(self, job: Job, diff: bool = True) -> dict:
        """Dry-run scheduler pass over a forked state (ref
        nomad/job_endpoint.go Job.Plan): insert the candidate job into a
        scratch store, run the real scheduler with a capturing planner, and
        return the annotated plan + job diff — Raft is never touched."""
        from ..scheduler import new_scheduler
        from ..scheduler.testing import Harness
        from ..structs.diff import job_diff
        from ..api_codec import to_api
        err = self._validate_job(job)
        if err:
            raise ValueError(err)
        old = self.state.job_by_id(job.namespace, job.id)
        scratch = self.state.fork()
        cand = job.copy()
        cand.version = (old.version + 1) if old else 0
        scratch.upsert_job(scratch.latest_index() + 1, cand)
        h = Harness(scratch)
        h.next_index = scratch.latest_index() + 1
        ev = Evaluation(
            namespace=job.namespace, priority=job.priority, type=job.type,
            job_id=job.id, triggered_by=TRIGGER_JOB_REGISTER,
            status=EVAL_STATUS_PENDING, annotate_plan=True)
        h.process(lambda snap, planner: new_scheduler(ev.type, snap, planner),
                  ev)
        plan = h.plans[-1] if h.plans else None
        final_ev = h.evals[-1] if h.evals else ev
        # contextual=True per ref job_endpoint.go Plan → Diff(job, true):
        # unchanged fields ride along as Type None for `plan -verbose`
        the_diff = job_diff(old, cand, contextual=True) if diff else None
        if the_diff is not None and plan is not None and \
                plan.annotations is not None:
            # scheduling-consequence annotations (ref scheduler/annotate.go
            # Annotate): what each change FORCES + per-group update counts
            from ..scheduler.annotate import annotate_job_diff
            annotate_job_diff(the_diff, plan.annotations)
        return {
            "Annotations": to_api(plan.annotations) if plan else None,
            "FailedTGAllocs": to_api(final_ev.failed_tg_allocs) or None,
            "JobModifyIndex": old.modify_index if old else 0,
            "CreatedEvals": [to_api(e) for e in h.created_evals],
            "Diff": the_diff,
            "Index": self.state.latest_index(),
        }

    def job_deregister(self, namespace: str, job_id: str,
                       purge: bool = False) -> dict:
        job = self.state.job_by_id(namespace, job_id)
        ev = Evaluation(
            namespace=namespace,
            priority=job.priority if job else 50,
            type=job.type if job else JOB_TYPE_SERVICE,
            triggered_by=TRIGGER_JOB_DEREGISTER, job_id=job_id,
            status=EVAL_STATUS_PENDING)
        index = self.raft.apply(JOB_DEREGISTER, {
            "namespace": namespace, "job_id": job_id, "purge": purge,
            "evals": [ev]})
        self.periodic.remove(namespace, job_id)
        self.blocked_evals.untrack(namespace, job_id)
        return {"eval_id": ev.id, "index": index}

    def job_evaluate(self, namespace: str, job_id: str,
                     force_reschedule: bool = False) -> dict:
        """Force a new evaluation of an existing job (ref
        nomad/job_endpoint.go Evaluate): no spec change, just re-run the
        scheduler — used to kick a job after node capacity changes or to
        force failed-alloc reschedules."""
        job = self.state.job_by_id(namespace, job_id)
        if job is None:
            raise ValueError(f"job {job_id!r} not found")
        if job.is_periodic():
            raise ValueError("can't evaluate periodic job")
        if job.is_parameterized():
            raise ValueError("can't evaluate parameterized job")
        ev = Evaluation(
            namespace=namespace, priority=job.priority, type=job.type,
            triggered_by=TRIGGER_JOB_REGISTER, job_id=job_id,
            status=EVAL_STATUS_PENDING)
        if force_reschedule:
            ev.triggered_by = TRIGGER_RETRY_FAILED_ALLOC
        # the FSM's on_eval_update hook enqueues it on the leader
        index = self.raft.apply(EVAL_UPDATE, {"evals": [ev]})
        return {"eval_id": ev.id, "eval_create_index": index,
                "job_modify_index": job.modify_index, "index": index}

    def job_dispatch(self, namespace: str, job_id: str,
                     payload: bytes = b"", meta: Optional[dict] = None) -> dict:
        """Parameterized job dispatch (ref nomad/job_endpoint.go Dispatch)."""
        parent = self.state.job_by_id(namespace, job_id)
        if parent is None or not parent.is_parameterized():
            raise ValueError(f"job {job_id!r} is not parameterized")
        cfg = parent.parameterized
        if cfg.payload == "forbidden" and payload:
            raise ValueError("payload forbidden")
        if cfg.payload == "required" and not payload:
            raise ValueError("payload required")
        meta = meta or {}
        for key in cfg.meta_required:
            if key not in meta:
                raise ValueError(f"missing required dispatch meta {key!r}")
        for key in meta:
            if key not in cfg.meta_required and key not in cfg.meta_optional:
                raise ValueError(f"unexpected dispatch meta {key!r}")
        child = parent.copy()
        child.id = f"{parent.id}/dispatch-{int(time.time())}-{new_id()[:8]}"
        child.parent_id = parent.id
        child.dispatched = True
        child.payload = payload
        child.meta = {**parent.meta, **meta}
        ev = Evaluation(
            namespace=namespace, priority=child.priority, type=child.type,
            triggered_by=TRIGGER_JOB_REGISTER, job_id=child.id,
            status=EVAL_STATUS_PENDING)
        index = self.raft.apply(JOB_REGISTER, {"job": child, "evals": [ev]})
        return {"dispatched_job_id": child.id, "eval_id": ev.id,
                "index": index}

    def job_scale(self, namespace: str, job_id: str, group: str,
                  count: Optional[int] = None, message: str = "",
                  error: bool = False, meta: Optional[dict] = None,
                  policy_override: bool = False) -> dict:
        """Scale a task group's count and record a scaling event (ref
        nomad/job_endpoint.go Job.Scale). With count=None only the event is
        recorded (autoscaler heartbeat/error reporting)."""
        from .fsm import SCALING_EVENT_REGISTER
        from ..structs.scaling import ScalingEvent
        job = self.state.job_by_id(namespace, job_id)
        if job is None:
            raise ValueError(f"job {job_id!r} not found")
        if job.stop and count is not None:
            raise ValueError("cannot scale a stopped job")
        tg = job.lookup_task_group(group)
        if tg is None:
            raise ValueError(f"task group {group!r} not found in {job_id!r}")
        prev_count = tg.count
        eval_id = ""
        index = 0
        if count is not None:
            if count < 0:
                raise ValueError("scaling count must be >= 0")
            if error:
                raise ValueError("cannot scale and report an error at once")
            pol = self.state.scaling_policy_by_target(namespace, job_id, group)
            if pol is not None and not policy_override:
                if count < pol.min:
                    raise ValueError(
                        f"group count was less than scaling policy minimum: "
                        f"{count} < {pol.min}")
                if pol.max and count > pol.max:
                    raise ValueError(
                        f"group count was greater than scaling policy "
                        f"maximum: {count} > {pol.max}")
            job = job.copy()
            job.lookup_task_group(group).count = count
            result = self.job_register(job)
            eval_id, index = result["eval_id"], result["index"]
        event = ScalingEvent(
            time=time.time(), count=count, previous_count=prev_count,
            message=message, error=error, meta=dict(meta or {}),
            eval_id=eval_id)
        ev_index = self.raft.apply(SCALING_EVENT_REGISTER, {
            "namespace": namespace, "job_id": job_id, "group": group,
            "event": event})
        return {"eval_id": eval_id, "index": index or ev_index,
                "eval_create_index": index}

    def job_scale_status(self, namespace: str, job_id: str) -> dict:
        """ref nomad/job_endpoint.go Job.ScaleStatus / structs.JobScaleStatus."""
        job = self.state.job_by_id(namespace, job_id)
        if job is None:
            raise ValueError(f"job {job_id!r} not found")
        events = self.state.scaling_events_by_job(namespace, job_id)
        groups = {}
        allocs = self.state.allocs_by_job(namespace, job_id)
        for tg in job.task_groups:
            placed = running = healthy = unhealthy = 0
            for a in allocs:
                if a.task_group != tg.name or a.terminal_status():
                    continue
                placed += 1
                if a.client_status == "running":
                    running += 1
                ds = a.deployment_status
                if ds is not None and ds.healthy is True:
                    healthy += 1
                elif ds is not None and ds.healthy is False:
                    unhealthy += 1
            groups[tg.name] = {
                "Desired": tg.count, "Placed": placed, "Running": running,
                "Healthy": healthy, "Unhealthy": unhealthy,
                "Events": events.get(tg.name, []),
            }
        return {
            "JobID": job.id, "Namespace": job.namespace,
            "JobStopped": job.stop, "JobCreateIndex": job.create_index,
            "JobModifyIndex": job.modify_index, "TaskGroups": groups,
        }

    def job_revert(self, namespace: str, job_id: str, version: int,
                   enforce_prior_version: Optional[int] = None) -> dict:
        """Re-register an older job version (ref nomad/job_endpoint.go
        Job.Revert)."""
        cur = self.state.job_by_id(namespace, job_id)
        if cur is None:
            raise ValueError(f"job {job_id!r} not found")
        if enforce_prior_version is not None \
                and cur.version != enforce_prior_version:
            raise ValueError(
                f"current version {cur.version} does not match enforced "
                f"prior version {enforce_prior_version}")
        if version == cur.version:
            raise ValueError(f"job already at version {version}")
        target = self.state.job_by_version(namespace, job_id, version)
        if target is None:
            raise ValueError(f"job {job_id!r} at version {version} not found")
        revert = target.copy()
        revert.stop = False
        return self.job_register(revert)

    def job_stable(self, namespace: str, job_id: str, version: int,
                   stable: bool) -> dict:
        """Mark a job version (un)stable (ref nomad/job_endpoint.go
        Job.Stable; used by deployment auto-revert)."""
        from .fsm import JOB_STABILITY
        if self.state.job_by_version(namespace, job_id, version) is None:
            raise ValueError(f"job {job_id!r} version {version} not found")
        index = self.raft.apply(JOB_STABILITY, {
            "namespace": namespace, "job_id": job_id, "version": version,
            "stable": stable})
        return {"index": index}

    def scaling_policies_list(self, namespace: Optional[str] = None,
                              job_id: Optional[str] = None,
                              type_: Optional[str] = None) -> list:
        return self.state.iter_scaling_policies(namespace, job_id, type_)

    def scaling_policy_get(self, policy_id: str):
        return self.state.scaling_policy_by_id(policy_id)

    # ----------------------------------------------- Service catalog + Vault

    def service_register(self, instances: list) -> dict:
        """ref the consul service_client Register path, state-store backed."""
        from .fsm import SERVICE_REGISTER
        index = self.raft.apply(SERVICE_REGISTER, {"services": instances})
        return {"index": index}

    def service_deregister(self, alloc_id: str = "",
                           keys: Optional[list] = None) -> dict:
        from .fsm import SERVICE_DEREGISTER
        index = self.raft.apply(SERVICE_DEREGISTER,
                                {"alloc_id": alloc_id, "keys": keys})
        return {"index": index}

    def service_list(self, namespace: Optional[str] = None) -> list:
        return self.state.iter_services(namespace)

    def service_instances(self, namespace: str, name: str) -> list:
        return self.state.services_by_name(namespace, name)

    # mesh authorization (Consul intentions analog): rules are raft-
    # replicated; the connect proxies consult IntentionAllowed per
    # connection
    def intention_upsert(self, intention) -> dict:
        from .fsm import INTENTION_UPSERT
        from ..integrations.services import INTENTION_ALLOW, INTENTION_DENY
        if intention.action not in (INTENTION_ALLOW, INTENTION_DENY):
            raise ValueError(f"invalid action {intention.action!r}")
        if not intention.source or not intention.destination:
            raise ValueError("intention requires source and destination")
        if not intention.namespace or intention.namespace == "*":
            # namespaces match exactly in intention_allowed (no
            # wildcarding) — a "*" namespace rule would be inert
            raise ValueError("intention requires a concrete namespace")
        index = self.raft.apply(INTENTION_UPSERT, {"intention": intention})
        return {"index": index}

    def intention_delete(self, namespace: str, source: str,
                         destination: str) -> dict:
        from .fsm import INTENTION_DELETE
        index = self.raft.apply(INTENTION_DELETE, {
            "namespace": namespace, "source": source,
            "destination": destination})
        return {"index": index}

    def intention_list(self, namespace: Optional[str] = None) -> list:
        return self.state.iter_intentions(namespace)

    def intention_allowed(self, namespace: str, source: str,
                          destination: str) -> bool:
        return self.state.intention_allowed(namespace, source, destination)

    def _reap_stale_services(self) -> None:
        """Registrations of terminal/vanished allocs are removed by the
        leader (the consul-integration's deregister-on-stop safety net)."""
        doomed = []
        for inst in self.state.iter_services():
            alloc = self.state.alloc_by_id(inst.alloc_id)
            if alloc is None or alloc.terminal_status():
                doomed.append(list(inst.key()))
        if doomed:
            self.service_deregister(keys=doomed)

    def vault_derive_token(self, alloc_id: str, task: str) -> dict:
        """ref nomad/node_endpoint.go DeriveVaultToken: validates the alloc
        asks for vault before issuing."""
        alloc = self.state.alloc_by_id(alloc_id)
        if alloc is None:
            raise ValueError(f"allocation {alloc_id!r} not found")
        tg = alloc.job.lookup_task_group(alloc.task_group) \
            if alloc.job else None
        t = tg.lookup_task(task) if tg else None
        if t is None or t.vault is None:
            raise ValueError(f"task {task!r} does not use vault")
        tok = self.secrets.derive_token(alloc_id, task,
                                        list(t.vault.policies))
        return {"token": tok.token, "ttl_sec": tok.ttl_sec}

    def derive_si_token(self, alloc_id: str, task: str) -> dict:
        """Service-identity token for a connect sidecar task (ref
        nomad/node_endpoint.go:DeriveSIToken + the client sids_hook:
        Consul SI tokens scoped to the service the sidecar fronts).
        Validates the named task IS the injected proxy of one of the
        alloc's connect services before minting."""
        from ..integrations.connect import PROXY_PREFIX
        alloc = self.state.alloc_by_id(alloc_id)
        if alloc is None:
            raise ValueError(f"allocation {alloc_id!r} not found")
        tg = alloc.job.lookup_task_group(alloc.task_group) \
            if alloc.job else None
        svc_name = task[len(PROXY_PREFIX):] \
            if task.startswith(PROXY_PREFIX) else ""
        svc = next((s for s in (tg.services if tg else [])
                    if s.name == svc_name and s.connect), None)
        if svc is None:
            raise ValueError(
                f"task {task!r} is not a connect sidecar of this alloc")
        tok = self.secrets.derive_token(
            alloc_id, task,
            ["si", f"service:{alloc.namespace}/{svc.name}"])
        return {"token": tok.token, "ttl_sec": tok.ttl_sec,
                "service": svc.name}

    def vault_renew_token(self, token: str) -> dict:
        tok = self.secrets.renew_token(token)
        return {"ttl_sec": tok.ttl_sec, "expires_at": tok.expires_at}

    def vault_revoke_token(self, token: str) -> dict:
        self.secrets.revoke_token(token)
        return {}

    def secret_read(self, path: str) -> Optional[dict]:
        return self.secrets.read(path)

    # --------------------------------------------------------- CSI endpoints

    def csi_volume_register(self, volumes: list) -> dict:
        """ref nomad/csi_endpoint.go CSIVolume.Register"""
        for vol in volumes:
            if not vol.id:
                raise ValueError("volume requires an ID")
            if not vol.plugin_id:
                raise ValueError(f"volume {vol.id!r} requires a plugin ID")
        from .fsm import CSI_VOLUME_REGISTER
        index = self.raft.apply(CSI_VOLUME_REGISTER, {"volumes": volumes})
        return {"index": index}

    def csi_volume_deregister(self, namespace: str, volume_id: str,
                              force: bool = False) -> dict:
        from .fsm import CSI_VOLUME_DEREGISTER
        # fail fast with a readable error before paying the raft round-trip
        vol = self.state.csi_volume_by_id(namespace, volume_id)
        if vol is None:
            raise ValueError(f"volume {volume_id!r} not found")
        if vol.in_use() and not force:
            raise ValueError(f"volume {volume_id!r} is in use")
        index = self.raft.apply(CSI_VOLUME_DEREGISTER, {
            "namespace": namespace, "volume_id": volume_id, "force": force})
        return {"index": index}

    def csi_volume_claim(self, namespace: str, volume_id: str, claim) -> dict:
        """Claim (or release, via claim.state) a volume for an alloc
        (ref csi_endpoint.go CSIVolume.Claim)."""
        from .fsm import CSI_VOLUME_CLAIM
        from ..structs.csi import (
            CLAIM_STATE_CONTROLLER_DETACHED, CLAIM_STATE_NODE_DETACHED,
            CLAIM_STATE_READY_TO_FREE,
        )
        vol = self.state.csi_volume_by_id(namespace, volume_id)
        if vol is None:
            raise ValueError(f"volume {volume_id!r} not found")
        if claim.state not in (CLAIM_STATE_READY_TO_FREE,
                               CLAIM_STATE_NODE_DETACHED,
                               CLAIM_STATE_CONTROLLER_DETACHED):
            if not vol.schedulable:
                raise ValueError(f"volume {volume_id!r} is not schedulable")
            # enforce claim limits BEFORE the raft round-trip: the clustered
            # applier swallows FSM errors, so an in-FSM rejection would be
            # reported as success to the caller
            from ..structs.csi import CLAIM_WRITE
            if claim.mode == CLAIM_WRITE \
                    and claim.alloc_id not in vol.write_claims \
                    and not vol.claim_ok(claim.mode):
                raise ValueError(
                    f"volume {volume_id!r} has no free write claims")
            if claim.mode != CLAIM_WRITE and not vol.claim_ok(claim.mode):
                raise ValueError(f"volume {volume_id!r} not readable")
        index = self.raft.apply(CSI_VOLUME_CLAIM, {
            "namespace": namespace, "volume_id": volume_id, "claim": claim})
        return {"index": index,
                "volume": self.state.csi_volume_by_id(namespace, volume_id)}

    def csi_volume_list(self, namespace: Optional[str] = None,
                        plugin_id: Optional[str] = None) -> list:
        return self.state.iter_csi_volumes(namespace, plugin_id)

    def _claim_alloc_gone(self, claim) -> bool:
        alloc = self.state.alloc_by_id(claim.alloc_id)
        return alloc is None or alloc.terminal_status()

    def csi_node_detach_pending(self, node_id: str) -> list[dict]:
        """Claims on `node_id` awaiting NODE unpublish: alloc terminal or
        gone, claim still in the taken state. The node's csimanager polls
        this and confirms each detach with a node-detached claim update
        (the pull-model half of volumewatcher/volume_watcher.go)."""
        from ..structs.csi import CLAIM_STATE_TAKEN
        out = []
        for vol in self.state.iter_csi_volumes():
            for claim in list(vol.read_claims.values()) + \
                    list(vol.write_claims.values()):
                if claim.node_id != node_id or \
                        claim.state != CLAIM_STATE_TAKEN:
                    continue
                if not self._claim_alloc_gone(claim):
                    continue
                out.append({"namespace": vol.namespace,
                            "volume_id": vol.id,
                            "alloc_id": claim.alloc_id,
                            "plugin_id": vol.plugin_id})
        return out

    def csi_controller_detach_pending(self, plugin_ids: list[str],
                                      node_id: str = "") -> list[dict]:
        """Claims awaiting CONTROLLER unpublish for plugins this caller
        hosts a controller for: node detach done, plugin requires a
        controller round before the claim can free. The round is LEASED
        to one controller node (lowest healthy id) so concurrent
        controller hosts don't issue duplicate backend unpublishes — the
        reference serializes this through the server-side volumewatcher."""
        from ..structs.csi import CLAIM_STATE_NODE_DETACHED
        wanted = set(plugin_ids)
        out = []
        for vol in self.state.iter_csi_volumes():
            if vol.plugin_id not in wanted:
                continue
            plug = self.state.csi_plugin_by_id(vol.plugin_id)
            if plug is None or not plug.controller_required:
                continue
            if node_id:
                from ..structs import NODE_STATUS_DOWN
                healthy = sorted(nid for nid, ok in plug.controllers.items()
                                 if ok)
                if not healthy:
                    # no controller reports healthy (ADVICE r4): lease on
                    # a registered id whose NODE is still alive rather
                    # than dropping the gate — an open gate hands the
                    # same claim to every polling host and the backend
                    # sees duplicate ControllerUnpublishVolume rounds.
                    # Dead-node registrations are excluded (leasing on a
                    # SIGKILL'd host would stall detach forever); if NO
                    # registered controller is provably alive, grant the
                    # caller (it is polling, therefore alive) — progress
                    # over dedup in the double-failure corner.
                    def _alive(nid: str) -> bool:
                        n = self.state.node_by_id(nid)
                        return (n is not None
                                and n.status != NODE_STATUS_DOWN)
                    healthy = sorted(nid for nid in plug.controllers
                                     if _alive(nid))
                if healthy and node_id != healthy[0]:
                    continue        # another node holds the lease
            for claim in list(vol.read_claims.values()) + \
                    list(vol.write_claims.values()):
                if claim.state != CLAIM_STATE_NODE_DETACHED:
                    continue
                if not self._claim_alloc_gone(claim):
                    continue
                out.append({"namespace": vol.namespace,
                            "volume_id": vol.id,
                            "alloc_id": claim.alloc_id,
                            "node_id": claim.node_id,
                            "plugin_id": vol.plugin_id})
        return out

    def csi_volume_get(self, namespace: str, volume_id: str):
        return self.state.csi_volume_by_id(namespace, volume_id)

    def csi_plugin_list(self) -> list:
        return self.state.iter_csi_plugins()

    def csi_plugin_get(self, plugin_id: str):
        return self.state.csi_plugin_by_id(plugin_id)

    # ------------------------------------------------------ Search endpoints

    def search_prefix(self, prefix: str, context: str = "all",
                      namespace: str = "default", acl=None) -> dict:
        from .search import prefix_search
        return prefix_search(self.state, prefix, context, namespace, acl)

    def search_fuzzy(self, text: str, context: str = "all",
                     namespace: str = "default", acl=None) -> dict:
        from .search import fuzzy_search
        return fuzzy_search(self.state, text, context, namespace, acl)

    # ------------------------------------------------------ Node endpoints

    def node_register(self, node: Node) -> dict:
        """ref nomad/node_endpoint.go:81 Register"""
        if not node.id:
            raise ValueError("missing node ID")
        node = node.copy()
        if not node.computed_class:
            node.compute_class()
        if not node.status:
            node.status = NODE_STATUS_READY
        prior = self.state.node_by_id(node.id)
        index = self.raft.apply(NODE_REGISTER, {"node": node})
        ttl = self.heartbeats.reset_heartbeat_timer(node.id)
        if node.status == NODE_STATUS_READY:
            hold = None
            if prior is not None and prior.status != NODE_STATUS_READY:
                # a down node coming back via re-register is the same
                # down->up edge the status endpoint sees (ISSUE 10)
                hold = self.flap_damper.record_up(node.id)
            if hold is not None:
                self.raft.apply(NODE_UPDATE_ELIGIBILITY, {
                    "node_id": node.id,
                    "eligibility": NODE_SCHED_INELIGIBLE,
                    "flap_until": hold})
            else:
                stored = self.state.node_by_id(node.id)
                if not getattr(stored, "flap_held_until", 0.0):
                    self.blocked_evals.unblock(node.computed_class, index)
        return {"heartbeat_ttl": ttl, "index": index}

    def node_update_status(self, node_id: str, status: str) -> dict:
        """ref node_endpoint.go:421 UpdateStatus"""
        node = self.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node {node_id} not found")
        if node.status == status and not self.raft.quorum_fresh():
            # the unchanged-status fast path (below) acks without a raft
            # round — safe only when the local state it consulted is
            # provably current. A leader healing from a partition can
            # still believe it leads while its state is behind the real
            # leader's: acking "already in that state" from it LOSES an
            # acked write (ISSUE 18, docs/PARTITIONS.md). Refuse instead;
            # the client's retry ladder re-lands the same dedup token on
            # a server that can vouch for its read.
            metrics.incr("nomad.rpc.stale_ack_refused")
            raise NotLeaderError("")
        evals: list[Evaluation] = []
        if node.status != status:
            was_up = node.status == NODE_STATUS_READY
            index = self.raft.apply(NODE_UPDATE_STATUS, {
                "node_id": node_id, "status": status,
                "updated_at": time.time()})
            if status == NODE_STATUS_DOWN:
                if was_up:
                    self.flap_damper.record_down(node_id)
                evals = create_node_evals(self.state, node_id)
            elif status == NODE_STATUS_READY:
                hold = self.flap_damper.record_up(node_id)
                if hold is not None:
                    # flap damping (ISSUE 10): the node cycled down/up
                    # past the threshold — hold it ineligible (the
                    # deadline rides raft) instead of letting reconnect
                    # churn oscillate the eligibility mask. No unblock,
                    # no system evals: nothing may schedule onto it yet.
                    self.raft.apply(NODE_UPDATE_ELIGIBILITY, {
                        "node_id": node_id,
                        "eligibility": NODE_SCHED_INELIGIBLE,
                        "flap_until": hold})
                else:
                    node = self.state.node_by_id(node_id)
                    # a node still inside an active flap hold cycling
                    # down/up below the (reset) threshold must not
                    # unblock evals or get system evals — it is
                    # ineligible until the readmit tick lifts the hold
                    # (same guard node_register applies)
                    if not getattr(node, "flap_held_until", 0.0):
                        self.blocked_evals.unblock(node.computed_class,
                                                   index)
                        evals = [e for e in
                                 create_node_evals(self.state, node_id)
                                 if e.type == JOB_TYPE_SYSTEM]
            if evals:
                self.raft.apply(EVAL_UPDATE, {"evals": evals})
        ttl = self.heartbeats.reset_heartbeat_timer(node_id)
        return {"heartbeat_ttl": ttl,
                "eval_ids": [e.id for e in evals]}

    def node_heartbeat(self, node_id: str) -> dict:
        ttl = self.heartbeats.reset_heartbeat_timer(node_id)
        return {"heartbeat_ttl": ttl}

    def node_update_drain(self, node_id: str,
                          drain: Optional[DrainStrategy],
                          mark_eligible: bool = False) -> dict:
        """ref node_endpoint.go:557 UpdateDrain"""
        node = self.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node {node_id} not found")
        if drain is not None and drain.deadline_sec > 0:
            drain.force_deadline_unix = time.time() + drain.deadline_sec
        index = self.raft.apply(NODE_UPDATE_DRAIN, {
            "node_id": node_id, "drain": drain,
            "mark_eligible": mark_eligible})
        evals = []
        if drain is not None:
            evals = create_node_evals(self.state, node_id)
            for ev in evals:
                ev.triggered_by = TRIGGER_NODE_DRAIN
            if evals:
                self.raft.apply(EVAL_UPDATE, {"evals": evals})
            self.drainer.track_node(node_id)
        return {"index": index, "eval_ids": [e.id for e in evals]}

    def node_update_eligibility(self, node_id: str, eligibility: str) -> dict:
        index = self.raft.apply(NODE_UPDATE_ELIGIBILITY, {
            "node_id": node_id, "eligibility": eligibility})
        # an operator eligibility write supersedes any flap hold (the
        # store cleared flap_held_until with this entry)
        self.flap_damper.release(node_id)
        if eligibility == "eligible":
            node = self.state.node_by_id(node_id)
            if node:
                self.blocked_evals.unblock(node.computed_class, index)
        return {"index": index}

    def node_get_client_allocs(self, node_id: str, min_index: int = 0,
                               timeout: float = 30.0) -> dict:
        """Blocking query the client long-polls (ref node_endpoint.go
        GetClientAllocs / client watchAllocations). The hold shrinks
        under pressure (brownout, ISSUE 8) — parked long-polls return
        capacity, clients just re-poll sooner."""
        deadline = time.time() + min(timeout, self.overload.blocking_cap_s())
        # park on the broker, not the store condvar: only Allocation
        # events wake this long-poll, instead of every write in the
        # cluster waking every parked client (ISSUE 16). `seen` tracks
        # the last observed topic index so unrelated alloc churn cannot
        # busy-spin the re-check loop; the deadline re-check keeps the
        # no-event GC paths correct (bounded-delay, never wrong).
        seen = min_index
        while True:
            allocs = self.state.allocs_by_node(node_id)
            index = self.state.latest_index()
            relevant = {a.id: a.modify_index for a in allocs
                        if not (a.desired_status == ALLOC_DESIRED_STOP and
                                a.client_terminal_status())}
            if any(mi > min_index for mi in relevant.values()) or \
               time.time() >= deadline:
                return {"allocs": relevant, "index": index}
            seen = max(seen, self.event_broker.wait_for_index(
                ("Allocation",), seen,
                timeout=max(0.05, deadline - time.time())))

    # ---------------------------------------------------------- read plane
    # ISSUE 16: list/get served from ANY server's replicated store off the
    # leader's hot lock, via the snapshot memo (`state/store.py _snap_memo`
    # — repeated reads between writes share one snapshot). Staleness is
    # provable: every response carries QueryMeta {LastIndex, KnownLeader,
    # Stale, Server} (ref nomad/structs QueryMeta + AllowStale).

    def _read_snapshot(self, stale: bool, max_stale_index: int,
                       timeout: float):
        """Resolve the snapshot a read is served from.

        Consistent (default) reads on a follower redirect to the leader
        via NotLeaderError (the rpc client retries transparently). Stale
        reads serve locally; `max_stale_index` bounds the staleness —
        the follower blocks until its store has applied that index, and
        redirects to the leader if it cannot catch up in time."""
        if self.raft_node is not None:
            # leader_rpc_addr is otherwise only refreshed when the
            # dispatcher gates a leader-only endpoint; read endpoints are
            # leader_only=False, so pull the current leader from raft here
            # or KnownLeader/redirects would ride a stale cache
            self._raft_leadership()
        if not stale and self.raft_node is not None and not self.is_leader:
            raise NotLeaderError(self.leader_rpc_addr)
        if max_stale_index:
            cap = min(timeout, self.overload.blocking_cap_s())
            try:
                return self.state.snapshot_min_index(max_stale_index,
                                                     timeout=cap)
            except TimeoutError:
                # this replica is too far behind the bound: the leader
                # (which defines the index) can always serve it
                if not self.is_leader and self.leader_rpc_addr:
                    raise NotLeaderError(self.leader_rpc_addr)
                raise
        return self.state.snapshot()

    def _read_meta(self, index: int, stale: bool) -> dict:
        # KnownLeader=False during elections is the client's signal that
        # LastIndex may lag an unreachable majority (ref QueryMeta)
        known = self.is_leader or bool(self.leader_rpc_addr)
        metrics.incr("nomad.read.leader_served" if self.is_leader
                     else "nomad.read.follower_served")
        return {"LastIndex": index, "KnownLeader": known,
                "Stale": bool(stale and not self.is_leader),
                "Server": self.name}

    def read_list(self, table: str, namespace: Optional[str] = None,
                  stale: bool = False, max_stale_index: int = 0,
                  fields: Optional[list] = None, columnar: bool = False,
                  timeout: float = 5.0) -> dict:
        """List stubs for the fleet-dashboard hot paths. Rows are sorted
        by (CreateIndex, ID) so leader and follower payloads at the same
        index are bit-identical (the staleness differential contract)."""
        from ..api_codec import (alloc_stub, job_stub, node_stub,
                                 project_fields, to_api, to_columnar)
        snap = self._read_snapshot(stale, max_stale_index, timeout)
        by_create = lambda o: (o.create_index, o.id)  # noqa: E731
        if table == "nodes":
            rows = [node_stub(n) for n in sorted(snap.iter_nodes(),
                                                 key=by_create)]
        elif table == "allocs":
            allocs = [a for a in snap.iter_allocs()
                      if namespace is None or a.namespace == namespace]
            rows = [alloc_stub(a) for a in sorted(allocs, key=by_create)]
        elif table == "evals":
            evals = [e for e in snap.iter_evals()
                     if namespace is None or e.namespace == namespace]
            rows = [to_api(e) for e in sorted(evals, key=by_create)]
        elif table == "jobs":
            rows = [job_stub(j, snap.job_summary(j.namespace, j.id))
                    for j in sorted(snap.iter_jobs(namespace),
                                    key=by_create)]
        else:
            raise ValueError(f"unknown read table: {table!r}")
        rows = project_fields(rows, fields)
        out = {"QueryMeta": self._read_meta(snap.index, stale)}
        if columnar:
            out["Columnar"] = to_columnar(rows)
        else:
            out["Items"] = rows
        return out

    def read_get(self, table: str, key: str,
                 namespace: str = "default", stale: bool = False,
                 max_stale_index: int = 0, timeout: float = 5.0) -> dict:
        """Single-object read off any server (same staleness contract as
        read_list)."""
        from ..api_codec import to_api
        snap = self._read_snapshot(stale, max_stale_index, timeout)
        if table == "node":
            obj = snap.node_by_id(key)
        elif table == "alloc":
            obj = snap.alloc_by_id(key)
        elif table == "eval":
            obj = snap.eval_by_id(key)
        elif table == "job":
            obj = snap.job_by_id(namespace, key)
        elif table == "deployment":
            obj = snap.deployment_by_id(key)
        else:
            raise ValueError(f"unknown read table: {table!r}")
        return {"Item": to_api(obj) if obj is not None else None,
                "QueryMeta": self._read_meta(snap.index, stale)}

    def node_update_allocs(self, allocs: list[Allocation]) -> dict:
        """Client pushes alloc status (ref node_endpoint.go UpdateAlloc):
        terminal transitions trigger new evals."""
        index = self.raft.apply(ALLOC_CLIENT_UPDATE, {"allocs": allocs})
        evals = []
        seen = set()
        for alloc in allocs:
            stored = self.state.alloc_by_id(alloc.id)
            if stored is None or stored.job is None:
                continue
            key = (stored.namespace, stored.job_id)
            if key in seen:
                continue
            if alloc.client_status in (ALLOC_CLIENT_FAILED,):
                seen.add(key)
                evals.append(Evaluation(
                    namespace=stored.namespace,
                    priority=stored.job.priority,
                    type=stored.job.type,
                    triggered_by=TRIGGER_RETRY_FAILED_ALLOC,
                    job_id=stored.job_id, status=EVAL_STATUS_PENDING))
            elif alloc.client_status == ALLOC_CLIENT_COMPLETE and \
                    stored.job.type in (JOB_TYPE_BATCH, JOB_TYPE_SYSBATCH):
                seen.add(key)
                evals.append(Evaluation(
                    namespace=stored.namespace,
                    priority=stored.job.priority,
                    type=stored.job.type,
                    triggered_by=TRIGGER_ALLOC_STOP,
                    job_id=stored.job_id, status=EVAL_STATUS_PENDING))
        if evals:
            self.raft.apply(EVAL_UPDATE, {"evals": evals})
        return {"index": index, "eval_ids": [e.id for e in evals]}

    # ----------------------------------------------------- Alloc endpoints

    def node_get_http_addr(self, node_id: str) -> str:
        """HTTP address of a node's agent (used by remote ephemeral-disk
        migration, ref client/allocwatcher remotePrevAlloc)."""
        node = self.state.node_by_id(node_id)
        return node.http_addr if node else ""

    def alloc_get(self, alloc_id: str):
        """ref nomad/alloc_endpoint.go GetAlloc"""
        return self.state.alloc_by_id(alloc_id)

    def alloc_stop(self, alloc_id: str) -> dict:
        """User-initiated alloc stop (ref alloc_endpoint.go Stop): mark the
        transition and create an eval."""
        alloc = self.state.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(f"alloc {alloc_id} not found")
        from ..structs import DesiredTransition
        ev = Evaluation(
            namespace=alloc.namespace,
            priority=alloc.job.priority if alloc.job else 50,
            type=alloc.job.type if alloc.job else JOB_TYPE_SERVICE,
            triggered_by=TRIGGER_ALLOC_STOP, job_id=alloc.job_id,
            status=EVAL_STATUS_PENDING)
        self.raft.apply(ALLOC_UPDATE_DESIRED_TRANSITION, {
            "transitions": {alloc_id: DesiredTransition(migrate=True)},
            "evals": [ev]})
        return {"eval_id": ev.id}

    # ------------------------------------------------------ Eval endpoints

    def eval_dequeue(self, schedulers: list[str],
                     timeout: float = 1.0) -> tuple[Optional[Evaluation], str]:
        """ref nomad/eval_endpoint.go:83 Dequeue"""
        return self.eval_broker.dequeue(schedulers, timeout)

    def eval_ack(self, eval_id: str, token: str) -> None:
        self.eval_broker.ack(eval_id, token)

    def eval_nack(self, eval_id: str, token: str) -> None:
        self.eval_broker.nack(eval_id, token)

    # ------------------------------------------------ Deployment endpoints

    def deployment_list(self, namespace: Optional[str] = None) -> list:
        return [d for d in self.state.iter_deployments()
                if namespace in (None, "*") or d.namespace == namespace]

    def deployment_promote(self, deployment_id: str,
                           groups: Optional[list] = None) -> dict:
        return self.deployment_watcher.promote(deployment_id, groups)

    def deployment_fail(self, deployment_id: str) -> dict:
        return self.deployment_watcher.fail_deployment(deployment_id)

    def deployment_pause(self, deployment_id: str, paused: bool) -> dict:
        return self.deployment_watcher.pause(deployment_id, paused)

    # -------------------------------------------------- Operator endpoints

    # ----------------------------------------------------- Operator: raft

    def operator_raft_configuration(self) -> dict:
        """ref nomad/operator_endpoint.go RaftGetConfiguration"""
        from .raft import RaftNode
        if isinstance(self.raft, RaftNode):
            is_leader, _ = self.raft.leadership()
            # snapshot membership under the raft lock: config-entry
            # application resizes these dicts concurrently, and this
            # endpoint is polled exactly during membership transitions
            with self.raft._lock:
                peers = dict(self.raft.peers)
                nonvoters = set(self.raft.nonvoters)
            servers = [{
                "ID": pid, "Node": pid, "Address": addr,
                "Leader": (pid == self.raft.node_id and is_leader)
                or pid == self.raft.leader_id,
                # real voter status: freshly (re)joined servers ride as
                # non-voters until autopilot promotes them, and operators
                # (and the e2e rejoin test) must see that
                "Voter": pid not in nonvoters,
                "RaftProtocol": "3",
            } for pid, addr in sorted(peers.items())]
            return {"Servers": servers, "Index": self.raft.barrier()}
        return {"Servers": [{
            "ID": "server-1", "Node": "server-1",
            "Address": self.rpc_addr if self.rpc_server else "local",
            "Leader": self.is_leader, "Voter": True, "RaftProtocol": "3",
        }], "Index": self.raft.barrier()}

    def operator_raft_remove_peer(self, peer_id: str = "",
                                  address: str = "") -> dict:
        """ref operator_endpoint.go RaftRemovePeerByAddress/ID"""
        from .raft import RaftNode
        if not isinstance(self.raft, RaftNode):
            raise ValueError("raft membership requires a multi-node cluster")
        if not peer_id and address:
            matches = [pid for pid, a in self.raft.peers.items()
                       if a == address]
            if not matches:
                raise ValueError(f"no raft peer at address {address!r}")
            peer_id = matches[0]
        index = self.raft.remove_peer(peer_id)
        return {"index": index}

    def operator_raft_add_peer(self, peer_id: str, address: str) -> dict:
        """Join a new server into the raft configuration (agent join path)."""
        from .raft import RaftNode
        if not isinstance(self.raft, RaftNode):
            raise ValueError("raft membership requires a multi-node cluster")
        index = self.raft.add_peer(peer_id, address)
        return {"index": index}

    def operator_autopilot_get_config(self) -> dict:
        return self.state.get_autopilot_config()

    def operator_autopilot_set_config(self, config: dict) -> dict:
        from .fsm import AUTOPILOT_CONFIG
        index = self.raft.apply(AUTOPILOT_CONFIG, {"config": config})
        return {"Updated": True, "index": index}

    def operator_server_health(self) -> dict:
        """ref operator autopilot health endpoint"""
        from .raft import RaftNode
        if isinstance(self.raft, RaftNode):
            servers = self.raft.server_health()
        else:
            servers = [{"ID": "server-1", "Address": "local",
                        "Leader": self.is_leader, "Voter": True,
                        "Healthy": True, "LastContactSec": 0.0,
                        "MatchIndex": self.raft.barrier()}]
        # Healthy=None means "unknown from this server" (follower view);
        # only definite failures make the cluster unhealthy
        healthy = all(s["Healthy"] is not False for s in servers)
        return {"Healthy": healthy,
                "FailureTolerance": max(0, (sum(
                    1 for s in servers if s["Healthy"]) - 1) // 2),
                "Servers": servers}

    def _autopilot_promote_stable_servers(self) -> None:
        """raft-autopilot stable-server promotion (ref nomad/autopilot.go
        promoteStableServers): a non-voter that has replicated healthily
        for ServerStabilizationTime becomes a voter."""
        from .raft import RaftNode
        if not isinstance(self.raft, RaftNode) or not self.is_leader:
            return
        # tick evidence: tests that drive this method directly (the
        # de-flaked gossip promote test) still assert the HOUSEKEEPING
        # LOOP invokes it, via this counter — dropping the loop call
        # would silently stop real clusters from promoting nonvoters
        from ..metrics import metrics
        metrics.incr("nomad.autopilot.promote_tick")
        cfg = self.state.get_autopilot_config()
        stabilization = float(cfg.get("ServerStabilizationTimeSec", 10.0))
        for s_h in self.raft.server_health():
            if s_h["Voter"] or not s_h["Healthy"]:
                continue
            if s_h.get("KnownForSec", 0.0) >= stabilization:
                # bounded: a promote racing the server's death must not
                # stall the 1s leader housekeeping loop for 30s
                self.raft.promote_peer(s_h["ID"], timeout=5.0)
                self.logger(
                    f"server: promoted stable server {s_h['ID']} to voter")

    def _autopilot_cleanup_dead_servers(self) -> None:
        """Leader-side dead-server reaping (ref nomad/autopilot.go
        pruneDeadServers), driven by the stored autopilot config."""
        from .raft import RaftNode
        if not isinstance(self.raft, RaftNode) or not self.is_leader:
            return
        cfg = self.state.get_autopilot_config()
        if not cfg.get("CleanupDeadServers", True):
            return
        threshold = float(cfg.get("LastContactThresholdSec", 10.0))
        stabilization = float(cfg.get("ServerStabilizationTimeSec", 10.0))
        health = self.raft.server_health()
        # never remove below a majority of the current config (autopilot's
        # quorum guard)
        removable = len(health) - max(2, len(health) // 2 + 1)
        for s in health:
            if removable <= 0:
                break
            if s["Healthy"] or s["ID"] == self.raft.node_id:
                continue
            if s.get("KnownForSec", 0.0) < stabilization:
                # just joined: give it time to come up before reaping
                continue
            age = s["LastContactSec"]
            if age is None or age < threshold:
                # None = no contact data (shouldn't happen on a leader past
                # election baseline) — never treat unknown as dead
                continue
            try:
                # bounded wait: a quorum-less cluster must not stall the
                # leader housekeeping loop for the full apply timeout
                self.raft.remove_peer(s["ID"], timeout=5.0)
                self.logger(f"autopilot: removed dead server {s['ID']}")
                removable -= 1
            except Exception as e:  # noqa: BLE001
                self.logger(f"autopilot: remove failed: {e!r}")
                break

    def get_scheduler_configuration(self) -> SchedulerConfiguration:
        return self.state.get_scheduler_config()

    def set_scheduler_configuration(self, config: SchedulerConfiguration
                                    ) -> dict:
        err = config.validate()
        if err:
            raise ValueError(err)
        index = self.raft.apply(SCHEDULER_CONFIG, {"config": config})
        return {"index": index}

    # ----------------------------------------------------------- utilities

    def status_summary(self) -> dict:
        """GET /v1/status: liveness + the overload/pressure block
        (docs/OVERLOAD.md). Served locally by any server — a follower
        reports its own (idle) pressure, which is itself informative."""
        return {
            "Leader": self.is_leader,
            "Name": self.name,
            "Pressure": self.overload.snapshot(),
            "Broker": dict(self.eval_broker.stats),
        }

    def operator_debug_bundle(self) -> dict:
        """GET /v1/operator/debug (ISSUE 11): one self-contained snapshot
        of everything an operator needs to explain THIS server's behavior
        after the fact — metrics, recent traces, pressure/broker/state-
        cache/breaker internals, the latest placement-explain records and
        the device-runtime telemetry — the server-side block `nomad-tpu
        operator debug` folds into its timestamped archive
        (docs/OBSERVABILITY.md lists the format). Read-only and local:
        every block samples in-process state, no raft round."""
        faults.fire("operator.debug")
        from ..api_codec import to_api
        from ..obs import devruntime
        from ..obs import trace as obs_trace
        from ..solver import backend as solver_backend
        from ..solver import explain as solver_explain
        from ..solver import sharding as solver_sharding
        from ..solver import state_cache
        # spec wall clock: capture timestamps are observability data
        # nomadlint: disable=DET001 — capture timestamp, not a decision
        captured = time.time()
        breaker = solver_backend.breaker()
        tiers = ("sharded", "pallas", "batch", "xla", "host")
        raft_block: dict = {"Enabled": self.raft_node is not None}
        if self.raft_node is not None:
            raft_block.update({
                "Term": self.raft_node.current_term,
                "CommitIndex": self.raft_node.commit_index,
                "LastApplied": self.raft_node.last_applied,
                "State": self.raft_node.state,
                "Health": self.raft_node.server_health(),
            })
            # durable-storage state (ISSUE 13, docs/DURABILITY.md):
            # generation, fsync discipline + counters, and how the last
            # boot recovered (tail truncation / quarantine / migration)
            dur = self.raft_node._durable
            raft_block["Durability"] = {
                "Stats": dur.stats() if dur is not None else None,
                "Restore": {
                    "Quarantined": self.raft_node.log_quarantined,
                    "TailTruncatedFrames":
                        self.raft_node.log_tail_truncated,
                    "Migrated": self.raft_node.log_migrated,
                },
            }
        return {
            "Meta": {
                "Name": self.name,
                "Leader": self.is_leader,
                "CapturedUnix": round(captured, 3),
                "EstablishTimings": dict(self._establish_timings),
            },
            "Status": self.status_summary(),
            "Metrics": metrics.snapshot(),
            "DeviceRuntime": devruntime.snapshot(),
            "Traces": {"Stats": obs_trace.stats(),
                       "Recent": obs_trace.traces(50)},
            "Explains": solver_explain.recent(64),
            "StateCache": state_cache.cache().stats(),
            # elastic-mesh state (ISSUE 14, docs/SHARDED_SOLVE.md):
            # generation, quarantined devices, surviving shard count —
            # plus the mesh counters an operator reads after a loss
            "Mesh": {
                **solver_sharding.describe(),
                "Rebuilds": int(metrics.counter("nomad.mesh.rebuilds")),
                "Replays": int(metrics.counter("nomad.mesh.replays")),
                "Evacuations": int(metrics.counter(
                    "nomad.solver.state_cache.evacuations")),
            },
            "Breakers": {t: breaker.state(t) for t in tiers},
            "BlockedEvals": dict(self.blocked_evals.stats),
            "SchedulerConfig": to_api(self.state.get_scheduler_config()),
            "Raft": raft_block,
            # partition-event forensics (ISSUE 18, docs/PARTITIONS.md):
            # per-peer outbound breaker state, dedup cache occupancy, and
            # the rpc retry/shed counters — one capture answers "which
            # link was down, what got retried, what got shed"
            "Rpc": {
                "Breakers": (self.rpc_server.rpc_breaker.snapshot()
                             if self.rpc_server is not None else {}),
                "Dedup": self.write_dedup.stats(),
                "Counters": {
                    k: int(metrics.counter(f"nomad.rpc.{k}"))
                    for k in ("retries", "failovers", "deadline_exceeded",
                              "dedup_hits", "breaker_open",
                              "breaker_closed")},
            },
        }

    def run_gc(self) -> None:
        """Force a full GC pass (the `nomad system gc` analog)."""
        self.core_scheduler.process(Evaluation(
            type=JOB_TYPE_CORE, job_id=CORE_JOB_FORCE_GC))

    def reconcile_summaries(self) -> dict:
        """Rebuild job summaries from allocs, replicated through Raft
        (ref nomad/system_endpoint.go ReconcileJobSummaries)."""
        from .fsm import RECONCILE_SUMMARIES
        index = self.raft.apply(RECONCILE_SUMMARIES, {})
        return {"index": index}

    def snapshot_save(self) -> bytes:
        return self.raft.snapshot()

    def snapshot_restore(self, data: bytes) -> None:
        self.raft.restore(data)
