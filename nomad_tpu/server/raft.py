"""Raft consensus (ref hashicorp/raft as wired in nomad/server.go:1221
setupRaft; nomad/leader.go:56 monitorLeadership): leader election, log
replication, and FSM snapshots over the framework's RPC transport.

TPU-native design note (SURVEY.md §2.7): consensus is a DCN protocol between
control-plane hosts — deliberately independent of the JAX/ICI compute path.
The contract it keeps for the scheduler is the same as the single-node
``RaftLog``: ``apply()`` returns only after the message is durably committed
and visible in the local FSM at the returned index, and every replica applies
the identical message sequence (replay determinism; the scheduler's
snapshot-min-index barrier, nomad/worker.go:536, builds on this).

Persistence (checkpoint/resume, SURVEY.md §5; crash consistency, ISSUE
13): term/vote in a crc-enveloped metadata file, log entries in an
append-only WAL whose frames carry (index, term, crc32) headers, FSM
snapshots + log generations named by an atomically-replaced MANIFEST —
all through `server/durable.py` (fsync discipline, fault sites, torn-
write recovery: docs/DURABILITY.md). A restarted server restores its
FSM from the snapshot and reloads the log; entries past the snapshot
re-apply through the applier only as commitment is re-established (ref
raft-boltdb + fsm.go Snapshot/Restore; an ex-leader's unsynced tail may
be truncated by the next leader, so it must never be applied eagerly at
boot).
"""
from __future__ import annotations

import heapq
import itertools
import os
import random
import threading
import time
from collections import deque
from typing import Callable, Optional

from .. import chrono
from ..metrics import metrics
from ..rpc.codec import FencedWriteError, LeadershipLostError, NotLeaderError

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

_CONFIG_TYPES = ("_config_add", "_config_remove")


class _Entry:
    __slots__ = ("term", "type", "payload")

    def __init__(self, term: int, type_: str, payload):
        self.term = term
        self.type = type_
        self.payload = payload


class _Proposal:
    """One queued apply() call riding the group-commit pipeline (ISSUE
    20). Acks are PER-PROPOSAL: `done` is set only on a terminal event
    for THIS proposal — staging failure (`error`), config append (those
    callers return at append), or its own index becoming applied — so a
    waiter never wakes for a batch-mate's progress (wake-by-index)."""
    __slots__ = ("msg_type", "payload", "fence", "index", "term",
                 "error", "appended", "done")

    def __init__(self, msg_type: str, payload, fence: Optional[int]):
        self.msg_type = msg_type
        self.payload = payload
        self.fence = fence
        self.index = 0
        self.term = 0
        self.error: Optional[BaseException] = None
        self.appended = False
        self.done = threading.Event()


class RaftNode:
    """One consensus participant. Peers are {server_id: rpc_addr}; the RPC
    handlers are registered on the server's RpcServer so Raft traffic shares
    the agent's single TCP listener (the reference multiplexes Raft on its
    RPC port the same way, nomad/rpc.go:341)."""

    def __init__(self, fsm, node_id: str, rpc_server, peers: dict[str, str],
                 data_dir: Optional[str] = None, logger=None,
                 election_timeout: tuple[float, float] = (0.4, 0.8),
                 heartbeat_interval: float = 0.1,
                 snapshot_threshold: int = 8192,
                 bootstrap: bool = True,
                 clock: Optional[chrono.Clock] = None,
                 seed: Optional[int] = None):
        self.fsm = fsm
        self.node_id = node_id
        # every timing DECISION (election deadlines, contact ages) reads
        # this clock; a chrono.ManualClock makes elections fire exactly
        # when a test advances time (ISSUE 6). Thread poll cadences stay
        # real — see chrono.py.
        self.clock = clock or chrono.REAL
        # election jitter from a private RNG: with an explicit seed the
        # campaign ORDER of a cluster is reproducible run to run (the
        # deterministic multi-server tests seed s0 < s1 < s2)
        self._rng = random.Random(seed) if seed is not None \
            else random.Random()
        # bootstrap=False: an expansion server (gossip auto-join, ref
        # bootstrap_expect) — it must NOT self-elect while its config is
        # the trivial {self}; it waits to be adopted by a leader's
        # _config_add and only then participates in elections
        self.bootstrap = bootstrap
        self.rpc_server = rpc_server
        self.addr = rpc_server.addr
        self.logger = logger or (lambda msg: None)
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        self.snapshot_threshold = snapshot_threshold
        self.data_dir = data_dir
        self._durable = None
        if data_dir:
            from .durable import DurableRaftDir
            self._durable = DurableRaftDir(
                data_dir, policy_fn=self._fsync_policy,
                logger=lambda m: self.logger(m), scope=node_id)
        # restore telemetry (tests + the operator debug bundle): how the
        # last boot had to recover its on-disk state
        self.log_quarantined = False
        self.log_tail_truncated = 0
        self.log_migrated = False

        self._lock = threading.RLock()
        self._apply_cond = threading.Condition(self._lock)
        self._commit_cond = threading.Condition(self._lock)
        # Serializes every DurableRaftDir touch (ISSUE 20): the group
        # committer writes its batch OUTSIDE self._lock (so enqueuers
        # never block on an in-flight fsync), but meta persists, the
        # follower append path, compaction and snapshot installs all
        # write under self._lock — without this second lock a step-down
        # mid-batch could interleave two writers in one WAL file. Lock
        # ORDER: _lock -> _disk_lock, and never acquire _lock while
        # holding _disk_lock (the committer releases it before
        # re-entering _lock to publish).
        self._disk_lock = threading.Lock()
        # group-commit pipeline state (all guarded by self._lock): FIFO
        # of staged proposals + the single-committer flag. The committer
        # is the FIRST enqueuing caller; everything that queues while
        # its batch is appending/fsyncing lands in the NEXT batch —
        # self-clocking, no timer, no added latency floor.
        self._proposals: deque[_Proposal] = deque()
        self._committer_busy = False
        # wake-by-index commit waiters: (index, seq, proposal) min-heap;
        # the applier pops exactly the prefix the new last_applied
        # covers instead of broadcasting to every waiter (ISSUE 20
        # satellite — the thundering herd matters exactly when group
        # commit raises writer concurrency).
        self._commit_waiters: list = []
        self._waiter_seq = itertools.count()
        # True between a batch's durable append and its publish into
        # self.log: compaction must not regenerate the WAL inside that
        # window (the new generation is built from self.log, which does
        # not hold the in-flight frames yet — they would vanish from
        # disk the moment the batch publishes and acks)
        self._commit_in_flight = False

        # persistent state
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log: list[_Entry] = []      # log[i] has index base_index + i + 1
        self.base_index = 0              # last index covered by the snapshot
        self.base_term = 0
        self.peers = dict(peers)         # id -> addr, includes self
        # autopilot non-voting members (raft-autopilot AddNonvoter): fully
        # replicated to, but excluded from elections and commit quorums
        # until promoted after stabilizing
        self.nonvoters: set[str] = set()
        # configuration as of base_index (snapshot point); the live config
        # is always _base_peers + the _config_* entries in the log, so a
        # truncated config entry can be rolled back (Raft §4.1: servers
        # adopt the latest configuration entry in their log at append time)
        self._base_peers = dict(peers)
        self._base_nonvoters: set[str] = set()

        # volatile state
        self.state = FOLLOWER
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id: Optional[str] = None
        self.leader_addr = ""
        self._last_contact = self.clock.monotonic()
        self._votes = 0
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        self._last_ok: dict[str, float] = {}   # peer -> last successful repl
        now = self.clock.monotonic()
        self._peer_added_at: dict[str, float] = {p: now for p in peers}
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._replicate_events: dict[str, threading.Event] = {}
        # leadership observer (Server establish/revoke), called off-lock
        self.on_leadership_change: Callable[[bool], None] = lambda lead: None

        self._restore_from_disk()

        rpc_server.register("Raft.RequestVote", self._rpc_request_vote)
        rpc_server.register("Raft.AppendEntries", self._rpc_append_entries)
        rpc_server.register("Raft.InstallSnapshot", self._rpc_install_snapshot)

    # ------------------------------------------------------------ indexing

    def _last_index(self) -> int:
        return self.base_index + len(self.log)

    def _term_at(self, index: int) -> int:
        if index == self.base_index:
            return self.base_term
        if index < self.base_index or index > self._last_index():
            return -1
        return self.log[index - self.base_index - 1].term

    def _entry_at(self, index: int) -> _Entry:
        return self.log[index - self.base_index - 1]

    # --------------------------------------------------------- persistence

    def _fsync_policy(self) -> tuple:
        """-> (mode, interval_s) for the durable dir. Reads the raft-
        replicated SchedulerConfiguration each call — the same hot-
        reload path as every other runtime knob; NOMAD_RAFT_FSYNC
        (`mode` or `mode:interval_ms`) force-overrides for bench legs
        and tests."""
        env = os.environ.get("NOMAD_RAFT_FSYNC", "")
        if env:
            mode, _, iv = env.partition(":")
            if mode in ("always", "interval", "never"):
                try:
                    interval = float(iv) / 1000.0 if iv else 0.05
                except ValueError:
                    interval = 0.05
                return mode, interval
        try:
            cfg = self.fsm.state.get_scheduler_config()
            return cfg.raft_fsync, cfg.raft_fsync_interval_ms / 1000.0
        except Exception:       # noqa: BLE001 — config unreadable mid-
            return "always", 0.0    # restore: default to safety

    def _group_commit_max(self) -> int:
        """Group-commit window ceiling (ISSUE 20): how many queued
        proposals one committer drain may stage into a SINGLE WAL
        append + fsync. 1 = today's serial one-entry-per-sync shape
        (the differential-test oracle). Same hot-reload plumbing as
        _fsync_policy; NOMAD_RAFT_GROUP_COMMIT force-overrides for
        bench legs and the crash fuzzer."""
        env = os.environ.get("NOMAD_RAFT_GROUP_COMMIT", "")
        if env:
            try:
                return max(1, int(env))
            except ValueError:
                pass
        try:
            return max(1, int(self.fsm.state.get_scheduler_config()
                              .raft_group_commit_max_entries))
        except Exception:   # noqa: BLE001 — config unreadable mid-
            return 64           # restore: bounded default

    def _replicate_batch_max(self) -> int:
        """Per-AppendEntries shipping window (ISSUE 20): the follower
        persists the whole batch with ONE fsync before acking."""
        env = os.environ.get("NOMAD_RAFT_REPL_BATCH", "")
        if env:
            try:
                return max(1, int(env))
            except ValueError:
                pass
        try:
            return max(1, int(self.fsm.state.get_scheduler_config()
                              .raft_replicate_batch_max))
        except Exception:   # noqa: BLE001 — config unreadable mid-
            return 1024         # restore: bounded default

    def _persist_meta(self) -> None:
        if self._durable is None:
            return
        with self._disk_lock:
            # _disk_lock EXISTS to serialize durable I/O (the state
            # lock is not held here) — nomadlint: disable=LOCK003
            self._durable.save_meta(
                {"term": self.current_term, "voted_for": self.voted_for,
                 "peers": self.peers, "nonvoters": set(self.nonvoters)})

    def _append_to_disk(self, entries: list[_Entry]) -> None:
        """Append the TAIL `entries` (already in self.log) to the WAL."""
        if self._durable is None or not entries:
            return
        start = self._last_index() - len(entries) + 1
        with self._disk_lock:
            self._durable.append(
                start, [(e.term, e.type, e.payload) for e in entries])

    def _rewrite_log_on_disk(self) -> None:
        """After truncation/conflict resolution: commit a new log
        generation under the manifest (the snapshot is untouched)."""
        if self._durable is None:
            return
        with self._disk_lock:
            # truncation must be durable before any later append lands
            # behind it; _disk_lock is the I/O serialization lock, not
            # the state lock — nomadlint: disable=LOCK003
            self._durable.commit_generation(
                None, [(e.term, e.type, e.payload) for e in self.log],
                self.base_index + 1)

    def _snapshot_doc(self, data: bytes) -> dict:
        return {"index": self.base_index, "term": self.base_term,
                "data": data, "peers": dict(self._base_peers),
                "nonvoters": set(self._base_nonvoters)}

    def _restore_from_disk(self) -> None:
        if self._durable is None:
            return
        st = self._durable.load()
        self.log_quarantined = st.quarantined
        self.log_tail_truncated = st.tail_truncated_frames
        self.log_migrated = st.migrated
        if st.snapshot is not None:
            snap = st.snapshot
            self.fsm.restore_bytes(snap["data"])
            self.base_index = snap["index"]
            self.base_term = snap["term"]
            if snap.get("peers"):
                # authoritative config at snapshot time: replace, don't
                # merge — a merge would resurrect removed peers
                self.peers = dict(snap["peers"])
                self._base_peers = dict(snap["peers"])
                self.nonvoters = set(snap.get("nonvoters", ()))
                self._base_nonvoters = set(snap.get("nonvoters", ()))
            self.commit_index = self.last_applied = self.base_index
        if st.meta is not None:
            meta = st.meta
            self.current_term = meta["term"]
            self.voted_for = meta["voted_for"]
            if meta.get("peers"):
                self.peers = dict(meta["peers"])
                self.nonvoters = set(meta.get("nonvoters", ()))
        if st.entries:
            # frames are self-identifying: durable.load() already
            # verified contiguity from base_index+1, CRC-truncated any
            # torn tail, and quarantined mid-file damage — what arrives
            # here is replayable by construction
            for _idx, term, type_, payload in st.entries:
                self.log.append(_Entry(term, type_, payload))
            # Membership is adopted from the log at restore (config is
            # append-time state in this design), but the FSM is NOT:
            # a restarted server cannot know which tail entries were
            # committed — an ex-leader's log may end in UNCOMMITTED
            # entries a new leader will truncate and replace. Eagerly
            # applying them bakes phantom state into the FSM AND pins
            # last_applied past the replaced indexes, so the
            # replacements (including this node's own re-add/promote
            # config entries after an autopilot removal) are silently
            # skipped — the multi-process e2e rejoin test caught a
            # restarted server stuck as a permanent self-nonvoter this
            # way. Like hashicorp/raft: FSM = snapshot; log entries
            # re-apply through the applier once a leader of the next
            # term re-establishes commitment (its election no-op).
            for e in self.log:
                if e.type == "_config_remove":
                    with self._lock:
                        self._apply_config_locked(e.payload)
                elif e.type == "_config_add":
                    with self._lock:
                        self._apply_config_add_locked(e.payload)
            self.commit_index = self.last_applied = self.base_index
            if self._voters() in ([], [self.node_id]):
                # sole voter: every entry in its own log IS committed
                # (majority of one) — eager replay keeps single-server
                # restarts serving immediately, with none of the
                # uncommitted-tail hazard above
                for i, e in enumerate(self.log):
                    idx = self.base_index + i + 1
                    if e.type in ("_config_remove", "_config_add",
                                  "_noop"):
                        continue
                    try:
                        self.fsm.apply(idx, e.type, e.payload)
                    except Exception as ex:   # noqa: BLE001
                        # a bad entry must never brick restart/replay
                        self.logger(
                            f"raft: fsm replay failed at {idx}: {ex!r}")
                self.commit_index = self.last_applied = self._last_index()
        if self.base_index or self.log:
            self.logger(
                f"raft: {self.node_id} restored snapshot to index "
                f"{self.base_index}, log to index {self._last_index()} "
                f"(term {self.current_term}); uncommitted tail applies "
                f"once a leader re-establishes commitment")

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._stop.clear()
        t = threading.Thread(target=self._run_elections, daemon=True,
                             name=f"raft-elect-{self.node_id}")
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._run_apply, daemon=True,
                             name=f"raft-apply-{self.node_id}")
        t.start()
        self._threads.append(t)

    def shutdown(self) -> None:
        self._stop.set()
        with self._lock:
            self._commit_cond.notify_all()
            self._apply_cond.notify_all()
            for ev in self._replicate_events.values():
                ev.set()
            # release apply() waiters promptly: commit waiters break on
            # the stop flag once woken (same contract as the old
            # cond-broadcast shutdown)
            while self._commit_waiters:
                heapq.heappop(self._commit_waiters)[2].done.set()
        if self._durable is not None:
            with self._disk_lock:
                self._durable.close()

    # ------------------------------------------------------- public: apply

    def _voters(self) -> list[str]:
        return [pid for pid in self.peers if pid not in self.nonvoters]

    def fence_token(self) -> Optional[int]:
        """The leadership fence (ISSUE 6): the current term while this
        node is leader, else None. A caller that captured the token
        before a side-effect-free preparation phase (the plan applier's
        batch evaluation) passes it back to `apply(fence=...)` — the
        write is rejected ATOMICALLY, before the entry is appended, if
        leadership was lost (or lost and re-won at a higher term, i.e.
        state may have changed under an interim leader) in between.
        Contract: docs/FAILOVER.md."""
        with self._lock:
            return self.current_term if self.state == LEADER else None

    def apply(self, msg_type: str, payload, timeout: float = 30.0,
              fence: Optional[int] = None):
        """Commit one message through the replicated log. Leader-only;
        raises NotLeaderError with a redirect hint on followers.

        `timeout` is the caller's remaining budget for THIS message, not
        a per-message constant: the coalescing plan applier passes the
        remainder of its per-batch budget, so a batch of N plans riding
        one entry never waits N x 30s (docs/COMMIT_COALESCING.md). A
        timeout is counted (`nomad.raft.apply_timeout`) — the plan
        applier layers its per-plan `nomad.plan.commit_timeout` on top.

        `fence` (a fence_token() value) rejects the write atomically —
        FencedWriteError, entry NOT appended, commit provably impossible
        — when the term has moved since the token was captured.

        Group commit (ISSUE 20): callers ENQUEUE proposals; the first
        enqueuer becomes the committer and drains everything queued
        while the previous batch was appending/fsyncing into ONE
        multi-entry WAL append (one fsync at raft_fsync=always). Acks
        stay per-proposal: this caller returns only once ITS index is
        durable and applied, and a persist failure fails the whole
        batch with nothing entered into memory (the PR-13 memory==disk
        invariant at batch granularity, via disk-first staging)."""
        from .. import faults
        faults.fire("raft.apply")
        faults.fire(f"raft.apply.{self.node_id}")
        # idempotency stamp (ISSUE 18): a dedup-tokened RPC dispatch on
        # this thread marks the entry BEFORE append, so the ack
        # replicates with the write and survives failover (rpc/dedup.py)
        from ..rpc import dedup as rpc_dedup
        payload = rpc_dedup.stamp(payload)
        t_enter = time.monotonic()
        prop = _Proposal(msg_type, payload, fence)
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_addr)
            if fence is not None and fence != self.current_term:
                # deposed (and possibly re-elected at a higher term)
                # since the caller captured its token: the caller's
                # prepared write raced another leader's commits. Checked
                # under the SAME lock that serializes step-down, so the
                # rejection is atomic with the append decision (the
                # committer re-checks at staging time for proposals that
                # queue before a step-down lands).
                metrics.incr("nomad.raft.fence_rejected")
                from ..obs import trace
                trace.annotate(fence_rejected=True, fence_expected=fence,
                               fence_current=self.current_term)
                raise FencedWriteError(self.current_term, fence,
                                       self.leader_addr)
            self._proposals.append(prop)
            run_committer = not self._committer_busy
            if run_committer:
                self._committer_busy = True
        if run_committer:
            self._commit_proposals()
        deadline = t_enter + timeout
        index = 0
        while True:
            with self._lock:
                if prop.error is not None:
                    raise prop.error
                if prop.appended:
                    index = prop.index
                    if msg_type in _CONFIG_TYPES:
                        # membership changes take effect at append
                        # (adopted by the committer) and commit
                        # asynchronously once the NEW majority acks —
                        # blocking here would deadlock a 1→2 addition
                        # where the joining server only starts raft
                        # after `join` returns (hashicorp/raft AddVoter
                        # likewise returns an index future)
                        return index
                    if self.last_applied >= index or self._stop.is_set():
                        break
                    if self.state != LEADER:
                        # the entry IS appended; it may still commit
                        # under the next leader — callers must not
                        # retry/forward (ref hashicorp/raft
                        # ErrLeadershipLost)
                        raise LeadershipLostError(self.leader_addr)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    metrics.incr("nomad.raft.apply_timeout")
                    raise TimeoutError(
                        f"raft apply of {msg_type} timed out at index "
                        f"{prop.index} (budget {timeout:.1f}s)")
            # wait OUTSIDE the lock, in bounded slices: the wake is the
            # per-proposal event (set by the committer on failure or by
            # the applier exactly when this index is covered), and the
            # slices keep leadership loss / shutdown / timeout
            # observable even if no wake ever arrives
            prop.done.wait(min(remaining, 0.5))
        with self._lock:
            # leadership lost mid-wait: a new leader may have overwritten
            # our uncommitted entry at this index (hashicorp/raft returns
            # ErrLeadershipLost for exactly this)
            if index > self.base_index and \
                    self._term_at(index) != prop.term:
                raise LeadershipLostError(self.leader_addr)
        metrics.add_sample("nomad.raft.apply_wait",
                           time.monotonic() - t_enter)
        # attribute the replication wait + assigned index onto the
        # caller's in-flight span (the applier's plan.commit, ISSUE 7)
        from ..obs import trace
        trace.annotate(raft_index=index, term=prop.term,
                       replicate_wait_s=round(
                           time.monotonic() - t_enter, 6))
        return index

    def _commit_proposals(self) -> None:
        """THE group committer (ISSUE 20): runs on the first enqueuing
        caller's thread and drains the proposal queue batch by batch
        until it is empty, then clears the busy flag — so any queued
        proposal always has a live committer, and an idle leader's lone
        proposal commits immediately on its own thread (no timer, no
        handoff latency).

        Disk-first staging keeps memory == disk at batch granularity:
        staged entries enter self.log only AFTER the durable append
        succeeds, so the replicate loops can never ship an entry that a
        persist failure would roll back (same-index+term ⇒ same-entry
        stays inviolate), and a failed batch leaves memory untouched —
        every batch-mate fails, none half-lands. A batch orphaned on
        disk by a mid-write deposition resolves at the next append or
        boot through the WAL's index-regression later-write-wins rule
        (docs/DURABILITY.md)."""
        while True:
            with self._lock:
                if not self._proposals:
                    if (self.last_applied < self.commit_index
                            and not self._stop.is_set()):
                        # an apply window is in flight: park (bounded)
                        # instead of resigning the committer role. The
                        # waiters that apply wakes re-enqueue into ONE
                        # drain here, rather than racing a fresh
                        # committer one at a time — the thundering-herd
                        # shape that halves batch sizes under storm
                        # load. An idle leader never enters this arm
                        # (applier caught up ⇒ immediate exit), so the
                        # lone-proposal latency floor stays zero.
                        self._apply_cond.wait(0.05)
                        continue
                    self._committer_busy = False
                    return
                if self._stop.is_set():
                    while self._proposals:
                        p = self._proposals.popleft()
                        p.error = NotLeaderError(self.leader_addr)
                        p.done.set()
                    self._committer_busy = False
                    return
                limit = self._group_commit_max()
                batch = []
                while self._proposals and len(batch) < limit:
                    batch.append(self._proposals.popleft())
                if self.state != LEADER:
                    for p in batch:
                        p.error = NotLeaderError(self.leader_addr)
                        p.done.set()
                    continue
                term = self.current_term
                accepted = []
                for p in batch:
                    if p.fence is not None and p.fence != term:
                        # the term moved while this proposal sat queued:
                        # same atomic rejection as the enqueue-time
                        # check — the entry is provably not appended
                        metrics.incr("nomad.raft.fence_rejected")
                        p.error = FencedWriteError(term, p.fence,
                                                   self.leader_addr)
                        p.done.set()
                        continue
                    accepted.append(p)
                if not accepted:
                    continue
                start = self._last_index() + 1
                frames = []
                for off, p in enumerate(accepted):
                    p.term = term
                    p.index = start + off
                    frames.append((term, p.msg_type, p.payload))
                durable = self._durable
                self._commit_in_flight = True
                # take the disk lock BEFORE releasing the state lock
                # (consistent _lock -> _disk_lock order): from here to
                # release, no other durable writer can interleave with
                # this batch's frames
                self._disk_lock.acquire()
            persist_err: Optional[BaseException] = None
            try:
                if durable is not None:
                    try:
                        # one append per drained WINDOW, never per
                        # entry — this IS the amortized batch call:
                        # nomadlint: disable=DUR002 — per-window batch
                        durable.append(start, frames)
                    except Exception as e:   # noqa: BLE001
                        persist_err = e
            finally:
                self._disk_lock.release()
            if persist_err is None:
                try:
                    # crash window between the durable batch append and
                    # its acks (ISSUE 20 fuzzer site): treated exactly
                    # like a persist failure — nothing entered memory,
                    # the indexes will be re-staged, and the orphaned
                    # frames resolve by the index-regression rule
                    from .. import faults
                    faults.fire("raft.group_commit.ack")
                    faults.fire(f"raft.group_commit.ack.{self.node_id}")
                except Exception as e:   # noqa: BLE001
                    persist_err = e
            with self._lock:
                self._commit_in_flight = False
                if persist_err is not None:
                    # durability first: the WHOLE batch's callers see
                    # the failure and no entry is visible to
                    # replication or the FSM — memory and disk stay one
                    # object (any flushed prefix is superseded on the
                    # next append at the same indexes)
                    metrics.incr("nomad.raft.persist_errors")
                    for p in accepted:
                        p.error = persist_err
                        p.done.set()
                    continue
                if self.state != LEADER or self.current_term != term:
                    # deposed while the batch was on its way to disk:
                    # disk-first staging means self.log never saw these
                    # entries, so there is nothing to roll back
                    for p in accepted:
                        p.error = LeadershipLostError(self.leader_addr)
                        p.done.set()
                    continue
                if self._last_index() + 1 != start:
                    # a leader-elect establishment batch landed between
                    # staging and publish (state flips to LEADER before
                    # _become_leader appends): the reserved indexes
                    # moved under us. Re-stage the same proposals at
                    # the head of the queue — the superseding append
                    # overwrites the orphaned frames.
                    for p in reversed(accepted):
                        self._proposals.appendleft(p)
                    continue
                for p in accepted:
                    e = _Entry(term, p.msg_type, p.payload)
                    self.log.append(e)
                    if p.msg_type in _CONFIG_TYPES:
                        # adopt the new configuration at append time
                        # (§4.1); a leader removing itself keeps
                        # replicating but no longer counts toward
                        # majority, and steps down only once the entry
                        # commits (§4.2.2, handled by the apply loop)
                        self._adopt_config_locked(e)
                        p.appended = True
                        p.done.set()   # config callers return at append
                    else:
                        p.appended = True
                        heapq.heappush(
                            self._commit_waiters,
                            (p.index, next(self._waiter_seq), p))
                self._match_index[self.node_id] = self._last_index()
                metrics.add_sample("nomad.raft.batch_entries",
                                   len(accepted))
                for ev in self._replicate_events.values():
                    ev.set()
                if len(self._voters()) == 1:
                    self._advance_commit_locked()

    def bootstrap_with(self, peers: dict[str, str]) -> bool:
        """One-shot cluster bootstrap with a full initial configuration
        (ref serf maybeBootstrap -> raft.BootstrapCluster): every server
        of a bootstrap_expect=N group calls this with the SAME sorted
        member set once gossip has found N servers, then elections run
        over that config. No-op unless this node is still pristine."""
        with self._lock:
            if self._last_index() > 0 or len(self.peers) > 1:
                return False            # already part of a cluster
            self.peers = dict(peers)
            self._base_peers = dict(peers)
            self.bootstrap = True
            self._persist_meta()
            return True

    def add_peer(self, peer_id: str, addr: str, timeout: float = 30.0,
                 voter: bool = True) -> int:
        """Single-entry membership addition (ref raft AddVoter /
        AddNonvoter): replicate a _config_add entry; the leader starts
        replicating to the new peer on apply. Non-voters receive the full
        log but stay out of quorums until promote_peer."""
        with self._lock:
            if peer_id in self.peers and self.peers[peer_id] == addr and \
                    (peer_id not in self.nonvoters) == voter:
                return self.last_applied
        return self.apply("_config_add", (peer_id, addr, voter),
                          timeout=timeout)

    def promote_peer(self, peer_id: str, timeout: float = 30.0) -> int:
        """Non-voter -> voter (raft-autopilot promotion after the server
        stabilization window)."""
        with self._lock:
            if peer_id not in self.nonvoters:
                return self.last_applied
            addr = self.peers.get(peer_id, "")
        return self.apply("_config_add", (peer_id, addr, True),
                          timeout=timeout)

    def remove_peer(self, peer_id: str, timeout: float = 30.0) -> int:
        """Single-entry membership change: replicate a _config_remove entry;
        every node drops the peer on apply (ref raft RemoveServer /
        operator raft remove-peer). Removing self steps down."""
        if peer_id not in self.peers:
            raise ValueError(f"unknown raft peer {peer_id!r}")
        if len(self.peers) <= 1:
            raise ValueError("cannot remove the last raft peer")
        return self.apply("_config_remove", peer_id, timeout=timeout)

    def _adopt_config_locked(self, entry: "_Entry") -> None:
        """Structural config change without the leader-self-removal
        step-down — safe to run at append time and idempotent at commit."""
        if entry.type == "_config_add":
            self._apply_config_add_locked(entry.payload)
            return
        pid = entry.payload
        self.peers.pop(pid, None)
        self.nonvoters.discard(pid)
        self._next_index.pop(pid, None)
        self._match_index.pop(pid, None)
        ev = self._replicate_events.pop(pid, None)
        if ev is not None:
            ev.set()    # wake the loop so it notices removal and exits
        self._peer_added_at.pop(pid, None)
        self._persist_meta()

    def _recompute_config_locked(self) -> None:
        """Rebuild the configuration from the snapshot-point config plus
        every _config_* entry still in the log. Called after log truncation
        on a follower: a conflicting leader may have removed an appended
        (never-committed) config entry, which must be rolled back."""
        peers = dict(self._base_peers)
        nonvoters = set(self._base_nonvoters)
        for e in self.log:
            if e.type == "_config_add":
                pid, addr, voter = e.payload if len(e.payload) == 3 \
                    else (*e.payload, True)
                peers[pid] = addr
                if voter:
                    nonvoters.discard(pid)
                else:
                    nonvoters.add(pid)
            elif e.type == "_config_remove":
                peers.pop(e.payload, None)
                nonvoters.discard(e.payload)
        if peers != self.peers or nonvoters != self.nonvoters:
            self.peers = peers
            self.nonvoters = nonvoters
            self._persist_meta()

    def _apply_config_locked(self, payload) -> None:
        pid = payload
        self._adopt_config_locked(_Entry(0, "_config_remove", pid))
        if pid == self.node_id and self.state == LEADER:
            self._step_down_locked(self.current_term)

    def _apply_config_add_locked(self, payload) -> None:
        pid, addr, voter = payload if len(payload) == 3 else (*payload, True)
        if pid in self.peers:
            self.peers[pid] = addr
            if voter:
                self.nonvoters.discard(pid)
            else:
                self.nonvoters.add(pid)
            self._persist_meta()
            return
        self.peers[pid] = addr
        if not voter:
            self.nonvoters.add(pid)
        self._peer_added_at[pid] = self.clock.monotonic()
        self._persist_meta()
        if self.state == LEADER:
            self._next_index[pid] = self._last_index() + 1
            self._match_index[pid] = 0
            ev = threading.Event()
            self._replicate_events[pid] = ev
            t = threading.Thread(target=self._replicate_loop, daemon=True,
                                 args=(pid, self.current_term),
                                 name=f"raft-repl-{pid}")
            t.start()
            self._threads.append(t)
            ev.set()

    def server_health(self) -> list[dict]:
        """Per-peer replication health (operator autopilot health analog)."""
        with self._lock:
            now = self.clock.monotonic()
            is_leader = self.state == LEADER
            out = []
            for pid, addr in sorted(self.peers.items()):
                known_for = now - self._peer_added_at.get(pid, now)
                if pid == self.node_id:
                    healthy, age = True, 0.0
                elif is_leader:
                    last = self._last_ok.get(pid)
                    age = (now - last) if last is not None else float("inf")
                    healthy = age < max(1.0, self.heartbeat_interval * 10)
                elif pid == self.leader_id:
                    # a follower knows only its leader's liveness
                    age = now - self._last_contact
                    healthy = age < max(1.0, self.election_timeout[0])
                else:
                    # unknown from here: only the leader tracks replication
                    age, healthy = None, None
                out.append({
                    "ID": pid, "Address": addr,
                    "Leader": pid == self.node_id and is_leader
                    or pid == self.leader_id,
                    "Voter": pid not in self.nonvoters,
                    "Healthy": healthy,
                    "LastContactSec": None
                    if age in (None, float("inf")) else age,
                    "KnownForSec": known_for,
                    "MatchIndex": self._match_index.get(pid, 0)
                    if is_leader else None,
                })
            return out

    def barrier(self) -> int:
        with self._lock:
            return self.last_applied

    def wait_barrier(self, timeout: float = 30.0) -> int:
        """Block until every entry in the log as of THIS call is applied
        to the FSM (ref hashicorp/raft Barrier, used by leader.go:224
        establishLeadership). A new leader's log already ends with its
        election no-op (§8), so waiting for the current last index
        guarantees all entries committed under previous terms are
        visible in state before the leader restores broker/watcher
        bookkeeping from it — without this, a freshly-elected leader can
        re-enqueue an eval whose plan it has not applied yet and place
        DUPLICATE allocations (caught by the multi-process e2e tier)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            target = self._last_index()
            while self.last_applied < target and not self._stop.is_set():
                if self.state != LEADER:
                    raise NotLeaderError(self.leader_addr)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"leadership barrier timed out at {target}")
                self._apply_cond.wait(min(remaining, 0.5))
            return self.last_applied

    def snapshot(self) -> bytes:
        return self.fsm.snapshot_bytes()

    def restore(self, data: bytes) -> None:
        """Operator-initiated restore (snapshot_restore endpoint)."""
        self.fsm.restore_bytes(data)

    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def quorum_fresh(self, window: Optional[float] = None) -> bool:
        """Leader-lease check (read-index lite, ISSUE 18): True iff this
        node is leader AND has replicated successfully to a voting
        quorum within `window` seconds (default: half the minimum
        election timeout — no rival can have been elected while a
        quorum was heard from inside that window). A leader that heals
        from a partition still believing it leads fails this check
        until its next successful replication round, so local-state
        fast paths (e.g. the unchanged-status heartbeat ack) must not
        vouch for reads taken from a possibly-deposed leader's state —
        acking a write from stale state LOSES it (docs/PARTITIONS.md)."""
        with self._lock:
            if self.state != LEADER:
                return False
            voters = [pid for pid in self.peers
                      if pid not in self.nonvoters]
            need = len(voters) // 2 + 1
            if need <= 1:
                return True
            w = window if window is not None \
                else self.election_timeout[0] / 2.0
            now = self.clock.monotonic()
            fresh = sum(
                1 for pid in voters
                if pid == self.node_id
                or now - self._last_ok.get(pid, float("-inf")) <= w)
            return fresh >= need

    def leadership(self) -> tuple[bool, str]:
        with self._lock:
            if self.state == CANDIDATE:
                # mid-election there is NO known leader: advertising the
                # deposed one would forward RPCs at a server we just
                # timed out on, and stale reads must be able to stamp
                # KnownLeader=False while a vote is in flight (ISSUE 16)
                return False, ""
            return self.state == LEADER, self.leader_addr

    # ----------------------------------------------------------- elections

    def _election_deadline(self) -> float:
        lo, hi = self.election_timeout
        return self.clock.monotonic() + self._rng.uniform(lo, hi)

    def _run_elections(self) -> None:
        deadline = self._election_deadline()
        while not self._stop.is_set():
            # REAL poll cadence by design: under a ManualClock the loop
            # keeps spinning but deadlines only expire when the test
            # advances virtual time (chrono.py)
            time.sleep(0.02)
            with self._lock:
                if self.state == LEADER:
                    deadline = self._election_deadline()
                    continue
                if self.clock.monotonic() < deadline:
                    continue
                # recent leader contact pushes the deadline instead of
                # triggering an election
                lo, _hi = self.election_timeout
                if self.clock.monotonic() - self._last_contact < lo:
                    deadline = self._last_contact + \
                        self._rng.uniform(*self.election_timeout)
                    continue
                # a non-bootstrap server with only itself in config is
                # waiting for adoption, not for votes; a non-voter never
                # campaigns at all (raft-autopilot nonvoter semantics)
                if not self.bootstrap and len(self.peers) <= 1:
                    deadline = self._election_deadline()
                    continue
                if self.node_id in self.nonvoters:
                    deadline = self._election_deadline()
                    continue
                prev_term, prev_vote = self.current_term, self.voted_for
                self.current_term += 1
                self.voted_for = self.node_id
                try:
                    self._persist_meta()
                except Exception as e:   # noqa: BLE001
                    # an unpersisted self-vote must never be acted on: a
                    # crash would forget it and this term could see a
                    # second vote — revert to the PRIOR persisted pair
                    # (never to None: that would erase the memory of a
                    # vote already granted in prev_term and allow a
                    # second grant there) and retry next deadline
                    self.current_term = prev_term
                    self.voted_for = prev_vote
                    metrics.incr("nomad.raft.persist_errors")
                    self.logger(f"raft: vote persist failed, campaign "
                                f"aborted: {e!r}")
                    deadline = self._election_deadline()
                    continue
                self.state = CANDIDATE
                self._votes = 1
                term = self.current_term
                self.logger(f"raft: {self.node_id} campaigning "
                            f"(term {term})")
                last_idx = self._last_index()
                last_term = self._term_at(last_idx)
                peers = {pid: addr for pid, addr in self.peers.items()
                         if pid != self.node_id and
                         pid not in self.nonvoters}
                deadline = self._election_deadline()
            if not peers:
                self._become_leader(term)
                continue
            for pid, addr in peers.items():
                threading.Thread(
                    target=self._request_vote_from, daemon=True,
                    args=(pid, addr, term, last_idx, last_term)).start()

    def _request_vote_from(self, pid, addr, term, last_idx, last_term):
        try:
            with self.rpc_server.client_for(addr, timeout=1.0) as cli:
                resp = cli.call("Raft.RequestVote", term, self.node_id,
                                last_idx, last_term)
        except Exception:    # noqa: BLE001
            return
        with self._lock:
            if resp["term"] > self.current_term:
                self._step_down_locked(resp["term"])
                return
            if self.state != CANDIDATE or term != self.current_term:
                return
            if resp["granted"]:
                self._votes += 1
                if self._votes * 2 > len(self._voters()):
                    # transition exactly once: later vote responses see
                    # state != CANDIDATE and bail above
                    self.state = LEADER
                    threading.Thread(target=self._become_leader, daemon=True,
                                     args=(term,)).start()

    def _become_leader(self, term: int) -> None:
        with self._lock:
            if self.current_term != term:
                return
            self.state = LEADER     # idempotent for the self-elect path
            self.leader_id = self.node_id
            self.leader_addr = self.addr
            nxt = self._last_index() + 1
            self._next_index = {pid: nxt for pid in self.peers}
            self._match_index = {pid: 0 for pid in self.peers}
            self._match_index[self.node_id] = self._last_index()
            # baseline contact at election: a fresh leader must not report
            # never-contacted-yet peers as long-dead (autopilot would reap
            # a briefly-slow follower right after failover)
            now = self.clock.monotonic()
            self._last_ok = {pid: now for pid in self.peers}
            # commit a no-op entry to finalize commitment of prior terms
            # (Raft §8: a leader may only count replicas of current-term
            # entries toward commit)
            noop = _Entry(term, "_noop", {})
            # make membership fully log-described: re-append the current
            # config so servers adopted later (gossip auto-join with a
            # trivial {self} base config) learn EVERY member — including
            # those only present in this leader's bootstrap config —
            # purely from the log. Idempotent at adopt/apply time.
            cfg_entries = [_Entry(term, "_config_add",
                                  (pid, addr, pid not in self.nonvoters))
                           for pid, addr in self.peers.items()]
            establish = [noop] + cfg_entries
            self.log.extend(establish)
            try:
                self._append_to_disk(establish)
            except Exception as e:   # noqa: BLE001
                # a leader that cannot write its own log cannot lead:
                # roll the entries back and step down — the next
                # election re-tries (possibly on healed disk)
                del self.log[-len(establish):]
                metrics.incr("nomad.raft.persist_errors")
                self.logger(f"raft: establishment append failed, "
                            f"stepping down: {e!r}")
                self._step_down_locked(self.current_term)
                return
            self._match_index[self.node_id] = self._last_index()
            peers = {pid: addr for pid, addr in self.peers.items()
                     if pid != self.node_id}
            if len(self._voters()) == 1:
                # sole voter: its own match IS the quorum — non-voter
                # peers must not gate commitment of the term's entries
                self._advance_commit_locked()
        self.logger(f"raft: {self.node_id} became leader (term {term})")
        for pid in peers:
            ev = threading.Event()
            ev.set()
            self._replicate_events[pid] = ev
            t = threading.Thread(target=self._replicate_loop, daemon=True,
                                 args=(pid, term), name=f"raft-repl-{pid}")
            t.start()
            self._threads.append(t)
        self.on_leadership_change(True)

    def _step_down_locked(self, term: int) -> None:
        was_leader = self.state == LEADER
        if term > self.current_term:
            # only a term bump may reset the vote (one vote per term)
            self.current_term = term
            self.voted_for = None
        self.state = FOLLOWER
        try:
            self._persist_meta()
        except Exception as e:   # noqa: BLE001
            # stepping down must never fail: callers include the
            # election/replication threads (an escaped exception kills
            # the daemon for good) and the establishment-failure path
            # (which would leave leader_id advertising a follower).
            # Vote safety is unaffected — any future grant/campaign
            # re-persists term+vote atomically BEFORE acting, and is
            # itself withheld when that persist fails
            metrics.incr("nomad.raft.persist_errors")
            self.logger(f"raft: meta persist failed during step-down "
                        f"(continuing as follower): {e!r}")
        if was_leader:
            self.leader_id = None
            self.leader_addr = ""
            threading.Thread(target=self.on_leadership_change, daemon=True,
                             args=(False,)).start()

    # --------------------------------------------------------- replication

    def _replicate_loop(self, pid: str, term: int) -> None:
        addr = self.peers.get(pid)
        if addr is None:
            return
        cli = self.rpc_server.client_for(addr, timeout=2.0)
        ev = self._replicate_events[pid]
        fails = 0
        try:
            while not self._stop.is_set():
                with self._lock:
                    if self.state != LEADER or self.current_term != term:
                        return
                    if pid not in self.peers:
                        return   # removed from the config mid-term
                ev.wait(self.heartbeat_interval)
                ev.clear()
                try:
                    self._replicate_once(cli, pid, term)
                    if fails >= 10:
                        self.logger(f"raft: replication to {pid} "
                                    f"recovered")
                    fails = 0
                except Exception as e:   # noqa: BLE001
                    fails += 1
                    if fails in (10, 100, 1000):   # once per decade, not
                        self.logger(           # one line per heartbeat
                            f"raft: replication to {pid} ({addr}) "
                            f"failing x{fails}: {e!r}")
                    time.sleep(self.heartbeat_interval)
        finally:
            cli.close()

    def _replicate_once(self, cli, pid: str, term: int) -> None:
        with self._lock:
            if self.state != LEADER or self.current_term != term \
                    or pid not in self.peers:
                return
            nxt = self._next_index.get(pid, self._last_index() + 1)
            if nxt <= self.base_index:
                # follower is behind our snapshot horizon
                # ship the config as of base_index, not the live one: the
                # receiver stores this as its rollback base, and live peers
                # may include uncommitted config entries past base_index
                snap = {"index": self.base_index, "term": self.base_term,
                        "data": self.fsm.snapshot_bytes(),
                        "peers": dict(self._base_peers),
                        "nonvoters": sorted(self._base_nonvoters)}
                commit = self.commit_index
            else:
                snap = None
                prev_idx = nxt - 1
                prev_term = self._term_at(prev_idx)
                # ship the full pending window, bounded by the hot-
                # reloadable replication knob (ISSUE 20): the follower
                # persists the whole batch with ONE fsync before acking
                win = self._replicate_batch_max()
                entries = [(e.term, e.type, e.payload)
                           for e in self.log[prev_idx - self.base_index:
                                             prev_idx - self.base_index
                                             + win]]
                if entries:
                    metrics.add_sample("nomad.raft.replicate_batch_entries",
                                       len(entries))
                commit = self.commit_index
        if snap is not None:
            resp = cli.call("Raft.InstallSnapshot", term, self.node_id,
                            self.addr, snap)
            with self._lock:
                if resp["term"] > self.current_term:
                    self._step_down_locked(resp["term"])
                    return
                self._next_index[pid] = snap["index"] + 1
                self._match_index[pid] = snap["index"]
                self._last_ok[pid] = self.clock.monotonic()
            return
        resp = cli.call("Raft.AppendEntries", term, self.node_id, self.addr,
                        prev_idx, prev_term, entries, commit)
        with self._lock:
            if resp["term"] > self.current_term:
                self._step_down_locked(resp["term"])
                return
            if self.state != LEADER or self.current_term != term:
                return
            if resp["success"]:
                match = prev_idx + len(entries)
                self._last_ok[pid] = self.clock.monotonic()
                self._match_index[pid] = max(self._match_index.get(pid, 0),
                                             match)
                self._next_index[pid] = self._match_index[pid] + 1
                self._advance_commit_locked()
                if self._next_index[pid] <= self._last_index():
                    ev = self._replicate_events.get(pid)
                    if ev is not None:
                        ev.set()   # more to send
            elif resp.get("retry"):
                # follower persist hiccup, not a conflict: keep
                # next_index where it is; the loop's heartbeat-interval
                # wait retries the identical batch until the disk heals
                pass
            else:
                # conflict: back up (follower hints its last index)
                hint = resp.get("last_index")
                self._next_index[pid] = max(
                    1, min(nxt - 1, (hint + 1) if hint is not None else nxt - 1))
                ev = self._replicate_events.get(pid)
                if ev is not None:
                    ev.set()

    def _advance_commit_locked(self) -> None:
        """Majority-match commit rule over VOTERS (current-term entries
        only; non-voters replicate but never count, raft §4.2.1)."""
        matches = sorted(self._match_index.get(pid, 0)
                         for pid in self._voters())
        majority_idx = matches[(len(matches) - 1) // 2]
        if majority_idx > self.commit_index and \
                self._term_at(majority_idx) == self.current_term:
            self.commit_index = majority_idx
            self._commit_cond.notify_all()

    # --------------------------------------------------------------- apply

    def _wake_applied_locked(self) -> None:
        """Wake exactly the apply() waiters whose index the new
        last_applied covers (wake-by-index, ISSUE 20 satellite): pop
        the covered prefix of the waiter heap instead of broadcasting
        to every writer parked on the node."""
        waiters = self._commit_waiters
        while waiters and waiters[0][0] <= self.last_applied:
            heapq.heappop(waiters)[2].done.set()

    def _run_apply(self) -> None:
        """Dedicated applier: keeps FSM application strictly ordered.
        When the commit index jumps N entries (group commit, batched
        replication), contiguous runs of FSM entries apply as ONE
        fsm.apply_batch window — one store-lock hold, one snapshot-memo
        displacement, one event-broker publish batch — and commit
        waiters wake once, by index (ISSUE 20)."""
        while not self._stop.is_set():
            with self._lock:
                while self.last_applied >= self.commit_index and \
                        not self._stop.is_set():
                    self._commit_cond.wait(0.5)
                if self._stop.is_set():
                    return
                start = self.last_applied + 1
                end = self.commit_index
                batch = [(i, self._entry_at(i)) for i in range(start, end + 1)]

            def _on_entry_error(idx: int, ex: BaseException) -> None:
                # per-entry error isolation inside a batched window: a
                # malformed entry must not drop its batch-mates
                self.logger(f"raft: fsm apply failed at {idx}: {ex!r}")

            i, n = 0, len(batch)
            while i < n:
                idx, e = batch[i]
                if e.type in _CONFIG_TYPES:
                    try:
                        with self._lock:
                            if e.type == "_config_remove":
                                self._apply_config_locked(e.payload)
                            else:
                                self._apply_config_add_locked(e.payload)
                    except Exception as ex:   # noqa: BLE001
                        # a meta-persist failure inside a config apply
                        # must not kill the applier: the config is
                        # adopted in memory and the LOG is the
                        # authority at restore — the meta peers field
                        # is a cache rebuilt from snapshot + log
                        metrics.incr("nomad.raft.persist_errors")
                        self.logger(f"raft: config apply persist "
                                    f"failed at {idx}: {ex!r}")
                    i += 1
                elif e.type == "_noop":
                    i += 1
                else:
                    # contiguous FSM run: config/noop entries break the
                    # window so raft-state and store-state mutations
                    # stay in strict log order relative to each other
                    run = []
                    while i < n and batch[i][1].type not in _CONFIG_TYPES \
                            and batch[i][1].type != "_noop":
                        run.append((batch[i][0], batch[i][1].type,
                                    batch[i][1].payload))
                        i += 1
                    try:
                        self.fsm.apply_batch(run, on_error=_on_entry_error)
                    except Exception as ex:   # noqa: BLE001
                        self.logger(f"raft: fsm apply batch failed at "
                                    f"{run[0][0]}..{run[-1][0]}: {ex!r}")
            with self._lock:
                self.last_applied = end
                self._wake_applied_locked()
                self._apply_cond.notify_all()
                if len(self.log) >= self.snapshot_threshold:
                    try:
                        # compaction must be atomic with log state;
                        # audited ISSUE 13 — nomadlint: disable=LOCK003
                        self._compact_locked()
                    except Exception as ex:   # noqa: BLE001
                        # a failed compaction must not kill the applier:
                        # the manifest still names the old consistent
                        # generation, memory is already compacted, and
                        # the next apply batch retries
                        metrics.incr("nomad.raft.compact_failed")
                        self.logger(
                            f"raft: compaction persist failed "
                            f"(retrying next batch): {ex!r}")

    def _compact_locked(self) -> None:
        """Snapshot the FSM and truncate the applied prefix of the log."""
        snap_index = self.last_applied
        if snap_index <= self.base_index:
            return
        if self._commit_in_flight:
            # a group-commit batch sits between its durable append and
            # its publish (ISSUE 20): the regenerated WAL would be
            # built from a self.log that lacks the in-flight frames,
            # silently un-persisting entries about to be acked. Skip;
            # the applier retries after the next batch.
            return
        data = self.fsm.snapshot_bytes()
        keep_from = snap_index - self.base_index
        self.base_term = self._term_at(snap_index)
        # fold config entries covered by the snapshot into the base config
        for e in self.log[:keep_from]:
            if e.type == "_config_add":
                pid, addr, voter = e.payload if len(e.payload) == 3 \
                    else (*e.payload, True)
                self._base_peers[pid] = addr
                if voter:
                    self._base_nonvoters.discard(pid)
                else:
                    self._base_nonvoters.add(pid)
            elif e.type == "_config_remove":
                self._base_peers.pop(e.payload, None)
                self._base_nonvoters.discard(e.payload)
        self.log = self.log[keep_from:]
        self.base_index = snap_index
        if self._durable is not None:
            # ONE generation commit (snapshot + truncated log behind an
            # atomic manifest replace) — the old persist-snapshot-then-
            # rewrite-log pair left a crash window in which an
            # index-less stale log shadowed the new snapshot (ISSUE 13)
            # raft persists before acking; the disk commit IS the state
            # transition, by design — nomadlint: disable=LOCK003
            with self._disk_lock:
                # same audit: _disk_lock is the durable-I/O serializer
                # (ISSUE 13/20) — nomadlint: disable=LOCK003
                self._durable.commit_generation(
                    self._snapshot_doc(data),
                    [(e.term, e.type, e.payload) for e in self.log],
                    self.base_index + 1)

    # ------------------------------------------------------- RPC handlers

    def _rpc_request_vote(self, term, candidate_id, last_idx, last_term):
        with self._lock:
            if self._stop.is_set():
                return {"term": self.current_term, "granted": False}
            if term > self.current_term:
                self._step_down_locked(term)
            granted = False
            if term == self.current_term and \
                    self.voted_for in (None, candidate_id):
                my_last = self._last_index()
                my_term = self._term_at(my_last)
                up_to_date = (last_term, last_idx) >= (my_term, my_last)
                if up_to_date:
                    granted = True
                    prev_vote = self.voted_for
                    self.voted_for = candidate_id
                    try:
                        # the vote must be durable BEFORE the grant
                        # leaves this server (fsync=always): a granted-
                        # then-forgotten vote is the double-vote hole
                        self._persist_meta()
                    except Exception as e:   # noqa: BLE001
                        # revert to the PRIOR value (a retransmitted
                        # grant's prev is the same candidate — setting
                        # None instead would forget the original
                        # persisted grant and free this term's vote)
                        self.voted_for = prev_vote
                        granted = False
                        metrics.incr("nomad.raft.persist_errors")
                        self.logger(f"raft: vote persist failed, grant "
                                    f"withheld: {e!r}")
                        return {"term": self.current_term,
                                "granted": False}
                    self._last_contact = self.clock.monotonic()
                    # the old leader is presumed dead: stop advertising it
                    # for forwarding until the new leader heartbeats us
                    self.leader_id = None
                    self.leader_addr = ""
            return {"term": self.current_term, "granted": granted}

    def _rpc_append_entries(self, term, leader_id, leader_addr,
                            prev_idx, prev_term, entries, leader_commit):
        with self._lock:
            if self._stop.is_set():
                # a shut-down node must not ack replication: live pooled
                # connections would otherwise keep it looking healthy
                return {"term": self.current_term, "success": False}
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.state != FOLLOWER:
                self._step_down_locked(term)
            self.leader_id = leader_id
            self.leader_addr = leader_addr
            self._last_contact = self.clock.monotonic()

            if prev_idx > self._last_index() or \
                    (prev_idx >= self.base_index and
                     self._term_at(prev_idx) != prev_term):
                return {"term": self.current_term, "success": False,
                        "last_index": min(self._last_index(), prev_idx - 1)}
            if prev_idx < self.base_index:
                # snapshot already covers part of this batch
                skip = self.base_index - prev_idx
                entries = entries[skip:]
                prev_idx = self.base_index
            # append, truncating conflicts; the common case is a pure
            # append which hits the cheap append-only disk path.
            # truncation REBINDS self.log (slice copy), so orig_log
            # stays the untouched pre-RPC list — the persist-failure
            # path below restores it wholesale, keeping memory == disk
            orig_log = self.log
            truncated = False
            appended: list[_Entry] = []
            for i, (eterm, etype, epayload) in enumerate(entries):
                idx = prev_idx + i + 1
                if idx <= self._last_index():
                    if self._term_at(idx) != eterm:
                        self.log = self.log[:idx - self.base_index - 1]
                        truncated = True
                    else:
                        continue
                e = _Entry(eterm, etype, epayload)
                self.log.append(e)
                appended.append(e)
            persist_ok = True
            try:
                if truncated:
                    # replication ack only after the truncated log is
                    # durable (raft safety) — nomadlint: disable=LOCK003
                    self._rewrite_log_on_disk()
                elif appended:
                    self._append_to_disk(appended)
            except Exception as e:   # noqa: BLE001
                persist_ok = False
                metrics.incr("nomad.raft.persist_errors")
                self.logger(f"raft: follower persist failed: {e!r}")
                if truncated:
                    # a failed conflict rewrite must not leave memory
                    # truncated while disk still holds the old tail: a
                    # leader RETRY would then match memory and ack
                    # entries that never reached disk. Restore the
                    # pre-RPC log; the retry re-runs the whole exchange
                    self.log = orig_log
                    truncated = False
                elif appended:
                    # pure-append failure: roll the tail back so memory
                    # and disk agree, and make the leader retry
                    del self.log[-len(appended):]
                appended = []
            if truncated or any(e.type in _CONFIG_TYPES for e in appended):
                # adopt appended config entries immediately (§4.1) and roll
                # back any truncated ones, in one recompute
                self._recompute_config_locked()
            if appended:
                # crash window between a durable follower persist and
                # the ack leaving this server (ISSUE 20 fuzzer site): a
                # raise here drops the response — the leader retries
                # the identical batch, which matches in place and acks,
                # so a durably-persisted-but-unacked follower batch is
                # never double-applied and never lost
                from .. import faults
                faults.fire("raft.follower.ack")
                faults.fire(f"raft.follower.ack.{self.node_id}")
            if not persist_ok:
                # `retry` distinguishes a LOCAL persist hiccup from a
                # log conflict: the logs match, so the leader must not
                # walk next_index backwards (that re-ships ever-larger
                # matching prefixes and eventually a pointless
                # InstallSnapshot) — it just retries the same batch
                return {"term": self.current_term, "success": False,
                        "retry": True,
                        "last_index": self._last_index()}
            if leader_commit > self.commit_index:
                self.commit_index = min(leader_commit, self._last_index())
                self._commit_cond.notify_all()
            return {"term": self.current_term, "success": True}

    def _rpc_install_snapshot(self, term, leader_id, leader_addr, snap):
        with self._lock:
            if term < self.current_term:
                return {"term": self.current_term}
            if term > self.current_term or self.state != FOLLOWER:
                self._step_down_locked(term)
            self.leader_id = leader_id
            self.leader_addr = leader_addr
            self._last_contact = self.clock.monotonic()
            if snap["index"] <= self.base_index:
                return {"term": self.current_term}
            if self._durable is not None:
                # one atomic generation commit (snapshot + empty log +
                # manifest): the pre-WAL code wrote snapshot and log as
                # two files and a crash in between re-based the stale
                # log under the new snapshot. Persist BEFORE mutating
                # memory: if this raises, the handler surfaces the
                # error with memory untouched, so the leader's RETRY
                # is not short-circuited by an already-advanced
                # base_index into never persisting (which would strand
                # the durable dir's append cursor behind memory and
                # fail every subsequent replication append)
                peers = dict(snap["peers"]) if snap.get("peers") \
                    else dict(self._base_peers)
                nonvoters = set(snap.get("nonvoters", ())) \
                    if snap.get("peers") else set(self._base_nonvoters)
                # an installed snapshot must be durable before the node
                # acks it (raft safety) — nomadlint: disable=LOCK003
                with self._disk_lock:
                    # same audit: _disk_lock is the durable-I/O
                    # serializer — nomadlint: disable=LOCK003
                    self._durable.commit_generation(
                        {"index": snap["index"], "term": snap["term"],
                         "data": snap["data"], "peers": peers,
                         "nonvoters": nonvoters},
                        [], snap["index"] + 1)
            self.fsm.restore_bytes(snap["data"])
            self.base_index = snap["index"]
            self.base_term = snap["term"]
            self.log = []
            if snap.get("peers"):
                self.peers = dict(snap["peers"])
                self._base_peers = dict(snap["peers"])
                self.nonvoters = set(snap.get("nonvoters", ()))
                self._base_nonvoters = set(snap.get("nonvoters", ()))
            self.commit_index = max(self.commit_index, snap["index"])
            self.last_applied = snap["index"]
            self._persist_meta()
            return {"term": self.current_term}
