"""Gossip membership: SWIM-style failure detection + state dissemination
over UDP (the Serf/memberlist tier, ref nomad/server.go:1388 setupSerf,
nomad/serf.go nodeJoin/nodeFailed, hashicorp/memberlist).

Design (one pool, region-tagged — NOT a translation of the reference's
two-pool LAN/WAN split): every server joins a single gossip pool carrying
tags {role, region, rpc_addr, id}. Same-region members feed Raft peer
management (the LAN pool's job); cross-region members feed the federation
routing table (the WAN pool's job). One SWIM loop does both.

Protocol per period (SWIM):
  * ping a random member, piggybacking pending membership updates;
  * no ack -> ask k random members to ping it for us (indirect probe);
  * still nothing -> broadcast SUSPECT; unrefuted suspicion times out
    to DEAD (failure detected);
  * a member hearing itself suspected refutes with a higher incarnation.
Joins do a full push-pull state sync with a seed, then spread via
piggybacked ALIVE updates.

Messages are HMAC-authenticated JSON datagrams under the cluster key —
unauthenticated packets are dropped before parsing.
"""
from __future__ import annotations

import hashlib
import hmac
import json
import random
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"
LEFT = "left"


@dataclass
class Member:
    name: str
    host: str
    port: int
    incarnation: int = 0
    status: str = ALIVE
    tags: dict = field(default_factory=dict)
    status_time: float = 0.0

    @property
    def addr(self) -> tuple:
        return (self.host, self.port)

    def to_wire(self) -> dict:
        return {"name": self.name, "host": self.host, "port": self.port,
                "inc": self.incarnation, "status": self.status,
                "tags": self.tags}

    @staticmethod
    def from_wire(d: dict) -> "Member":
        return Member(name=d["name"], host=d["host"], port=int(d["port"]),
                      incarnation=int(d.get("inc", 0)),
                      status=d.get("status", ALIVE),
                      tags=dict(d.get("tags", {})))


class Gossip:
    def __init__(self, name: str, bind: str = "127.0.0.1", port: int = 0,
                 tags: Optional[dict] = None, key: bytes = b"nomad-tpu-dev",
                 interval: float = 0.3, suspect_timeout: float = 2.0,
                 probe_timeout: float = 0.5, sync_interval: float = 2.0,
                 logger=None,
                 on_join: Optional[Callable] = None,
                 on_leave: Optional[Callable] = None,
                 on_fail: Optional[Callable] = None):
        self.name = name
        self.key = key
        self.interval = interval
        self.suspect_timeout = suspect_timeout
        self.probe_timeout = probe_timeout
        self.sync_interval = sync_interval
        self._last_sync = 0.0
        self.logger = logger or (lambda msg: None)
        self.on_join = on_join or (lambda m: None)
        self.on_leave = on_leave or (lambda m: None)
        self.on_fail = on_fail or (lambda m: None)

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((bind, port))
        self._sock.settimeout(0.2)
        self.host, self.port = self._sock.getsockname()[:2]

        self._lock = threading.RLock()
        self.members: dict[str, Member] = {}
        me = Member(name=name, host=self.host, port=self.port,
                    incarnation=1, tags=dict(tags or {}),
                    status_time=time.monotonic())
        self.members[name] = me
        # pending updates to piggyback: name -> (retransmits left, member)
        self._updates: dict[str, list] = {}
        self._acks: dict[int, threading.Event] = {}
        self._seq = 0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------- wire

    def _pack(self, msg: dict) -> bytes:
        raw = json.dumps(msg, separators=(",", ":")).encode()
        sig = hmac.new(self.key, raw, hashlib.sha256).digest()[:16]
        return sig + raw

    def _unpack(self, data: bytes) -> Optional[dict]:
        if len(data) < 16:
            return None
        sig, raw = data[:16], data[16:]
        want = hmac.new(self.key, raw, hashlib.sha256).digest()[:16]
        if not hmac.compare_digest(sig, want):
            return None
        try:
            return json.loads(raw.decode())
        except ValueError:
            return None

    def _send(self, addr: tuple, msg: dict) -> None:
        msg["updates"] = self._take_piggyback()
        try:
            self._sock.sendto(self._pack(msg), addr)
        except OSError:
            pass

    # ------------------------------------------------------- dissemination

    def _queue_update(self, member: Member) -> None:
        import math
        with self._lock:
            n = max(len(self.members), 2)
            retransmits = int(math.ceil(math.log2(n))) + 2
            self._updates[member.name] = [retransmits, member.to_wire()]

    def _take_piggyback(self, limit: int = 8) -> list:
        with self._lock:
            out = []
            for name in list(self._updates)[:limit]:
                entry = self._updates[name]
                out.append(entry[1])
                entry[0] -= 1
                if entry[0] <= 0:
                    del self._updates[name]
            return out

    def _apply_update(self, wire: dict) -> None:
        m = Member.from_wire(wire)
        if m.name == self.name:
            # refute rumors about ourselves (SWIM refutation)
            if m.status in (SUSPECT, DEAD) and \
                    m.incarnation >= self.members[self.name].incarnation:
                with self._lock:
                    me = self.members[self.name]
                    me.incarnation = m.incarnation + 1
                    me.status = ALIVE
                    self._queue_update(me)
            return
        with self._lock:
            cur = self.members.get(m.name)
            if cur is None:
                if m.status in (ALIVE, SUSPECT):
                    m.status_time = time.monotonic()
                    self.members[m.name] = m
                    self._queue_update(m)
                    if m.status != ALIVE:
                        # store the rumor but don't announce a join for a
                        # member first heard of as suspect — adopting a
                        # possibly-failing server as a voter stalls quorum
                        return
                    new_member = m
                else:
                    return
            else:
                # incarnation ordering: higher wins; same incarnation,
                # worse status wins (alive < suspect < dead)
                rank = {ALIVE: 0, SUSPECT: 1, DEAD: 2, LEFT: 2}
                if m.incarnation < cur.incarnation:
                    return
                if m.incarnation == cur.incarnation and \
                        rank[m.status] <= rank[cur.status]:
                    return
                was = cur.status
                cur.incarnation = m.incarnation
                cur.status = m.status
                cur.tags = m.tags or cur.tags
                cur.host, cur.port = m.host, m.port
                cur.status_time = time.monotonic()
                self._queue_update(cur)
                if m.status == ALIVE and was != ALIVE:
                    new_member = cur
                elif m.status == DEAD and was != DEAD:
                    threading.Thread(target=self.on_fail, args=(cur,),
                                     daemon=True).start()
                    return
                elif m.status == LEFT and was not in (DEAD, LEFT):
                    threading.Thread(target=self.on_leave, args=(cur,),
                                     daemon=True).start()
                    return
                else:
                    return
        threading.Thread(target=self.on_join, args=(new_member,),
                         daemon=True).start()

    # ------------------------------------------------------------ handlers

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                data, addr = self._sock.recvfrom(64 * 1024)
            except socket.timeout:
                continue
            except OSError:
                return
            msg = self._unpack(data)
            if msg is None:
                continue
            try:
                self._handle_msg(msg, addr)
            except Exception as e:      # noqa: BLE001 - a malformed (but
                # authenticated) message from a skewed peer must not kill
                # the receive thread and leave this node deaf
                self.logger(f"gossip: bad message from {addr}: {e!r}")

    def _handle_msg(self, msg: dict, addr: tuple) -> None:
        for upd in msg.get("updates", ()):
            self._apply_update(upd)
        t = msg.get("t")
        if t == "ping":
            self._send(addr, {"t": "ack", "seq": msg.get("seq")})
        elif t == "ping-req":
            # indirect probe on behalf of `from`
            target = tuple(msg.get("target", ()))
            seq = msg.get("seq")
            origin = addr

            def relay(target=target, seq=seq, origin=origin):
                ok = self._ping(target)
                if ok:
                    self._send(origin, {"t": "ack", "seq": seq})
            threading.Thread(target=relay, daemon=True).start()
        elif t == "ack":
            ev = self._acks.get(msg.get("seq"))
            if ev is not None:
                ev.set()
        elif t == "push-pull":
            for wire in msg.get("members", ()):
                self._apply_update(wire)
            with self._lock:
                wire_members = [m.to_wire() for m in
                                self.members.values()]
            self._send(addr, {"t": "push-pull-ack",
                              "seq": msg.get("seq"),
                              "members": wire_members})
        elif t == "push-pull-ack":
            for wire in msg.get("members", ()):
                self._apply_update(wire)
            ev = self._acks.get(msg.get("seq"))
            if ev is not None:
                ev.set()

    def _ping(self, addr: tuple, timeout: Optional[float] = None) -> bool:
        with self._lock:
            self._seq += 1
            seq = self._seq
        ev = threading.Event()
        self._acks[seq] = ev
        self._send(addr, {"t": "ping", "seq": seq})
        ok = ev.wait(timeout or self.probe_timeout)
        self._acks.pop(seq, None)
        return ok

    # --------------------------------------------------------- probe loop

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.probe_tick()

    def probe_tick(self) -> None:
        """One probe-loop pass, extracted from the daemon loop so tests
        can drive it directly inside a bounded wait_until poll instead
        of racing the background thread's scheduling on a loaded box
        (the PR-6 gossip / PR-13 deployment-watcher deflake pattern).
        An extra pass is idempotent by construction: probes re-confirm
        state, suspicion/reaping key on wall-clock timeouts."""
        # periodic anti-entropy push-pull with a random member of ANY
        # status (memberlist's full state sync): this is how a node
        # wrongly marked DEAD after a healed partition hears the
        # rumor about itself and refutes — probes alone never reach
        # it because DEAD members leave the probe set
        now = time.monotonic()
        if now - self._last_sync >= self.sync_interval:
            self._last_sync = now
            with self._lock:
                others = [m for m in self.members.values()
                          if m.name != self.name]
            if others:
                target = random.choice(others)
                with self._lock:
                    wire = [m.to_wire() for m in self.members.values()]
                self._send(target.addr, {"t": "push-pull", "seq": 0,
                                         "members": wire})
        with self._lock:
            candidates = [m for m in self.members.values()
                          if m.name != self.name and
                          m.status in (ALIVE, SUSPECT)]
        if not candidates:
            return
        target = random.choice(candidates)
        if self._ping(target.addr):
            self._mark_alive_probe(target)
            return
        # indirect probes via k helpers
        with self._lock:
            helpers = [m for m in candidates
                       if m.name != target.name and m.status == ALIVE]
        random.shuffle(helpers)
        with self._lock:
            self._seq += 1
            seq = self._seq
        ev = threading.Event()
        self._acks[seq] = ev
        for h in helpers[:2]:
            self._send(h.addr, {"t": "ping-req", "seq": seq,
                                "target": [target.host, target.port]})
        ok = ev.wait(self.probe_timeout * 2)
        self._acks.pop(seq, None)
        if ok:
            self._mark_alive_probe(target)
        else:
            self._suspect(target)
        self._reap_suspects()

    def _mark_alive_probe(self, target: Member) -> None:
        with self._lock:
            cur = self.members.get(target.name)
            if cur is not None and cur.status == SUSPECT:
                cur.status = ALIVE
                cur.status_time = time.monotonic()
                self._queue_update(cur)

    def _suspect(self, target: Member) -> None:
        with self._lock:
            cur = self.members.get(target.name)
            if cur is None or cur.status != ALIVE:
                return
            cur.status = SUSPECT
            cur.status_time = time.monotonic()
            self._queue_update(cur)
            self.logger(f"gossip: {self.name}: suspect {cur.name}")

    def _reap_suspects(self) -> None:
        now = time.monotonic()
        failed = []
        with self._lock:
            for m in self.members.values():
                if m.status == SUSPECT and \
                        now - m.status_time > self.suspect_timeout:
                    m.status = DEAD
                    m.status_time = now
                    self._queue_update(m)
                    failed.append(m)
                    self.logger(f"gossip: {self.name}: {m.name} failed")
        for m in failed:
            threading.Thread(target=self.on_fail, args=(m,),
                             daemon=True).start()

    # -------------------------------------------------------------- API

    def start(self) -> None:
        for fn, nm in ((self._recv_loop, "gossip-recv"),
                       (self._probe_loop, "gossip-probe")):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"{nm}-{self.name}")
            t.start()
            self._threads.append(t)

    def join(self, seeds: list[str], timeout: float = 3.0) -> int:
        """Push-pull state sync with seed "host:port" addrs (ref
        serf.Join). Returns the number of seeds reached."""
        reached = 0
        for seed in seeds:
            host, _, port = seed.rpartition(":")
            addr = (host or "127.0.0.1", int(port))
            with self._lock:
                self._seq += 1
                seq = self._seq
                wire_members = [m.to_wire() for m in self.members.values()]
            ev = threading.Event()
            self._acks[seq] = ev
            self._send(addr, {"t": "push-pull", "seq": seq,
                              "members": wire_members})
            if ev.wait(timeout):
                reached += 1
            self._acks.pop(seq, None)
        return reached

    def leave(self) -> None:
        """Graceful departure: broadcast LEFT before stopping."""
        with self._lock:
            me = self.members[self.name]
            me.incarnation += 1
            me.status = LEFT
            self._queue_update(me)
            targets = [m.addr for m in self.members.values()
                       if m.name != self.name and m.status == ALIVE]
        for addr in targets[:8]:
            self._send(addr, {"t": "ping", "seq": 0})   # carries the update
        time.sleep(0.05)
        self.shutdown()

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def alive_members(self) -> list[Member]:
        with self._lock:
            return [m for m in self.members.values() if m.status == ALIVE]

    def members_snapshot(self) -> list[dict]:
        with self._lock:
            return [m.to_wire() for m in self.members.values()]
