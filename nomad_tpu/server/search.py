"""Search: prefix and fuzzy matching across state objects (ref
nomad/search_endpoint.go Search.PrefixSearch / Search.FuzzySearch).

Contexts mirror the reference (structs/search.go Context values); results
are truncated at TRUNCATE_LIMIT per context with a truncation flag so the
CLI/UI can show "and more...".
"""
from __future__ import annotations

from typing import Optional

# ref nomad/search_endpoint.go truncateLimit
TRUNCATE_LIMIT = 20

CTX_ALL = "all"
CTX_JOBS = "jobs"
CTX_EVALS = "evals"
CTX_ALLOCS = "allocs"
CTX_NODES = "nodes"
CTX_DEPLOYMENTS = "deployment"
CTX_NAMESPACES = "namespaces"
CTX_SCALING_POLICIES = "scaling_policy"
CTX_PLUGINS = "plugins"
CTX_VOLUMES = "volumes"

# contexts scoped to a namespace (ACL-checked per namespace); nodes and
# plugins are cluster-scoped
NS_CONTEXTS = (CTX_JOBS, CTX_EVALS, CTX_ALLOCS, CTX_DEPLOYMENTS,
               CTX_SCALING_POLICIES, CTX_VOLUMES)


def _collect(state, context: str, ns: Optional[str]) -> list[tuple[str, str]]:
    """Yield (id, namespace) pairs for one context. ns=None means all."""
    if context == CTX_JOBS:
        return [(j.id, j.namespace) for j in state.iter_jobs(ns)]
    if context == CTX_EVALS:
        return [(e.id, e.namespace) for e in state.iter_evals()
                if ns is None or e.namespace == ns]
    if context == CTX_ALLOCS:
        return [(a.id, a.namespace) for a in state.iter_allocs()
                if ns is None or a.namespace == ns]
    if context == CTX_NODES:
        return [(n.id, "") for n in state.iter_nodes()]
    if context == CTX_DEPLOYMENTS:
        return [(d.id, d.namespace) for d in state.iter_deployments()
                if ns is None or d.namespace == ns]
    if context == CTX_NAMESPACES:
        return [(n["name"], "") for n in state.iter_namespaces()]
    if context == CTX_SCALING_POLICIES:
        return [(p.id, p.target_key()[0])
                for p in state.iter_scaling_policies(ns)]
    if context == CTX_PLUGINS:
        iter_plugins = getattr(state, "iter_csi_plugins", None)
        return [(p.id, "") for p in iter_plugins()] if iter_plugins else []
    if context == CTX_VOLUMES:
        iter_vols = getattr(state, "iter_csi_volumes", None)
        if iter_vols is None:
            return []
        return [(v.id, v.namespace) for v in iter_vols()
                if ns is None or v.namespace == ns]
    return []


def _fuzzy_score(text: str, pattern: str) -> Optional[int]:
    """Subsequence match; lower score = tighter match (ref fuzzy search's
    substring semantics — we accept substrings first, subsequences after)."""
    t, p = text.lower(), pattern.lower()
    pos = t.find(p)
    if pos >= 0:
        return pos  # substring: rank by how early it starts
    # subsequence fallback, scored by span length
    start = ti = 0
    for i, ch in enumerate(p):
        ti = t.find(ch, ti)
        if ti < 0:
            return None
        if i == 0:
            start = ti
        ti += 1
    return 100 + (ti - start)


def _ctx_allowed(ctx: str, acl) -> bool:
    """Cluster-scoped contexts mirror their direct endpoints' ACLs (ref
    search_endpoint.go sufficientSearchPerms): nodes need node:read,
    plugins need plugin:read; namespace contexts filter per object."""
    if acl is None:
        return True
    if ctx == CTX_NODES:
        return acl.allow_node_read()
    if ctx == CTX_PLUGINS:
        return acl.allow_plugin_read()
    return True


def prefix_search(state, prefix: str, context: str = CTX_ALL,
                  namespace: Optional[str] = "default",
                  acl=None) -> dict:
    """ref Search.PrefixSearch: exact-prefix id matching per context."""
    contexts = ([CTX_JOBS, CTX_EVALS, CTX_ALLOCS, CTX_NODES, CTX_DEPLOYMENTS,
                 CTX_NAMESPACES, CTX_SCALING_POLICIES, CTX_PLUGINS,
                 CTX_VOLUMES]
                if context in (CTX_ALL, "") else [context])
    ns = None if namespace in ("*", None) else namespace
    matches: dict[str, list[str]] = {}
    truncations: dict[str, bool] = {}
    for ctx in contexts:
        if not _ctx_allowed(ctx, acl):
            continue
        ids = []
        for oid, ons in _collect(state, ctx, ns):
            if not oid.startswith(prefix):
                continue
            if acl is not None and ctx in NS_CONTEXTS \
                    and not acl.allow_namespace(ons):
                continue
            if acl is not None and ctx == CTX_NAMESPACES \
                    and not acl.allow_namespace(oid):
                continue
            ids.append(oid)
        ids.sort()
        truncations[ctx] = len(ids) > TRUNCATE_LIMIT
        matches[ctx] = ids[:TRUNCATE_LIMIT]
    return {"Matches": matches, "Truncations": truncations,
            "Index": state.latest_index()}


def fuzzy_search(state, text: str, context: str = CTX_ALL,
                 namespace: Optional[str] = "default",
                 acl=None) -> dict:
    """ref Search.FuzzySearch: name-based fuzzy matching. Jobs additionally
    expose scoped matches (task groups, tasks) like the reference."""
    ns = None if namespace in ("*", None) else namespace
    matches: dict[str, list[dict]] = {}
    truncations: dict[str, bool] = {}

    def add(ctx, entries):
        entries.sort(key=lambda e: e[0])
        truncations[ctx] = len(entries) > TRUNCATE_LIMIT
        if entries:
            matches[ctx] = [e[1] for e in entries[:TRUNCATE_LIMIT]]

    contexts = ([CTX_JOBS, CTX_NODES, CTX_ALLOCS, CTX_NAMESPACES,
                 CTX_PLUGINS]
                if context in (CTX_ALL, "") else [context])
    for ctx in contexts:
        if not _ctx_allowed(ctx, acl):
            continue
        entries = []
        if ctx == CTX_JOBS:
            groups, tasks = [], []
            for j in state.iter_jobs(ns):
                if acl is not None and not acl.allow_namespace(j.namespace):
                    continue
                sc = _fuzzy_score(j.name or j.id, text)
                if sc is not None:
                    entries.append(
                        (sc, {"ID": j.id, "Scope": [j.namespace, j.id]}))
                for tg in j.task_groups:
                    sc = _fuzzy_score(tg.name, text)
                    if sc is not None:
                        groups.append((sc, {
                            "ID": tg.name,
                            "Scope": [j.namespace, j.id]}))
                    for t in tg.tasks:
                        sc = _fuzzy_score(t.name, text)
                        if sc is not None:
                            tasks.append((sc, {
                                "ID": t.name,
                                "Scope": [j.namespace, j.id, tg.name]}))
            add(CTX_JOBS, entries)
            add("groups", groups)
            add("tasks", tasks)
            continue
        if ctx == CTX_NODES:
            for n in state.iter_nodes():
                sc = _fuzzy_score(n.name, text)
                if sc is not None:
                    entries.append((sc, {"ID": n.name, "Scope": [n.id]}))
        elif ctx == CTX_ALLOCS:
            for a in state.iter_allocs():
                if ns is not None and a.namespace != ns:
                    continue
                if acl is not None and not acl.allow_namespace(a.namespace):
                    continue
                sc = _fuzzy_score(a.name, text)
                if sc is not None:
                    entries.append((sc, {"ID": a.name,
                                         "Scope": [a.namespace, a.id]}))
        elif ctx == CTX_NAMESPACES:
            for n in state.iter_namespaces():
                if acl is not None and not acl.allow_namespace(n["name"]):
                    continue
                sc = _fuzzy_score(n["name"], text)
                if sc is not None:
                    entries.append((sc, {"ID": n["name"], "Scope": []}))
        elif ctx == CTX_PLUGINS:
            for pid, _ in _collect(state, CTX_PLUGINS, None):
                sc = _fuzzy_score(pid, text)
                if sc is not None:
                    entries.append((sc, {"ID": pid, "Scope": []}))
        add(ctx, entries)
    return {"Matches": matches, "Truncations": truncations,
            "Index": state.latest_index()}
